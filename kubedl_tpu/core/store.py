"""In-process object store with watch semantics — the etcd/api-server analogue.

The reference rides controller-runtime's informer cache + client (SURVEY.md
L0). Here a single thread-safe store holds every object, hands out deep
copies (so controllers can't mutate shared state accidentally — the same
reason the reference reads via a cache and writes via the client), and fans
out Added/Modified/Deleted events to registered watchers. Controllers never
poll: watch events feed their workqueues
(:mod:`kubedl_tpu.core.workqueue`), exactly like informer event handlers.

Durability is opt-in: ``ObjectStore(wal_dir=...)`` puts a write-ahead log
(:mod:`kubedl_tpu.core.wal`) in front of every mutation and rehydrates the
pre-crash world from snapshot+log in the constructor — before any
controller registers. The default in-memory path is untouched (WAL-off
writes pay one ``None`` test).
"""

from __future__ import annotations

import copy
import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from kubedl_tpu import chaos
from kubedl_tpu.core.objects import BaseObject, ensure_uid_floor, match_labels

log = logging.getLogger("kubedl_tpu.core.store")

WatchCallback = Callable[[str, BaseObject, Optional[BaseObject]], None]
# signature: (event_type, new_obj, old_obj) with event_type in
# {"ADDED", "MODIFIED", "DELETED"}


class Conflict(Exception):
    """Optimistic-concurrency failure (stale resource_version on update)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


@dataclass
class _Watcher:
    kinds: Optional[Tuple[str, ...]]
    callback: WatchCallback


class ObjectStore:
    def __init__(
        self,
        wal_dir: Optional[str] = None,
        wal_fsync: str = "always",
        wal_snapshot_every: int = 1000,
        wal_fsync_floor: float = 0.0,
    ) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[Tuple[str, str], BaseObject]] = {}
        self._rv = 0
        self._watchers: List[_Watcher] = []
        #: revision of the most recent delete — a watcher replaying from an
        #: older revision can never see that DELETED event (gap detection)
        self._last_delete_rev = 0
        #: watchers registered with a since_revision older than replayable
        #: history (exported as a gauge by the operator)
        self.watch_gaps = 0
        self._wal = None
        self.rehydrated = False
        self.replayed_records = 0
        self.recovery_seconds = 0.0
        if wal_dir:
            self._open_wal(
                wal_dir, wal_fsync, wal_snapshot_every, wal_fsync_floor
            )

    # ---- durability (WAL) ------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rv

    def _open_wal(
        self,
        wal_dir: str,
        fsync: str,
        snapshot_every: int,
        fsync_floor: float = 0.0,
    ) -> None:
        """Replay snapshot+log into memory, then arm the WAL on the write
        path. Runs in the constructor so every object is back before any
        watcher or controller exists."""
        from kubedl_tpu.api.codec import decode_object
        from kubedl_tpu.core.wal import WriteAheadLog

        t0 = time.perf_counter()
        wal = WriteAheadLog(
            wal_dir,
            fsync=fsync,
            snapshot_every=snapshot_every,
            fsync_floor=fsync_floor,
        )
        snap_rev, snap_objs, records = wal.recover()
        max_uid = 0
        with self._lock:
            self._rv = snap_rev
            for data in snap_objs:
                obj = decode_object(data)
                self._objects.setdefault(obj.kind, {})[obj.key] = obj
            for rec in records:
                rev = int(rec["rev"])
                if rec["op"] == "PUT":
                    obj = decode_object(rec["obj"])
                    self._objects.setdefault(obj.kind, {})[obj.key] = obj
                else:
                    self._objects.get(rec["kind"], {}).pop(
                        (rec["namespace"], rec["name"]), None
                    )
                    self._last_delete_rev = rev
                self._rv = max(self._rv, rev)
            self.replayed_records = len(records)
            self.rehydrated = bool(snap_objs or records)
            # a restarted process mints uids from 1 again — colliding with
            # replayed objects would defeat adoption-by-(name, uid)
            for bucket in self._objects.values():
                for obj in bucket.values():
                    m = re.match(r"uid-(\d+)$", obj.metadata.uid)
                    if m:
                        max_uid = max(max_uid, int(m.group(1)))
            self._wal = wal
        ensure_uid_floor(max_uid)
        self.recovery_seconds = time.perf_counter() - t0
        if self.rehydrated:
            live = sum(len(b) for b in self._objects.values())
            log.info(
                "rehydrated %d objects (snapshot rv=%d + %d WAL records, "
                "%d torn bytes dropped) in %.1fms",
                live, snap_rev, len(records), wal.torn_tail_bytes,
                self.recovery_seconds * 1e3,
            )

    def _wal_put(self, rev: int, obj: BaseObject) -> None:
        """Append a PUT record; raises (nothing applied) on failure."""
        if self._wal is None:
            return
        from kubedl_tpu.api.codec import encode

        self._wal.append(
            rev, "PUT", obj.kind, obj.metadata.namespace, obj.metadata.name,
            encode(obj),
        )

    def _wal_delete(self, rev: int, kind: str, namespace: str, name: str) -> None:
        if self._wal is None:
            return
        self._wal.append(rev, "DELETE", kind, namespace, name)

    def _maybe_compact(self) -> None:
        """Snapshot + truncate once enough records accumulated. Caller
        holds the lock; the dump is O(live objects)."""
        if self._wal is None or not self._wal.should_snapshot():
            return
        from kubedl_tpu.api.codec import encode

        objs = [
            encode(o) for bucket in self._objects.values() for o in bucket.values()
        ]
        self._wal.snapshot(self._rv, objs)

    @property
    def wal_appends(self) -> int:
        return self._wal.appends if self._wal is not None else 0

    @property
    def wal_fsyncs(self) -> int:
        return self._wal.fsyncs if self._wal is not None else 0

    def compact(self) -> None:
        """Force a snapshot+truncate now (test/ops hook)."""
        with self._lock:
            if self._wal is None:
                return
            from kubedl_tpu.api.codec import encode

            objs = [
                encode(o)
                for bucket in self._objects.values()
                for o in bucket.values()
            ]
            self._wal.snapshot(self._rv, objs)

    def close(self) -> None:
        """Detach the WAL (flush + stop accepting writes). In-memory
        operation continues — late writers from a dying incarnation mutate
        only their abandoned memory image, never the files the next
        incarnation replays."""
        with self._lock:
            if self._wal is not None:
                self._wal.close()

    # ---- CRUD ------------------------------------------------------------

    def create(self, obj: BaseObject) -> BaseObject:
        chaos.check("store.create")
        with self._lock:
            bucket = self._objects.setdefault(obj.kind, {})
            if obj.key in bucket:
                raise AlreadyExists(f"{obj.kind} {obj.key} already exists")
            rev = self._rv + 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = rev
            self._wal_put(rev, stored)  # durability first; raises unapplied
            self._rv = rev
            obj.metadata.resource_version = rev
            bucket[obj.key] = stored
            self._maybe_compact()
            snapshot = copy.deepcopy(stored)
        self._notify("ADDED", snapshot, None)
        return snapshot

    def get(self, kind: str, name: str, namespace: str = "default") -> BaseObject:
        with self._lock:
            bucket = self._objects.get(kind, {})
            obj = bucket.get((namespace, name))
            if obj is None or obj.metadata.deletion_timestamp is not None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def try_get(
        self, kind: str, name: str, namespace: str = "default"
    ) -> Optional[BaseObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: BaseObject) -> BaseObject:
        """Optimistic update: fails with Conflict on stale resource_version
        (the reference requeues on conflict, job.go:298-306)."""
        chaos.check("store.update")
        with self._lock:
            bucket = self._objects.get(obj.kind, {})
            cur = bucket.get(obj.key)
            if cur is None:
                raise NotFound(f"{obj.kind} {obj.key} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {obj.key}: stale rv "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            old = copy.deepcopy(cur)
            rev = self._rv + 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = rev
            self._wal_put(rev, stored)  # durability first; raises unapplied
            self._rv = rev
            obj.metadata.resource_version = rev
            bucket[obj.key] = stored
            self._maybe_compact()
            snapshot = copy.deepcopy(stored)
        self._notify("MODIFIED", snapshot, old)
        return snapshot

    def update_with_retry(
        self, kind: str, name: str, namespace: str, mutate: Callable[[BaseObject], None],
        attempts: int = 5,
    ) -> BaseObject:
        """Read-modify-write loop, the client-go `retry.RetryOnConflict` idiom.

        Retries ride the shared :class:`~kubedl_tpu.chaos.RetryPolicy`
        (in-process conflicts are cheap, so the backoff floor is tiny —
        jitter only matters when many workers contend on one object)."""
        policy = chaos.RetryPolicy(
            max_attempts=attempts, base_delay=0.001, max_delay=0.02
        )

        def attempt() -> BaseObject:
            obj = self.get(kind, name, namespace)
            mutate(obj)
            return self.update(obj)

        return policy.call(attempt, retry_on=(Conflict,))

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        chaos.check("store.delete")
        with self._lock:
            bucket = self._objects.get(kind, {})
            obj = bucket.get((namespace, name))
            if obj is not None:
                rev = self._rv + 1
                self._wal_delete(rev, kind, namespace, name)  # raises unapplied
                self._rv = rev
                self._last_delete_rev = rev
                bucket.pop((namespace, name))
                self._maybe_compact()
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        self._notify("DELETED", copy.deepcopy(obj), copy.deepcopy(obj))

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    def list(
        self,
        kind: str,
        namespace: Optional[str] = "default",
        selector: Optional[Dict[str, str]] = None,
    ) -> List[BaseObject]:
        with self._lock:
            bucket = self._objects.get(kind, {})
            out = []
            for (ns, _), obj in bucket.items():
                if namespace is not None and ns != namespace:
                    continue
                if selector and not match_labels(obj.metadata.labels, selector):
                    continue
                out.append(copy.deepcopy(obj))
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def kinds(self) -> Iterable[str]:
        with self._lock:
            return list(self._objects)

    # ---- watches ---------------------------------------------------------

    def watch(
        self,
        callback: WatchCallback,
        kinds: Optional[Iterable[str]] = None,
        since_revision: Optional[int] = None,
    ) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe function. Watchers run
        inline on the mutating thread (informer-style handlers must be quick
        — typically just a workqueue enqueue).

        ``since_revision`` replays history missed before registration:
        synthesized ADDED events (revision rides each object's
        ``metadata.resource_version``) are delivered for every live matching
        object newer than that revision — ``since_revision=0`` is a full
        relist. Deletions are not reconstructible from live state; if any
        happened after ``since_revision`` the watcher has a real gap, which
        is logged and counted (``watch_gaps``) instead of passing silently.
        Replay runs inline before this call returns; a concurrent mutation
        may deliver its live event before the replayed ADDED (same
        relist-vs-watch race informers have — handlers must be level-driven).
        """
        w = _Watcher(tuple(kinds) if kinds else None, callback)
        replay: List[BaseObject] = []
        with self._lock:
            self._watchers.append(w)
            if since_revision is not None and since_revision < self._rv:
                if since_revision < self._last_delete_rev:
                    self.watch_gaps += 1
                    log.warning(
                        "watcher registered at revision %d but deletes up to "
                        "revision %d are gone — DELETED events in that gap "
                        "cannot be replayed",
                        since_revision, self._last_delete_rev,
                    )
                for kind, bucket in self._objects.items():
                    if w.kinds is not None and kind not in w.kinds:
                        continue
                    for obj in bucket.values():
                        if obj.metadata.resource_version > since_revision:
                            replay.append(copy.deepcopy(obj))
        for obj in sorted(replay, key=lambda o: o.metadata.resource_version):
            callback("ADDED", obj, None)

        def cancel() -> None:
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)

        return cancel

    def _notify(
        self, event: str, obj: BaseObject, old: Optional[BaseObject]
    ) -> None:
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            if w.kinds is None or obj.kind in w.kinds:
                w.callback(event, obj, old)

    # ---- garbage collection ---------------------------------------------

    def collect_orphans(self) -> int:
        """Delete objects whose controller owner is gone (the kube GC
        analogue; the reference leans on ownerReferences for cascade)."""
        doomed: List[BaseObject] = []
        with self._lock:
            uids = {
                o.metadata.uid
                for bucket in self._objects.values()
                for o in bucket.values()
            }
            for bucket in self._objects.values():
                for obj in bucket.values():
                    ref = obj.metadata.controller_ref()
                    if ref is not None and ref.uid not in uids:
                        doomed.append(obj)
        for obj in doomed:
            self.try_delete(obj.kind, obj.metadata.name, obj.metadata.namespace)
        return len(doomed)

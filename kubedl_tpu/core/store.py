"""In-process object store with watch semantics — the etcd/api-server analogue.

The reference rides controller-runtime's informer cache + client (SURVEY.md
L0). Here a single thread-safe store holds every object, hands out deep
copies (so controllers can't mutate shared state accidentally — the same
reason the reference reads via a cache and writes via the client), and fans
out Added/Modified/Deleted events to registered watchers. Controllers never
poll: watch events feed their workqueues
(:mod:`kubedl_tpu.core.workqueue`), exactly like informer event handlers.

Durability is opt-in: ``ObjectStore(wal_dir=...)`` puts a write-ahead log
(:mod:`kubedl_tpu.core.wal`) in front of every mutation and rehydrates the
pre-crash world from snapshot+log in the constructor — before any
controller registers. The default in-memory path is untouched (WAL-off
writes pay one ``None`` test).

Concurrency model (the PR 19 scaling contract):

- Stored objects are **replace-on-write**: a mutation deepcopies into a
  fresh object and swaps the bucket slot; the displaced object is never
  touched again. That invariant is what makes the read fast paths legal.
- :meth:`peek` is a lock-free point read (CPython dict reads are atomic
  under the GIL) returning the stored object itself — callers must not
  mutate it; :meth:`get` deepcopies it outside any lock.
- Scans (:meth:`list`, :meth:`collect_orphans`, the sharded facade's
  counters) run over :meth:`snapshot_view` — an RCU-style copy-on-write
  per-kind tuple rebuilt lazily when that kind's generation counter moved,
  so read fan-out never holds the write lock while copying.
- Under ``wal_fsync="group"`` a write stages its WAL record and applies to
  memory inside the lock, then blocks in ``wait_durable`` OUTSIDE the lock
  (group commit: N writers share one fsync) before watchers are notified
  or the call returns. Readers may therefore observe a record the batched
  fsync hasn't covered yet; writers never acknowledge one.
"""

from __future__ import annotations

import copy
import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from kubedl_tpu import chaos
from kubedl_tpu.core.objects import BaseObject, ensure_uid_floor, match_labels

log = logging.getLogger("kubedl_tpu.core.store")

WatchCallback = Callable[[str, BaseObject, Optional[BaseObject]], None]
# signature: (event_type, new_obj, old_obj) with event_type in
# {"ADDED", "MODIFIED", "DELETED"}


class Conflict(Exception):
    """Optimistic-concurrency failure (stale resource_version on update)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


@dataclass
class _Watcher:
    kinds: Optional[Tuple[str, ...]]
    callback: WatchCallback


class ObjectStore:
    def __init__(
        self,
        wal_dir: Optional[str] = None,
        wal_fsync: str = "always",
        wal_snapshot_every: int = 1000,
        wal_fsync_floor: float = 0.0,
        wal_group_window: Optional[float] = None,
    ) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[Tuple[str, str], BaseObject]] = {}
        #: per-kind write generation + lazily rebuilt snapshot views
        #: (kind -> (generation, tuple of stored objects)); see the module
        #: docstring's concurrency model
        self._gen: Dict[str, int] = {}
        self._views: Dict[str, Tuple[int, Tuple[BaseObject, ...]]] = {}
        self._rv = 0
        self._watchers: List[_Watcher] = []
        #: revision of the most recent delete — a watcher replaying from an
        #: older revision can never see that DELETED event (gap detection)
        self._last_delete_rev = 0
        #: watchers registered with a since_revision older than replayable
        #: history (exported as a gauge by the operator)
        self.watch_gaps = 0
        self._wal = None
        self.rehydrated = False
        self.replayed_records = 0
        self.recovery_seconds = 0.0
        if wal_dir:
            self._open_wal(
                wal_dir, wal_fsync, wal_snapshot_every, wal_fsync_floor,
                wal_group_window,
            )

    # ---- durability (WAL) ------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rv

    def _open_wal(
        self,
        wal_dir: str,
        fsync: str,
        snapshot_every: int,
        fsync_floor: float = 0.0,
        group_window: Optional[float] = None,
    ) -> None:
        """Replay snapshot+log into memory, then arm the WAL on the write
        path. Runs in the constructor so every object is back before any
        watcher or controller exists."""
        from kubedl_tpu.api.codec import decode_object
        from kubedl_tpu.core.wal import DEFAULT_GROUP_WINDOW, WriteAheadLog

        t0 = time.perf_counter()
        wal = WriteAheadLog(
            wal_dir,
            fsync=fsync,
            snapshot_every=snapshot_every,
            fsync_floor=fsync_floor,
            group_window=(
                DEFAULT_GROUP_WINDOW if group_window is None else group_window
            ),
        )
        snap_rev, snap_objs, records = wal.recover()
        max_uid = 0
        with self._lock:
            self._rv = snap_rev
            for data in snap_objs:
                obj = decode_object(data)
                self._objects.setdefault(obj.kind, {})[obj.key] = obj
            for rec in records:
                rev = int(rec["rev"])
                if rec["op"] == "PUT":
                    obj = decode_object(rec["obj"])
                    self._objects.setdefault(obj.kind, {})[obj.key] = obj
                else:
                    self._objects.get(rec["kind"], {}).pop(
                        (rec["namespace"], rec["name"]), None
                    )
                    self._last_delete_rev = rev
                self._rv = max(self._rv, rev)
            self.replayed_records = len(records)
            self.rehydrated = bool(snap_objs or records)
            # a restarted process mints uids from 1 again — colliding with
            # replayed objects would defeat adoption-by-(name, uid)
            for bucket in self._objects.values():
                for obj in bucket.values():
                    m = re.match(r"uid-(\d+)$", obj.metadata.uid)
                    if m:
                        max_uid = max(max_uid, int(m.group(1)))
            self._wal = wal
        ensure_uid_floor(max_uid)
        self.recovery_seconds = time.perf_counter() - t0
        if self.rehydrated:
            live = sum(len(b) for b in self._objects.values())
            log.info(
                "rehydrated %d objects (snapshot rv=%d + %d WAL records, "
                "%d torn bytes dropped) in %.1fms",
                live, snap_rev, len(records), wal.torn_tail_bytes,
                self.recovery_seconds * 1e3,
            )

    def _wal_put(self, rev: int, obj: BaseObject) -> Optional[int]:
        """Append a PUT record; raises (nothing applied) on failure. Under
        group commit returns a staging ticket the caller must pass to
        :meth:`_wait_durable` AFTER releasing the store lock."""
        if self._wal is None:
            return None
        from kubedl_tpu.api.codec import encode

        return self._wal.append(
            rev, "PUT", obj.kind, obj.metadata.namespace, obj.metadata.name,
            encode(obj),
        )

    def _wal_delete(
        self, rev: int, kind: str, namespace: str, name: str
    ) -> Optional[int]:
        if self._wal is None:
            return None
        return self._wal.append(rev, "DELETE", kind, namespace, name)

    def _wait_durable(self, ticket: Optional[int]) -> None:
        """Fsync-before-ack barrier for group commit: block (outside the
        store lock) until the batched fsync covers ``ticket``. No-op for
        every other policy. Tickets are monotonic per WAL, so waiting on a
        batch's LAST ticket covers the whole batch."""
        if ticket is not None and self._wal is not None:
            self._wal.wait_durable(ticket)

    def _maybe_compact(self) -> None:
        """Snapshot + truncate once enough records accumulated. Caller
        holds the lock; the dump is O(live objects)."""
        if self._wal is None or not self._wal.should_snapshot():
            return
        from kubedl_tpu.api.codec import encode

        objs = [
            encode(o) for bucket in self._objects.values() for o in bucket.values()
        ]
        self._wal.snapshot(self._rv, objs)

    @property
    def wal_appends(self) -> int:
        return self._wal.appends if self._wal is not None else 0

    @property
    def wal_fsyncs(self) -> int:
        return self._wal.fsyncs if self._wal is not None else 0

    @property
    def wal_batches(self) -> int:
        return self._wal.batches if self._wal is not None else 0

    @property
    def wal_batch_records(self) -> int:
        return self._wal.batch_records if self._wal is not None else 0

    def set_wal_batch_observer(self, cb: Callable[[int], None]) -> None:
        """Install the per-batch size callback (feeds the
        ``kubedl_tpu_wal_batch_size`` histogram); called from the
        committer thread with the number of records each fsync covered."""
        if self._wal is not None:
            self._wal.on_batch = cb

    def compact(self) -> None:
        """Force a snapshot+truncate now (test/ops hook)."""
        with self._lock:
            if self._wal is None:
                return
            from kubedl_tpu.api.codec import encode

            objs = [
                encode(o)
                for bucket in self._objects.values()
                for o in bucket.values()
            ]
            self._wal.snapshot(self._rv, objs)

    def close(self) -> None:
        """Detach the WAL (flush + stop accepting writes). In-memory
        operation continues — late writers from a dying incarnation mutate
        only their abandoned memory image, never the files the next
        incarnation replays."""
        with self._lock:
            if self._wal is not None:
                self._wal.close()

    # ---- CRUD ------------------------------------------------------------

    def _bump(self, kind: str) -> None:
        """Advance ``kind``'s write generation (caller holds the lock);
        snapshot views for that kind rebuild lazily on next read."""
        self._gen[kind] = self._gen.get(kind, 0) + 1

    def create(self, obj: BaseObject) -> BaseObject:
        chaos.check("store.create")
        with self._lock:
            bucket = self._objects.setdefault(obj.kind, {})
            if obj.key in bucket:
                raise AlreadyExists(f"{obj.kind} {obj.key} already exists")
            rev = self._rv + 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = rev
            ticket = self._wal_put(rev, stored)  # raises unapplied
            self._rv = rev
            obj.metadata.resource_version = rev
            bucket[obj.key] = stored
            self._bump(obj.kind)
            self._maybe_compact()
        self._wait_durable(ticket)  # fsync-before-ack, outside the lock
        snapshot = copy.deepcopy(stored)  # stored is replace-on-write: safe
        self._notify("ADDED", snapshot, None)
        return snapshot

    def create_many(self, objs: List[BaseObject]) -> List[BaseObject]:
        """Create a batch under ONE lock hold and ONE durability wait —
        under group commit N sequential :meth:`create` calls would each pay
        a full commit window; a batch stages every record and waits once on
        the last (monotonic) ticket. All-or-nothing on name collisions: the
        whole batch is pre-checked and :class:`AlreadyExists` raises before
        anything is staged or applied, so callers can fall back to the
        per-object path. The chaos ``store.create`` site fires once per
        batch (a batch is one API call). Watch events still fan out one
        ADDED per object, in batch order, after the batch is durable."""
        if not objs:
            return []
        chaos.check("store.create")
        ticket = None
        stored_objs: List[BaseObject] = []
        with self._lock:
            for obj in objs:
                bucket = self._objects.setdefault(obj.kind, {})
                if obj.key in bucket:
                    raise AlreadyExists(f"{obj.kind} {obj.key} already exists")
            for obj in objs:
                rev = self._rv + 1
                stored = copy.deepcopy(obj)
                stored.metadata.resource_version = rev
                ticket = self._wal_put(rev, stored) or ticket
                self._rv = rev
                obj.metadata.resource_version = rev
                self._objects[obj.kind][obj.key] = stored
                self._bump(obj.kind)
                stored_objs.append(stored)
            self._maybe_compact()
        self._wait_durable(ticket)
        out = []
        for stored in stored_objs:
            snapshot = copy.deepcopy(stored)
            self._notify("ADDED", snapshot, None)
            out.append(snapshot)
        return out

    def peek(
        self, kind: str, name: str, namespace: str = "default"
    ) -> Optional[BaseObject]:
        """Lock-free point read returning the STORED object (or ``None``
        if absent/terminating) — the internal fast path behind existence
        probes and :meth:`get`. Legal because stored objects are
        replace-on-write (module docstring) and CPython dict reads are
        GIL-atomic. Callers MUST NOT mutate the result; anything handed
        outside the store must be deepcopied first."""
        bucket = self._objects.get(kind)
        if bucket is None:
            return None
        obj = bucket.get((namespace, name))
        if obj is None or obj.metadata.deletion_timestamp is not None:
            return None
        return obj

    def get(self, kind: str, name: str, namespace: str = "default") -> BaseObject:
        obj = self.peek(kind, name, namespace)
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return copy.deepcopy(obj)  # outside any lock: see peek()

    def try_get(
        self, kind: str, name: str, namespace: str = "default"
    ) -> Optional[BaseObject]:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: BaseObject) -> BaseObject:
        """Optimistic update: fails with Conflict on stale resource_version
        (the reference requeues on conflict, job.go:298-306)."""
        chaos.check("store.update")
        with self._lock:
            bucket = self._objects.get(obj.kind, {})
            cur = bucket.get(obj.key)
            if cur is None:
                raise NotFound(f"{obj.kind} {obj.key} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {obj.key}: stale rv "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            rev = self._rv + 1
            stored = copy.deepcopy(obj)
            stored.metadata.resource_version = rev
            ticket = self._wal_put(rev, stored)  # raises unapplied
            self._rv = rev
            obj.metadata.resource_version = rev
            bucket[obj.key] = stored
            self._bump(obj.kind)
            self._maybe_compact()
        self._wait_durable(ticket)  # fsync-before-ack, outside the lock
        # cur was displaced from the bucket and is never mutated again, so
        # both copies are safe outside the lock
        old = copy.deepcopy(cur)
        snapshot = copy.deepcopy(stored)
        self._notify("MODIFIED", snapshot, old)
        return snapshot

    def update_with_retry(
        self, kind: str, name: str, namespace: str, mutate: Callable[[BaseObject], None],
        attempts: int = 5,
    ) -> BaseObject:
        """Read-modify-write loop, the client-go `retry.RetryOnConflict` idiom.

        Retries ride the shared :class:`~kubedl_tpu.chaos.RetryPolicy`
        (in-process conflicts are cheap, so the backoff floor is tiny —
        jitter only matters when many workers contend on one object)."""
        policy = chaos.RetryPolicy(
            max_attempts=attempts, base_delay=0.001, max_delay=0.02
        )

        def attempt() -> BaseObject:
            obj = self.get(kind, name, namespace)
            mutate(obj)
            return self.update(obj)

        return policy.call(attempt, retry_on=(Conflict,))

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        chaos.check("store.delete")
        ticket = None
        with self._lock:
            bucket = self._objects.get(kind, {})
            obj = bucket.get((namespace, name))
            if obj is not None:
                rev = self._rv + 1
                ticket = self._wal_delete(rev, kind, namespace, name)
                self._rv = rev
                self._last_delete_rev = rev
                bucket.pop((namespace, name))
                self._bump(kind)
                self._maybe_compact()
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        self._wait_durable(ticket)  # fsync-before-ack, outside the lock
        self._notify("DELETED", copy.deepcopy(obj), copy.deepcopy(obj))

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    def delete_many(self, keys: List[Tuple[str, str, str]]) -> int:
        """Delete a batch of ``(kind, name, namespace)`` keys under ONE
        lock hold and ONE durability wait (see :meth:`create_many` for
        why). Missing keys are skipped — try-delete semantics — and the
        count actually deleted is returned. The chaos ``store.delete``
        site fires once per batch; DELETED events fan out per object
        after the batch is durable."""
        if not keys:
            return 0
        chaos.check("store.delete")
        ticket = None
        doomed: List[BaseObject] = []
        with self._lock:
            for kind, name, namespace in keys:
                bucket = self._objects.get(kind, {})
                obj = bucket.get((namespace, name))
                if obj is None:
                    continue
                rev = self._rv + 1
                ticket = self._wal_delete(rev, kind, namespace, name) or ticket
                self._rv = rev
                self._last_delete_rev = rev
                bucket.pop((namespace, name))
                self._bump(kind)
                doomed.append(obj)
            self._maybe_compact()
        self._wait_durable(ticket)
        for obj in doomed:
            self._notify("DELETED", copy.deepcopy(obj), copy.deepcopy(obj))
        return len(doomed)

    def snapshot_view(self, kind: str) -> Tuple[BaseObject, ...]:
        """RCU-style scan view: an immutable tuple of ``kind``'s stored
        objects, consistent as of some point at or after the last write
        that completed before this call. Rebuilt (copy-on-write) only when
        the kind's generation moved, so steady-state readers touch no lock
        at all and never copy objects — deepcopy what leaves the store.
        The contained objects follow :meth:`peek` rules: do not mutate."""
        gen = self._gen.get(kind, 0)
        cached = self._views.get(kind)
        if cached is not None and cached[0] == gen:
            return cached[1]
        with self._lock:
            gen = self._gen.get(kind, 0)
            view = tuple(self._objects.get(kind, {}).values())
        self._views[kind] = (gen, view)
        return view

    def list(
        self,
        kind: str,
        namespace: Optional[str] = "default",
        selector: Optional[Dict[str, str]] = None,
    ) -> List[BaseObject]:
        out = []
        for obj in self.snapshot_view(kind):
            if namespace is not None and obj.metadata.namespace != namespace:
                continue
            if selector and not match_labels(obj.metadata.labels, selector):
                continue
            out.append(copy.deepcopy(obj))  # copied OUTSIDE the lock
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def kinds(self) -> Iterable[str]:
        with self._lock:
            return list(self._objects)

    # ---- watches ---------------------------------------------------------

    def watch(
        self,
        callback: WatchCallback,
        kinds: Optional[Iterable[str]] = None,
        since_revision: Optional[int] = None,
    ) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe function. Watchers run
        inline on the mutating thread (informer-style handlers must be quick
        — typically just a workqueue enqueue).

        ``since_revision`` replays history missed before registration:
        synthesized ADDED events (revision rides each object's
        ``metadata.resource_version``) are delivered for every live matching
        object newer than that revision — ``since_revision=0`` is a full
        relist. Deletions are not reconstructible from live state; if any
        happened after ``since_revision`` the watcher has a real gap, which
        is logged and counted (``watch_gaps``) instead of passing silently.
        Replay runs inline before this call returns; a concurrent mutation
        may deliver its live event before the replayed ADDED (same
        relist-vs-watch race informers have — handlers must be level-driven).
        """
        w = _Watcher(tuple(kinds) if kinds else None, callback)
        replay: List[BaseObject] = []
        with self._lock:
            self._watchers.append(w)
            if since_revision is not None and since_revision < self._rv:
                if since_revision < self._last_delete_rev:
                    self.watch_gaps += 1
                    log.warning(
                        "watcher registered at revision %d but deletes up to "
                        "revision %d are gone — DELETED events in that gap "
                        "cannot be replayed",
                        since_revision, self._last_delete_rev,
                    )
                for kind, bucket in self._objects.items():
                    if w.kinds is not None and kind not in w.kinds:
                        continue
                    for obj in bucket.values():
                        if obj.metadata.resource_version > since_revision:
                            replay.append(copy.deepcopy(obj))
        for obj in sorted(replay, key=lambda o: o.metadata.resource_version):
            callback("ADDED", obj, None)

        def cancel() -> None:
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)

        return cancel

    def _notify(
        self, event: str, obj: BaseObject, old: Optional[BaseObject]
    ) -> None:
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            if w.kinds is None or obj.kind in w.kinds:
                w.callback(event, obj, old)

    # ---- garbage collection ---------------------------------------------

    def collect_orphans(self) -> int:
        """Delete objects whose controller owner is gone (the kube GC
        analogue; the reference leans on ownerReferences for cascade).
        Scans snapshot views, not the live buckets: GC sweeps no longer
        stall writers, at the cost of possibly missing an orphan created
        mid-sweep (the next sweep gets it — GC is level-driven)."""
        doomed: List[BaseObject] = []
        views = [self.snapshot_view(kind) for kind in self.kinds()]
        uids = {o.metadata.uid for view in views for o in view}
        for view in views:
            for obj in view:
                ref = obj.metadata.controller_ref()
                if ref is not None and ref.uid not in uids:
                    doomed.append(obj)
        for obj in doomed:
            self.try_delete(obj.kind, obj.metadata.name, obj.metadata.namespace)
        return len(doomed)

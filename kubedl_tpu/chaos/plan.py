"""Deterministic, seed-driven fault injection.

The operator's failure behavior is a contract, not an accident — but until
now the only injection hook was the die-once-at-step-N env in
``training/entry.py``. This module gives every layer a *named injection
site* that production code consults via :func:`check` /
:func:`should_fail`. When no plan is armed (the default, and always the
case in production) the calls are a single module-global ``None`` test —
unmeasurable overhead. When a :class:`FaultPlan` is armed, each site
follows a seeded schedule so the same seed always produces the same fault
trace (asserted by the chaos suite's determinism test).

Injection sites wired in this repo::

    store.create / store.update / store.delete   ObjectStore writes
    node.heartbeat                               skip a kubelet beat
    elastic.preempt                              preemption notice on a node
    gang.bind                                    reject a slice reservation
    client.http                                  console client transport
    remote.request                               blob-server transport
    serving.dispatch                             device segment dispatch
    serving.canary_dispatch                      non-default-version dispatch tick
    serving.kv_alloc                             KV block allocation failure
    serving.kv_handoff                           KV handoff transfer failure
    serving.chunk_admit                          chunked-prefill admission dispatch
    serving.weight_swap                          corrupt/torn weight load or mid-swap crash
    checkpoint.torn                              die between shard + manifest
    store.wal_append                             torn WAL record (half-write)
    store.wal_fsync                              fail the WAL fsync syscall
    store.wal_group_commit                       fail the batched group-commit fsync
    watchdog.beacon                              freeze a node's beacon publish
    trainer.step_stall                           wedge the training step loop
    router.forward                               replica forward transport failure
    router.probe                                 router health-probe failure
    router.hedge                                 suppress a hedge dispatch
    ps.push                                      drop a parameter-service push
    ps.pull                                      drop a parameter-service pull
    ps.shard_failover                            kill a PS shard's owner mid-run
    shard.lease_renew                            skip a control-plane shard lease renewal beat
    shard.wal_append                             fail a fenced shard WAL append
    federation.heartbeat                         skip a federation member heartbeat beat
    federation.lease_io                          fail a federation member's lease-root IO

Schedules are per-site and deterministic: ``nth(n)`` fails exactly the
n-th call (1-based), ``first(k)`` fails the first k calls, ``prob(p, k)``
fails each of the first k calls with probability p using a RNG seeded from
``(plan.seed, site)``, ``always()`` fails every call, and
``latency(ms, every=n)`` injects a latency spike instead of an error.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


class FaultInjected(Exception):
    """Raised by :func:`check` when the armed plan schedules a fault."""


#: Canonical registry of every injection site wired into production code,
#: name -> one-line description. The module docstring table above and the
#: ``chaos.check``/``chaos.should_fail`` literals in the source are both
#: asserted against this mapping by the doc-drift test
#: (tests/test_chaos.py) — add new sites HERE first.
SITES: Dict[str, str] = {
    "store.create": "ObjectStore create write",
    "store.update": "ObjectStore update write",
    "store.delete": "ObjectStore delete write",
    "node.heartbeat": "skip a kubelet beat",
    "elastic.preempt": "preemption notice on a node",
    "gang.bind": "reject a slice reservation",
    "client.http": "console client transport",
    "remote.request": "blob-server transport",
    "serving.dispatch": "device segment dispatch",
    "serving.canary_dispatch": "non-default-version dispatch tick",
    "serving.kv_alloc": "KV block allocation failure",
    "serving.kv_handoff": "KV handoff transfer failure",
    "serving.chunk_admit": "chunked-prefill admission dispatch",
    "serving.weight_swap": "corrupt/torn weight load or mid-swap crash",
    "checkpoint.torn": "die between shard + manifest",
    "store.wal_append": "torn WAL record (half-write)",
    "store.wal_fsync": "fail the WAL fsync syscall",
    "store.wal_group_commit": "fail the batched group-commit fsync",
    "watchdog.beacon": "freeze a node's beacon publish",
    "trainer.step_stall": "wedge the training step loop",
    "router.forward": "replica forward transport failure",
    "router.probe": "router health-probe failure",
    "router.hedge": "suppress a hedge dispatch",
    "ps.push": "drop a parameter-service push",
    "ps.pull": "drop a parameter-service pull",
    "ps.shard_failover": "kill a PS shard's owner mid-run",
    "shard.lease_renew": "skip a control-plane shard lease renewal beat",
    "shard.wal_append": "fail a fenced shard WAL append",
    "federation.heartbeat": "skip a federation member heartbeat beat",
    "federation.lease_io": "fail a federation member's lease-root IO",
}


def sites() -> Dict[str, str]:
    """Introspection: every wired injection site with its description
    (a copy — mutating the result never corrupts the registry)."""
    return dict(SITES)


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled behavior at a site. Build via the class helpers."""

    mode: str                      # "nth" | "first" | "prob" | "always" | "latency"
    n: int = 0                     # nth: the 1-based call to fail
    k: int = 0                     # first/prob: number of leading calls in scope
    p: float = 0.0                 # prob: per-call failure probability
    latency_ms: float = 0.0        # latency: spike duration
    every: int = 1                 # latency: spike every n-th call
    exc: Optional[Callable[[str], BaseException]] = None  # exception factory

    @classmethod
    def nth(cls, n: int, exc: Optional[Callable[[str], BaseException]] = None) -> "FaultSpec":
        """Fail exactly the n-th call (1-based) to the site."""
        return cls(mode="nth", n=n, exc=exc)

    @classmethod
    def first(cls, k: int, exc: Optional[Callable[[str], BaseException]] = None) -> "FaultSpec":
        """Fail the first k calls to the site."""
        return cls(mode="first", k=k, exc=exc)

    @classmethod
    def prob(cls, p: float, k: int, exc: Optional[Callable[[str], BaseException]] = None) -> "FaultSpec":
        """Fail each of the first k calls with probability p (seeded)."""
        return cls(mode="prob", p=p, k=k, exc=exc)

    @classmethod
    def always(cls, exc: Optional[Callable[[str], BaseException]] = None) -> "FaultSpec":
        """Fail every call — the poison pill."""
        return cls(mode="always", exc=exc)

    @classmethod
    def latency(cls, ms: float, every: int = 1) -> "FaultSpec":
        """Inject a latency spike (no error) on every n-th call."""
        return cls(mode="latency", latency_ms=ms, every=max(1, every))


@dataclass
class TraceEntry:
    site: str
    call: int          # 1-based call number at the site
    action: str        # "fault" | "latency" | "pass"
    spec_mode: str = ""


class FaultPlan:
    """A seeded schedule of faults across named sites.

    The per-site RNG is derived from ``(seed, site)`` so adding a site or
    reordering calls at one site never perturbs another — same seed,
    same trace, every run.
    """

    def __init__(self, seed: int, sites: Optional[Dict[str, List[FaultSpec]]] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.seed = seed
        self._sites: Dict[str, List[FaultSpec]] = {}
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._sleep = sleep
        self.trace: List[TraceEntry] = []
        for site, specs in (sites or {}).items():
            for spec in specs:
                self.add(site, spec)

    def add(self, site: str, spec: FaultSpec) -> "FaultPlan":
        self._sites.setdefault(site, []).append(spec)
        return self

    def _rng(self, site: str) -> random.Random:
        if site not in self._rngs:
            self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return self._rngs[site]

    # ---- evaluation ------------------------------------------------------

    def evaluate(self, site: str) -> Tuple[str, Optional[FaultSpec], int]:
        """Advance the site's call counter and decide (action, spec, call#)."""
        with self._lock:
            call = self._calls.get(site, 0) + 1
            self._calls[site] = call
            specs = self._sites.get(site)
            if not specs:
                return ("pass", None, call)
            for spec in specs:
                if spec.mode == "nth" and call == spec.n:
                    hit = "fault"
                elif spec.mode == "first" and call <= spec.k:
                    hit = "fault"
                elif spec.mode == "always":
                    hit = "fault"
                elif spec.mode == "prob" and call <= spec.k:
                    if self._rng(site).random() < spec.p:
                        hit = "fault"
                    else:
                        continue
                elif spec.mode == "latency" and call % spec.every == 0:
                    hit = "latency"
                else:
                    continue
                self.trace.append(TraceEntry(site, call, hit, spec.mode))
                return (hit, spec, call)
            self.trace.append(TraceEntry(site, call, "pass"))
            return ("pass", None, call)

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def faults(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1 for t in self.trace
                if t.action == "fault" and (site is None or t.site == site)
            )

    def trace_tuples(self) -> List[Tuple[str, int, str]]:
        """Hashable trace view for determinism assertions."""
        with self._lock:
            return [(t.site, t.call, t.action) for t in self.trace]

    # ---- context manager -------------------------------------------------

    def __enter__(self) -> "FaultPlan":
        arm(self)
        return self

    def __exit__(self, *exc) -> None:
        disarm()


def plan_from_config(cfg: Dict,
                     sleep: Callable[[float], None] = time.sleep,
                     ) -> FaultPlan:
    """Build a :class:`FaultPlan` from a JSON-shaped dict, so a replica
    SUBPROCESS can arm a seeded schedule it cannot receive as an
    in-process context manager (``KUBEDL_SERVE_CONFIG["chaos"]`` — the
    rollout drill seeds a latency fault on a canary replica this way)::

        {"seed": 7, "sites": {"serving.canary_dispatch":
            [{"mode": "latency", "latency_ms": 250, "every": 1}]}}

    Unknown sites and modes raise ``ValueError`` at build time — a
    typo'd drill must fail at arm, not silently never fire."""
    seed = int(cfg.get("seed", 0))
    plan = FaultPlan(seed, sleep=sleep)
    for site, specs in dict(cfg.get("sites") or {}).items():
        if site not in SITES:
            raise ValueError(f"unknown chaos site {site!r}")
        for raw in specs:
            mode = str(raw.get("mode", ""))
            if mode == "latency":
                spec = FaultSpec.latency(float(raw["latency_ms"]),
                                         every=int(raw.get("every", 1)))
            elif mode == "nth":
                spec = FaultSpec.nth(int(raw["n"]))
            elif mode == "first":
                spec = FaultSpec.first(int(raw["k"]))
            elif mode == "prob":
                spec = FaultSpec.prob(float(raw["p"]), int(raw["k"]))
            elif mode == "always":
                spec = FaultSpec.always()
            else:
                raise ValueError(
                    f"unknown chaos spec mode {mode!r} at {site!r}"
                )
            plan.add(site, spec)
    return plan


# ---- module-level registry (the near-zero-cost fast path) ----------------

_PLAN: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Arm a plan globally. Tests should prefer ``with FaultPlan(...) as p:``."""
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def check(site: str) -> None:
    """Raise :class:`FaultInjected` (or the spec's exception) if the armed
    plan schedules a fault at this site. No-op when disarmed — callers pay
    one global load and a ``None`` test."""
    plan = _PLAN
    if plan is None:
        return
    action, spec, call = plan.evaluate(site)
    if action == "latency":
        plan._sleep(spec.latency_ms / 1000.0)
    elif action == "fault":
        if spec.exc is not None:
            raise spec.exc(site)
        raise FaultInjected(f"chaos: injected fault at {site} (call #{call})")


def should_fail(site: str) -> bool:
    """Bool-returning variant for sites that degrade by return value
    (gang bind rejection, skipped heartbeat) rather than by raising."""
    plan = _PLAN
    if plan is None:
        return False
    action, spec, _ = plan.evaluate(site)
    if action == "latency":
        plan._sleep(spec.latency_ms / 1000.0)
        return False
    return action == "fault"

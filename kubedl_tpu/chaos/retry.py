"""Shared retry policy: exponential backoff + full jitter + retry budget.

Every retry loop in the tree used to be hand-rolled (store conflict loops,
client re-dials, blob fetches). They now share this one policy so backoff
shape, jitter, and the total-sleep budget are a single contract. The
budget is the important part under heavy traffic: a storm of failing
calls must not multiply into unbounded sleeping threads — once a policy
instance has spent its budget, further failures surface immediately.

Full jitter per the AWS architecture blog: ``sleep = uniform(0, min(cap,
base * 2**attempt))``. Jitter decorrelates clients that fail in lockstep
(the thundering-herd the reference avoids via workqueue rate limiters).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


class RetryBudgetExhausted(Exception):
    """The policy's total-sleep budget ran out; the last error is chained."""


class RetryPolicy:
    def __init__(
        self,
        max_attempts: int = 5,
        base_delay: float = 0.02,
        max_delay: float = 1.0,
        budget_s: Optional[float] = None,
        rng: Optional[Callable[[float, float], float]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if rng is None:
            import random
            rng = random.Random(0xC4A05).uniform
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._rng = rng
        self._sleep = sleep
        self._lock = threading.Lock()
        self._budget = budget_s  # None = unlimited
        self.retries = 0         # total retries performed (observability)

    def backoff(self, attempt: int) -> float:
        """Full-jitter delay for a 0-based attempt number."""
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return self._rng(0.0, cap)

    def _spend(self, delay: float) -> float:
        """Debit the budget; returns the (possibly clipped) sleepable delay.

        Raises RetryBudgetExhausted when nothing is left."""
        with self._lock:
            self.retries += 1
            if self._budget is None:
                return delay
            if self._budget <= 0.0:
                raise RetryBudgetExhausted(
                    f"retry budget exhausted (spent across {self.retries} retries)"
                )
            delay = min(delay, self._budget)
            self._budget -= delay
            return delay

    def budget_remaining(self) -> Optional[float]:
        with self._lock:
            return self._budget

    def call(
        self,
        fn: Callable[[], T],
        retry_on: Tuple[Type[BaseException], ...] = (Exception,),
        giveup: Optional[Callable[[BaseException], bool]] = None,
    ) -> T:
        """Run ``fn`` with up to ``max_attempts`` tries.

        ``retry_on`` limits which exceptions are retried; ``giveup`` lets a
        caller refuse to retry specific instances (e.g. a 4xx ApiException
        is permanent, a 5xx is transient)."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:
                if giveup is not None and giveup(e):
                    raise
                last = e
                if attempt == self.max_attempts - 1:
                    break
                try:
                    delay = self._spend(self.backoff(attempt))
                except RetryBudgetExhausted as exhausted:
                    raise exhausted from e
                if delay > 0:
                    self._sleep(delay)
        assert last is not None
        raise last

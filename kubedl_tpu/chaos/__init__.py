"""Deterministic chaos layer: seeded fault injection + unified retry policy.

See :mod:`kubedl_tpu.chaos.plan` for injection sites and schedules, and
:mod:`kubedl_tpu.chaos.retry` for the shared backoff/budget policy.
``docs/robustness.md`` documents the contract.
"""

from kubedl_tpu.chaos.plan import (
    FaultInjected,
    FaultPlan,
    FaultSpec,
    TraceEntry,
    active,
    arm,
    check,
    disarm,
    plan_from_config,
    should_fail,
    sites,
)
from kubedl_tpu.chaos.retry import RetryBudgetExhausted, RetryPolicy

__all__ = [
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "TraceEntry",
    "RetryBudgetExhausted",
    "RetryPolicy",
    "active",
    "arm",
    "check",
    "disarm",
    "plan_from_config",
    "should_fail",
    "sites",
]

"""Inference serving: multi-predictor canary deployments over ModelVersions.

Reference: controllers/serving/ + apis/serving/v1alpha1 (SURVEY.md §2.3
Inference row): an Inference object fans out into per-predictor deployments
gated on the predictor's model artifact being built, fronted by one entry
service with weighted canary traffic across predictors (the reference uses
an Istio VirtualService; here a TrafficPolicy object consumed by the
router/console).
"""

from kubedl_tpu.serving.controller import InferenceController  # noqa: F401
from kubedl_tpu.serving.kv_blocks import BlockAllocator, TRASH_BLOCK  # noqa: F401
from kubedl_tpu.serving.prefix_cache import PrefixCache, PrefixEntry  # noqa: F401
from kubedl_tpu.serving.router import ServingRouter  # noqa: F401
from kubedl_tpu.serving.speculative import (  # noqa: F401
    NgramDraft, RepeatDraft, ScriptedDraft, SpecStats, accept_length,
    make_draft,
)
from kubedl_tpu.serving.types import Inference, Predictor, TrafficPolicy  # noqa: F401

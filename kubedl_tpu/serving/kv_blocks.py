"""Host-side block-table KV allocator for the paged serving cache.

The contiguous engine pre-allocates every batch row out to ``max_seq``,
so HBM — not compute — caps batch occupancy: a row serving a 40-token
chat holds the same KV footprint as one serving a 4k-token document.
Paged KV (the vLLM block-table idea) breaks the cache into fixed-size
blocks of ``block_size`` tokens; a row owns an ordered *block list* and
grows it as decode advances, so resident bytes track the tokens actually
cached, not the worst case (docs/serving.md "Paged KV").

This module is the HOST half: pure-Python bookkeeping over integer block
ids. The device half lives in `kubedl_tpu.models.llama` (pool layout
``[L, NB, BS, KV, hd]``; gather-view attention and scatter writes over a
``[B, MB]`` block table). The split keeps every policy decision —
refcounts, watermarks, copy-on-write, preemption — unit-testable with no
device in sight.

Invariants the engine relies on:

- **Block 0 is the trash block.** It is never allocated and never freed;
  every unmapped block-table entry points at it, so device writes from
  vacant/overshooting rows land in garbage nobody reads (the paged twin
  of the contiguous path's garbage-beyond-pos contract).
- **Refcounts make sharing safe.** A prefix-cache entry and any number
  of rows may reference the same block; `free` decrements and only
  returns the block to the free list at zero. A block with refs >= 2 is
  *shared* and therefore read-only — the engine copies it
  (`copy-on-write`) before any write can land inside it, which in
  practice means exactly the partial tail block of a grafted prefix:
  full blocks are never written again, so they are shared by reference
  forever at zero copy cost.
- **Watermarks drive admission, with hysteresis.** When the free
  fraction drops below ``low_watermark`` the allocator closes admission;
  it reopens only once frees recover past ``high_watermark``, so
  admission does not flap around one block. The engine sheds (503 +
  Retry-After) while closed and defers admitting queued requests.

Thread safety: one internal lock; the scheduler thread and request
threads (stats) both call in.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional

#: the reserved write-sink block every unmapped table entry points at
TRASH_BLOCK = 0


class BlockExhausted(Exception):
    """Raised by callers that treat allocation failure as an error (the
    allocator itself returns None — preemption is the engine's policy)."""


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size KV blocks.

    ``num_blocks`` INCLUDES the reserved trash block 0, mirroring the
    device pool's leading dimension; ``total`` reports usable blocks.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 low_watermark: float = 0.05,
                 high_watermark: float = 0.15) -> None:
        if num_blocks < 2:
            raise ValueError("need at least one usable block beyond trash")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        if not 0.0 <= low_watermark <= high_watermark <= 1.0:
            raise ValueError("need 0 <= low <= high <= 1 watermarks")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.low_watermark = float(low_watermark)
        self.high_watermark = float(high_watermark)
        self._lock = threading.Lock()
        # LIFO free list: hot blocks cycle, keeping the working set dense
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._refs: List[int] = [0] * self.num_blocks
        self._refs[TRASH_BLOCK] = 1  # pinned forever
        self._admitting = True
        self._stats = {"allocs": 0, "frees": 0, "alloc_failures": 0,
                       "cow_copies": 0}

    # -- capacity ----------------------------------------------------------

    @property
    def total(self) -> int:
        """Usable blocks (the trash block is not capacity)."""
        return self.num_blocks - 1

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to cache ``n_tokens`` token positions."""
        return max(0, (int(n_tokens) + self.block_size - 1) // self.block_size)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_count(self) -> int:
        with self._lock:
            return self.total - len(self._free)

    @property
    def shared_count(self) -> int:
        """Blocks referenced by >= 2 owners (prefix entries + rows)."""
        with self._lock:
            return sum(
                1 for b in range(1, self.num_blocks) if self._refs[b] >= 2
            )

    def free_fraction(self) -> float:
        with self._lock:
            return len(self._free) / max(self.total, 1)

    # -- alloc / free / sharing -------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks (refcount 1 each) or None if the free
        list cannot cover them — all-or-nothing, so a half-grown row
        never exists. Updates the admission hysteresis either way."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                self._stats["alloc_failures"] += 1
                return None
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            self._stats["allocs"] += n
            self._update_gate_locked()
            return out

    def incref(self, blocks: Iterable[int]) -> None:
        """Add one reference per block (prefix entry sharing a row's
        blocks, or a graft sharing an entry's)."""
        with self._lock:
            for b in blocks:
                if b == TRASH_BLOCK:
                    continue
                if self._refs[b] <= 0:
                    raise ValueError(f"incref of unallocated block {b}")
                self._refs[b] += 1

    def free(self, blocks: Iterable[int]) -> int:
        """Drop one reference per block; blocks reaching zero return to
        the free list. Returns how many were actually reclaimed."""
        reclaimed = 0
        with self._lock:
            for b in blocks:
                if b == TRASH_BLOCK:
                    continue
                if self._refs[b] <= 0:
                    raise ValueError(f"double free of block {b}")
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    self._free.append(b)
                    reclaimed += 1
            self._stats["frees"] += reclaimed
            self._update_gate_locked()
        return reclaimed

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs[block]

    def is_shared(self, block: int) -> bool:
        """True when a write into ``block`` would be visible to another
        owner — the copy-on-write trigger."""
        with self._lock:
            return self._refs[block] >= 2

    def cow(self, block: int) -> Optional[int]:
        """Copy-on-write bookkeeping: allocate a private replacement for
        shared ``block`` and drop this owner's reference to the original.
        The caller owns the DEVICE copy of the payload (the host side
        cannot move bytes). Returns the new block id, or None when no
        block is free. For an unshared block this is a no-op returning
        the block itself — callers can call it unconditionally."""
        with self._lock:
            if block != TRASH_BLOCK and self._refs[block] < 2:
                return block
            if not self._free:
                self._stats["alloc_failures"] += 1
                return None
            new = self._free.pop()
            self._refs[new] = 1
            if block != TRASH_BLOCK:
                self._refs[block] -= 1
                if self._refs[block] == 0:  # last other owner freed it
                    self._free.append(block)
            self._stats["allocs"] += 1
            self._stats["cow_copies"] += 1
            self._update_gate_locked()
            return new

    # -- admission watermarks ---------------------------------------------

    def _update_gate_locked(self) -> None:
        frac = len(self._free) / max(self.total, 1)
        if self._admitting and frac < self.low_watermark:
            self._admitting = False
        elif not self._admitting and frac >= self.high_watermark:
            self._admitting = True

    def admission_open(self) -> bool:
        """Hysteresis gate: False between crossing the low watermark and
        recovering past the high watermark. The engine sheds new requests
        (503 + Retry-After) and defers queued admissions while closed."""
        with self._lock:
            return self._admitting

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            free = len(self._free)
            shared = sum(
                1 for b in range(1, self.num_blocks) if self._refs[b] >= 2
            )
            out = dict(self._stats)
        out.update({
            "total": self.total,
            "free": free,
            "used": self.total - free,
            "shared": shared,
            "block_size": self.block_size,
            "free_fraction": round(free / max(self.total, 1), 4),
            "admission_open": self._admitting,
            "low_watermark": self.low_watermark,
            "high_watermark": self.high_watermark,
        })
        return out

"""Disaggregated prefill/decode serving: the KV handoff artifact and the
per-tenant QoS arbiter.

Prefill is compute-bound and batch-friendly; decode is memory-bandwidth-
bound and latency-critical. Running both on every replica forces one
batch geometry onto two regimes — segments cap at 4 steps whenever a
prefill is waiting, and prefill batches fragment around resident decode
rows. Splitting them into pools lets each be sized and scheduled for its
own regime. The transfer unit is PR 8's refcounted KV block: a prefill
replica fills a row's blocks, samples the first token, and exports the
block payloads + logical table order + first token as a ``KVHandoff``;
a decode replica adopts it — allocates blocks from its OWN pool
(all-or-nothing, same watermark admission), scatters the payloads in,
and resumes decoding as if it had prefilled the row itself. Greedy
output is bit-identical to the colocated path because the handoff point
is exactly the colocated engine's own prefill/decode seam: first token
from prefill logits, pos = prompt length, next input = first token.

The QoS side: requests carry a tenant (``X-Tenant`` header), tenants map
to classes, and a :class:`WeightedFairQueue` arbitrates dispatch slots —
smooth weighted round-robin across classes for proportional service,
strict shed-lowest-priority-first when the queue overflows. The router
composes this with the engines' own KV-watermark sheds: high classes
get dispatch slots first, so under sustained overload the lowest class
absorbs the 503s.
"""
from __future__ import annotations

import io
import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

HANDOFF_MAGIC = b"KVH1"


@dataclass
class KVHandoff:
    """Everything a decode replica needs to resume a prefilled row.

    ``k``/``v`` are [L, n_blocks, block_size, KV_heads, head_dim] host
    arrays — the row's block payloads in LOGICAL table order (block ids
    are allocator-local and never cross the wire). ``pos`` is the number
    of valid positions (== prompt length as fed); ``first_token`` is the
    token sampled from the prefill logits, which the adopter feeds as the
    first decode input exactly like the colocated engine would."""

    model: str
    prompt_ids: List[int]
    first_token: int
    pos: int
    block_size: int
    k: np.ndarray
    v: np.ndarray
    max_tokens: int = 16
    temperature: float = 0.0
    request_id: str = ""
    cache_prefix: bool = False
    ttft_ms: Optional[float] = None
    #: X-Trace-Context header string of the originating trace (parent =
    #: the prefill request span) — lets an adopter with no HTTP header
    #: of its own still attach its spans to the caller's trace
    trace: str = ""
    #: weight version the prefill ran on (docs/serving.md "Model
    #: lifecycle"): the adopter decodes on exactly this version or
    #: rejects the handoff — disagg legs never mix versions. "" keeps
    #: pre-versioning artifacts adoptable (engine default).
    model_version: str = ""

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes + self.v.nbytes)

    def to_bytes(self) -> bytes:
        """Serialize: magic, u32 header length, JSON header, raw K then V
        buffers (C-order). Dtype/shape ride in the header so the adopter
        validates geometry before touching its allocator."""
        header = json.dumps({
            "model": self.model,
            "prompt_ids": [int(t) for t in self.prompt_ids],
            "first_token": int(self.first_token),
            "pos": int(self.pos),
            "block_size": int(self.block_size),
            "max_tokens": int(self.max_tokens),
            "temperature": float(self.temperature),
            "request_id": self.request_id,
            "cache_prefix": bool(self.cache_prefix),
            "ttft_ms": self.ttft_ms,
            "trace": self.trace,
            "model_version": self.model_version,
            "dtype": str(self.k.dtype),
            "shape": list(self.k.shape),
        }).encode()
        buf = io.BytesIO()
        buf.write(HANDOFF_MAGIC)
        buf.write(len(header).to_bytes(4, "big"))
        buf.write(header)
        buf.write(np.ascontiguousarray(self.k).tobytes())
        buf.write(np.ascontiguousarray(self.v).tobytes())
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVHandoff":
        if data[:4] != HANDOFF_MAGIC:
            raise ValueError("not a KVHandoff artifact (bad magic)")
        hlen = int.from_bytes(data[4:8], "big")
        header = json.loads(data[8:8 + hlen])
        shape = tuple(header["shape"])
        dtype = np.dtype(header["dtype"])
        off = 8 + hlen
        size = int(np.prod(shape)) * dtype.itemsize
        if len(data) < off + 2 * size:
            raise ValueError("truncated KVHandoff artifact")
        k = np.frombuffer(data, dtype, count=int(np.prod(shape)),
                          offset=off).reshape(shape)
        v = np.frombuffer(data, dtype, count=int(np.prod(shape)),
                          offset=off + size).reshape(shape)
        return cls(
            model=header["model"],
            prompt_ids=list(header["prompt_ids"]),
            first_token=int(header["first_token"]),
            pos=int(header["pos"]),
            block_size=int(header["block_size"]),
            k=k, v=v,
            max_tokens=int(header["max_tokens"]),
            temperature=float(header["temperature"]),
            request_id=header.get("request_id", ""),
            cache_prefix=bool(header.get("cache_prefix", False)),
            ttft_ms=header.get("ttft_ms"),
            trace=header.get("trace", ""),
            model_version=header.get("model_version", ""),
        )


class HandoffError(RuntimeError):
    """A handoff transfer failed mid-flight (export/adopt leg). The
    blocks involved are already released — callers retry or fall back
    to the colocated path; they never clean up allocator state."""


class QoSShed(Exception):
    """Raised by :meth:`WeightedFairQueue.acquire` when a request is shed
    — queue overflow chose it as the lowest-priority victim, or its
    deadline expired while queued. Carries the class for metrics and the
    distinguishable 503 payload."""

    def __init__(self, qos_class: str, why: str = "queue overflow"):
        super().__init__(f"qos shed ({qos_class}): {why}")
        self.qos_class = qos_class
        self.why = why


@dataclass
class QoSClassSpec:
    """One QoS class: ``weight`` sets the dispatch share under contention
    (smooth weighted round-robin), ``priority`` sets shed order — HIGHER
    numbers shed first (priority 0 is the most protected class)."""

    weight: int = 1
    priority: int = 10


class _Waiter:
    __slots__ = ("cls", "event", "shed", "admitted")

    def __init__(self, cls: str):
        self.cls = cls
        self.event = threading.Event()
        self.shed = False
        self.admitted = False


class WeightedFairQueue:
    """Arbitrates a fixed number of concurrent dispatch slots across QoS
    classes. Admission order under contention is smooth weighted
    round-robin (nginx-style: each grant adds ``weight`` to the class's
    credit, the winner pays back the total) — deterministic and
    proportional. Overflow sheds strictly lowest-priority-first: the
    victim is a queued waiter from the worst class, or the arriving
    request itself if it IS the worst class."""

    def __init__(
        self,
        classes: Optional[Dict[str, QoSClassSpec]] = None,
        capacity: int = 8,
        max_queue: int = 64,
        default_class: str = "",
        clock=time.monotonic,
    ):
        self.classes: Dict[str, QoSClassSpec] = dict(classes or {})
        if not self.classes:
            self.classes = {"default": QoSClassSpec()}
        if not default_class or default_class not in self.classes:
            # default to the worst class: unknown tenants never outrank
            # a configured one
            default_class = max(
                self.classes, key=lambda c: (self.classes[c].priority, c)
            )
        self.default_class = default_class
        self.capacity = int(capacity)
        self.max_queue = int(max_queue)
        self._clock = clock
        self._lock = threading.Lock()
        self._active = 0
        self._queues: Dict[str, deque] = {c: deque() for c in self.classes}
        self._credit: Dict[str, float] = {c: 0.0 for c in self.classes}
        self.sheds: Dict[str, int] = {c: 0 for c in self.classes}
        self.admits: Dict[str, int] = {c: 0 for c in self.classes}

    def resolve(self, tenant_or_class: Optional[str],
                tenants: Optional[Dict[str, str]] = None) -> str:
        """Map an ``X-Tenant`` value to a class: explicit tenant map
        first, then a class named literally, else the default class."""
        t = (tenant_or_class or "").strip()
        if tenants and t in tenants and tenants[t] in self.classes:
            return tenants[t]
        if t in self.classes:
            return t
        return self.default_class

    def queue_depths(self) -> Dict[str, int]:
        with self._lock:
            return {c: len(q) for c, q in self._queues.items()}

    def acquire(self, cls: str, timeout_s: float = 30.0) -> str:
        """Block until a dispatch slot is granted; raises :class:`QoSShed`
        on overflow eviction or queue-deadline expiry. Returns the class
        actually charged (callers pass it back to :meth:`release`)."""
        if cls not in self.classes:
            cls = self.default_class
        me = _Waiter(cls)
        with self._lock:
            if self._active < self.capacity and not self._queued_locked():
                self._active += 1
                self.admits[cls] += 1
                return cls
            if self._queued_locked() >= self.max_queue:
                victim = self._worst_locked()
                if victim is None or (
                    self.classes[cls].priority
                    >= self.classes[victim.cls].priority
                ):
                    # the arrival is (at least tied for) the worst class:
                    # it absorbs the shed, queued work keeps its place
                    self.sheds[cls] += 1
                    raise QoSShed(cls)
                self._queues[victim.cls].remove(victim)
                victim.shed = True
                self.sheds[victim.cls] += 1
                victim.event.set()
            self._queues[cls].append(me)
        if not me.event.wait(timeout=max(0.0, timeout_s)):
            with self._lock:
                if not me.admitted and not me.shed:
                    try:
                        self._queues[cls].remove(me)
                    except ValueError:
                        pass
                    self.sheds[cls] += 1
                    raise QoSShed(cls, "queue deadline expired")
        if me.shed:
            raise QoSShed(cls)
        if me.admitted:
            return cls
        # woken between timeout and lock: treat as admitted iff flagged
        with self._lock:
            if me.admitted:
                return cls
            self.sheds[cls] += 1
            raise QoSShed(cls, "queue deadline expired")

    def release(self, cls: str) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)
            self._grant_locked()

    def _queued_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _worst_locked(self) -> Optional[_Waiter]:
        worst = None
        for c, q in self._queues.items():
            if not q:
                continue
            if worst is None or (
                self.classes[c].priority
                > self.classes[worst].priority
            ):
                worst = c
        return self._queues[worst][-1] if worst else None

    def _grant_locked(self) -> None:
        """Smooth weighted round-robin over nonempty classes."""
        while self._active < self.capacity:
            ready = [c for c, q in self._queues.items() if q]
            if not ready:
                return
            total = 0.0
            for c in ready:
                self._credit[c] += self.classes[c].weight
                total += self.classes[c].weight
            pick = max(
                ready,
                key=lambda c: (self._credit[c],
                               -self.classes[c].priority, c),
            )
            self._credit[pick] -= total
            w = self._queues[pick].popleft()
            w.admitted = True
            self._active += 1
            self.admits[pick] += 1
            w.event.set()


def qos_from_config(cfg: Optional[Dict]) -> Optional[WeightedFairQueue]:
    """Build the arbiter from a router-config ``qos`` block::

        {"classes": {"gold":   {"weight": 8, "priority": 0},
                     "bronze": {"weight": 1, "priority": 2}},
         "tenants": {"acme": "gold"},
         "default_class": "bronze", "capacity": 8, "max_queue": 64}
    """
    if not cfg or not isinstance(cfg, dict):
        return None
    classes = {
        name: QoSClassSpec(
            weight=int(spec.get("weight", 1)),
            priority=int(spec.get("priority", 10)),
        )
        for name, spec in (cfg.get("classes") or {}).items()
    }
    return WeightedFairQueue(
        classes=classes,
        capacity=int(cfg.get("capacity", 8)),
        max_queue=int(cfg.get("max_queue", 64)),
        default_class=str(cfg.get("default_class", "")),
    )


class DisaggCoordinator:
    """In-process prefill→adopt pump over engine objects — the local twin
    of the router's two-leg HTTP dispatch, used by tests, the conservation
    suite, and ``bench.py --disagg``. One call = one full request: prefill
    on the prefill engine, serialize/deserialize the handoff (exercising
    the wire format), adopt on the decode engine."""

    def __init__(self, prefill_engine, decode_engine, serialize: bool = True):
        self.prefill = prefill_engine
        self.decode = decode_engine
        self.serialize = serialize

    def generate(self, prompt_ids, max_tokens: int = 16,
                 temperature: float = 0.0, timeout_s: float = 600.0,
                 cache_prefix: bool = False, request_id: str = "",
                 trace=None, model_version: str = "") -> Dict:
        h = self.prefill.prefill_handoff(
            prompt_ids, max_tokens=max_tokens, temperature=temperature,
            timeout_s=timeout_s, cache_prefix=cache_prefix,
            request_id=request_id, trace=trace,
            model_version=model_version,
        )
        if self.serialize:
            h = KVHandoff.from_bytes(h.to_bytes())
        # no explicit trace here: the handoff's embedded header keeps the
        # adopt leg on the same trace (server._arm_trace parses it)
        return self.decode.adopt_handoff(h, timeout_s=timeout_s)

"""JAX inference server: the workload a JAX-framework predictor pod runs.

TPU-native serving path (BASELINE.md target 5): loads the checkpoint the
lineage pipeline published (KUBEDL_MODEL_PATH), jit-compiles the static-
shape KV-cache decode step ONCE (`llama.decode_step` — pre-allocated cache,
no retracing), and serves greedy decoding over HTTP:

- GET  /healthz            -> {"status": "ok"}
- GET  /v1/models          -> model metadata
- POST /v1/generate        -> {"prompt_ids": [...], "max_tokens": N}
                              -> {"token_ids": [...], "latency_ms": ...}

Runs under either container runtime: entrypoint
"kubedl_tpu.serving.server:serve_main" (ThreadRuntime) or
`python -m kubedl_tpu.serving.server` (SubprocessRuntime).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from kubedl_tpu import chaos
from kubedl_tpu.observability.tracing import (
    TRACE_HEADER,
    TRACER,
    TraceContext,
    new_span_id,
    new_trace_id,
    parse_trace_header,
    span_to_dict,
)

log = logging.getLogger("kubedl_tpu.serving.server")


class EngineOverloaded(Exception):
    """Queue-depth/age budget exceeded — callers get 503 + Retry-After
    instead of joining a queue that can no longer meet its latency budget
    (docs/robustness.md: shedding early keeps the served fraction fast).

    ``reason`` distinguishes the two admission-stop causes the router
    must treat differently: "overloaded" (come back after Retry-After)
    vs "draining" (this replica is going away — fail over NOW, and the
    rejection never counts against the retry budget because the request
    was never admitted)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 reason: str = "overloaded") -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.reason = reason


class UnknownModelVersion(ValueError):
    """A request named a model version this engine has not loaded (or
    one already retiring) — a client/config error, not overload: the
    handler answers 400, never 503, so the router does not fail it over
    to a replica that cannot know the version either."""


class _Slot:
    """One in-flight sequence occupying a batch row."""

    def __init__(self, prompt, max_tokens: int, temperature: float,
                 cache_prefix: bool = False, request_id: str = "") -> None:
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.cache_prefix = cache_prefix  # request opted into insertion
        self.request_id = request_id  # non-empty: cancellable via cancel()
        self.fed = 0  # inputs consumed (prompt + generated)
        self.pending = 0  # tokens dispatched on device, not yet harvested
        self.cached_len = 0  # prompt tokens grafted from the prefix cache
        #: chunked-prefill progress: prompt tokens whose KV is committed
        #: (grafted prefix + dispatched chunks); -1 = chunking not
        #: started. Stays strictly below len(prompt) until the FINAL
        #: chunk lands, which is also when ``fed`` jumps to the prompt
        #: length and the row becomes a decoding row.
        self.prefill_pos = -1
        self.pinned = None  # PrefixEntry pinned while this row uses it
        self.ttft_ms: Optional[float] = None
        self.out_ids: list = []
        #: disaggregation (docs/serving.md "Disaggregated serving"):
        #: ``handoff`` (a meta dict) marks a prefill-pool slot that
        #: finalizes into a parked KVHandoff after its first token;
        #: ``adopt`` (a KVHandoff) marks a decode-pool slot that skips
        #: prefill and resumes from imported blocks
        self.handoff: Optional[Dict] = None
        self.adopt = None
        #: weight version serving this row (docs/serving.md "Model
        #: lifecycle"): resolved at admission-gate time to a loaded
        #: version id ("" until then = the engine default). Dispatch is
        #: partitioned by version per tick, so one forward never mixes
        #: parameter trees.
        self.version = ""
        #: distributed tracing (docs/observability.md): ``trace`` is the
        #: caller's context (X-Trace-Context); ``span_id`` is this
        #: request's PRE-MINTED engine.request id, so scheduler-side
        #: sub-spans recorded before the request span exists can already
        #: parent under it
        self.trace: Optional[TraceContext] = None
        self.span_id = ""
        self.prefill_t0: Optional[float] = None
        self.done = threading.Event()
        self.result: Optional[Dict] = None
        self.t0 = time.perf_counter()

    def next_input(self) -> int:
        seq = self.prompt + self.out_ids
        return int(seq[self.fed])


class LlamaEngine:
    """Continuous-batching decode engine (the reference only *models*
    batching in the API, inference_types.go:96-104 — here it is real):
    up to ``max_batch`` sequences share one jitted
    `llama.decode_step_batched` with per-row positions; a scheduler thread
    admits waiting requests into free rows between steps, so concurrent
    requests interleave instead of queueing behind a lock. Static shapes:
    one compile serves every mix of in-flight requests. Decode runs in
    multi-step SEGMENTS with on-device sampling (llama.decode_segment):
    only sampled ids cross to the host, once per segment."""

    #: allowed decode-segment sizes, largest first — a small fixed menu
    #: bounds compiles to len(menu) while still amortizing the dispatch +
    #: host round trip ~32x on long generations; segments shrink to 4
    #: whenever requests are waiting (admission latency <= 4 tokens)
    SEGMENT_BUCKETS = (32, 4, 1)

    def __init__(self, preset: str = "tiny", ckpt_dir: str = "",
                 batch: int = 0, max_seq: int = 0, max_batch: int = 4,
                 quantize: str = "", mesh_axes: Optional[Dict] = None,
                 metrics=None, max_queue_depth: int = 64,
                 max_queue_age_s: float = 30.0,
                 prefix_cache_mb: float = 64.0,
                 prefix_min_len: int = 8,
                 kv_layout: str = "paged", kv_block_size: int = 16,
                 kv_blocks: int = 0, kv_low_watermark: float = 0.05,
                 kv_high_watermark: float = 0.15,
                 spec_k: int = 0, spec_draft: str = "ngram",
                 kv_attention: str = "gather",
                 spec_candidates: int = 1,
                 spec_draft_layers: int = 0,
                 spec_tree: bool = False,
                 prefill_chunk_tokens: int = 0,
                 role: str = "colocated",
                 advertise_prefix_len: int = 8,
                 handoff_ttl_s: float = 30.0,
                 model_version: str = "base") -> None:
        import jax

        from kubedl_tpu.models import llama

        if kv_layout not in ("paged", "contiguous"):
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        if role not in ("", "colocated", "prefill", "decode"):
            raise ValueError(
                f"unknown serving role {role!r} "
                "(have: colocated, prefill, decode)"
            )
        #: fleet role, ADVISORY: a prefill/decode engine still serves the
        #: full /v1/generate path (the router's colocated fallback when
        #: the peer pool is down depends on it) — the role only tells the
        #: router how to partition dispatch
        self.role = role or "colocated"
        self.preset_name = preset
        self.advertise_prefix_len = int(advertise_prefix_len)
        self.handoff_ttl_s = float(handoff_ttl_s)
        if kv_attention not in ("gather", "blocked"):
            raise ValueError(
                f"unknown kv_attention {kv_attention!r} "
                "(have: gather, blocked)"
            )
        if mesh_axes and kv_layout == "paged":
            # megatron-sharded serving keeps the CONTIGUOUS layout: the
            # paged pool gather reorders attention reductions enough to
            # flip near-tie argmaxes under row-parallel psum, which would
            # break the sharded==unsharded exactness contract. Paged KV
            # is a single-host batch-density lever.
            kv_layout = "contiguous"
            spec_k = 0
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        #: which paged-attention implementation the jitted hot paths
        #: compile in: "gather" (the bit-exactness oracle, default) or
        #: "blocked" (models.paged_attention — the online-softmax kernel
        #: that never materializes the [B, max_seq] view; fp-close,
        #: greedy-token-identical). Contiguous engines ignore it.
        self.kv_attention = kv_attention if self._paged else "gather"
        self.spec_k = int(spec_k)
        self.spec_candidates = max(1, int(spec_candidates))
        if self.spec_k and not self._paged:
            raise ValueError(
                "speculative decoding requires kv_layout='paged' (the "
                "verify rollback frees rejected-suffix blocks in place)"
            )
        #: tree speculation (docs/serving.md "Tree speculation"): fold
        #: the N candidate chains into a prefix trie and score every
        #: node in one read-only forward. Needs multi-candidate paged
        #: speculation to mean anything — silently off otherwise (the
        #: same normalization style as the mesh/paged interactions).
        self.spec_tree = (
            bool(spec_tree) and self.spec_k > 0 and self.spec_candidates > 1
        )
        self.cfg = llama.preset(preset)
        self.max_seq = max_seq or min(self.cfg.max_seq, 512)
        self.max_batch = batch or max_batch
        if self._paged:
            # the gathered view is [B, MB * BS]: max_seq rounds UP to a
            # whole number of blocks so view position t == logical t
            bs = max(1, int(kv_block_size))
            self.kv_block_size = bs
            self.max_seq = ((self.max_seq + bs - 1) // bs) * bs
        #: chunked prefill (docs/serving.md "Continuous batching"): > 0
        #: caps the PROMPT tokens one scheduler tick may prefill, so
        #: long prompts land block-sized chunk by chunk, interleaved
        #: with decode segments, instead of stalling the whole running
        #: batch for one giant forward. Paged-only (chunks must be
        #: block-aligned to keep every block fully owned by one write).
        pct = max(0, int(prefill_chunk_tokens))
        if pct and self._paged:
            pct = max(self.kv_block_size,
                      (pct // self.kv_block_size) * self.kv_block_size)
            self.prefill_chunk_tokens = pct
        else:
            self.prefill_chunk_tokens = 0
        if quantize and quantize != "int8":
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.quantize = quantize
        self.mesh = None
        if mesh_axes:
            # multi-chip serving (BASELINE target 5: Gemma-2B on v5e-4):
            # megatron-shard the weights over the mesh; XLA inserts the
            # collectives in the jitted decode/prefill
            from kubedl_tpu.api.topology import MeshSpec
            from kubedl_tpu.parallel.mesh import build_mesh

            spec = MeshSpec({k: int(v) for k, v in mesh_axes.items()})
            self.mesh = build_mesh(spec, jax.devices()[: spec.size()])
            log.info("serving over mesh %s", dict(mesh_axes))
        self._llama = llama
        self._jax = jax
        #: versioned weights (docs/serving.md "Model lifecycle"): every
        #: loaded parameter tree lives here under a version id; the
        #: default version serves requests that name none. All jitted
        #: entry points take params as an explicit argument, so a second
        #: tree rides the SAME compiles — hot-swap is just passing a
        #: different pytree.
        self._default_version = str(model_version) or "base"
        params = self._build_params(ckpt_dir)
        self.params = params
        self._versions: Dict[str, object] = {self._default_version: params}
        #: versions drained-and-awaiting-eviction: unroutable for new
        #: requests, evicted (tree dropped) once their last in-flight
        #: row frees — never while a row still dispatches on them
        self._retiring: set = set()
        self._vers_rr = 0
        # the cache is DONATED: decode/prefill update it in place in HBM
        # instead of allocating a fresh copy every step
        if self._paged:
            self._decode = jax.jit(
                lambda p, c, t: llama.paged_decode_step_batched(
                    p, c, t, self.cfg, kv_attention=self.kv_attention
                ),
                donate_argnums=(1,),
            )
            # whole-prompt prefill is LOCAL causal attention (no pool
            # read), so there is nothing for the blocked kernel to do
            self._prefill = jax.jit(
                lambda p, c, t, l: llama.paged_prefill_batched(
                    p, c, t, l, self.cfg
                ),
                donate_argnums=(1,),
            )
            self._prefill_from = jax.jit(
                lambda p, c, t, l, st: llama.paged_prefill_from(
                    p, c, t, l, st, self.cfg,
                    kv_attention=self.kv_attention,
                ),
                donate_argnums=(1,),
            )
            #: paged prefix-cache ops: entries normally share the row's
            #: blocks by reference (no device copy at all); _graft only
            #: fires for array-payload entries (direct inserts in tests),
            #: and _copy_block is the copy-on-write primitive for the
            #: partial tail block of a graft. One compile each.
            self._graft = jax.jit(
                llama.paged_graft_prefix, donate_argnums=(0,)
            )
            self._copy_block = jax.jit(
                llama.copy_kv_block, donate_argnums=(0,)
            )
            self._extract = None  # paged inserts never materialize arrays
        else:
            self._decode = jax.jit(
                lambda p, c, t: llama.decode_step_batched(p, c, t, self.cfg),
                donate_argnums=(1,),
            )
            self._prefill = jax.jit(
                lambda p, c, t, l: llama.prefill_batched(
                    p, c, t, l, self.cfg
                ),
                donate_argnums=(1,),
            )
            #: suffix-only prefill (per-row start offsets): newly admitted
            #: rows with a grafted prefix consume only their uncached tail.
            #: Same power-of-2 bucketing as _prefill, so compile count
            #: stays bounded (<= one per bucket per path).
            self._prefill_from = jax.jit(
                lambda p, c, t, l, st: llama.prefill_batched_from(
                    p, c, t, l, st, self.cfg
                ),
                donate_argnums=(1,),
            )
            #: prefix-cache device ops: graft writes a cached entry's K/V
            #: into a row (donated: in-place in HBM), extract copies a
            #: row's prefix span out as a new entry (NOT donated — the
            #: live cache survives). One compile per entry bucket length.
            self._graft = jax.jit(
                llama.copy_prefix_into_row, donate_argnums=(0,)
            )
            self._extract = jax.jit(
                llama.extract_prefix_from_row, static_argnums=(2,)
            )
        # first-token sampler, ON DEVICE: fetching the prefill logits to
        # sample on the host moved the full [B, V] array over the wire —
        # 8MB for Gemma-2B at B=8, measured ~0.8s of the engine's TTFT on
        # the tunnel. Only the sampled ids ([B] int32) cross now.
        import jax.numpy as _jnp

        def _pick(logits, temps, key):
            g = jax.random.gumbel(key, logits.shape, dtype=logits.dtype)
            z = _jnp.where(
                temps[:, None] > 0.0,
                logits / _jnp.maximum(temps[:, None], 1e-4) + g,
                logits,
            )
            return _jnp.argmax(z, axis=-1).astype(_jnp.int32)

        self._sample_logits = jax.jit(_pick)
        #: grafts prefill-sampled first tokens into the device token chain
        #: (llama.merge_chain_tokens) so interleaved admissions never force
        #: the chain back through the host
        self._merge_chain = jax.jit(llama.merge_chain_tokens)
        if self._paged:
            import math

            import numpy as np

            from kubedl_tpu.serving.kv_blocks import BlockAllocator
            from kubedl_tpu.serving.speculative import SpecStats, make_draft

            bs = self.kv_block_size
            mb = self.max_seq // bs
            #: bytes one block holds across both pools and all layers —
            #: the unit prefix-cache budget accounting is charged in
            self._block_bytes = int(
                2 * self.cfg.n_layers * bs * self.cfg.n_kv_heads
                * self.cfg.head_dim * np.dtype(self.cfg.dtype).itemsize
            )
            if kv_blocks:
                nb = int(kv_blocks)
                if nb < mb + 1:
                    raise ValueError(
                        f"kv_blocks={nb} cannot hold one max_seq row "
                        f"({mb} blocks + trash)"
                    )
            else:
                # parity sizing: every batch row can still reach max_seq
                # (the contiguous footprint), plus headroom for prefix-
                # cache entries capped at one batch's worth of blocks
                prefix_blocks = 0
                if prefix_cache_mb > 0:
                    prefix_blocks = min(
                        math.ceil(prefix_cache_mb * 1e6 / self._block_bytes),
                        self.max_batch * mb,
                    )
                nb = 1 + self.max_batch * mb + prefix_blocks
            self.kv_blocks = nb
            self._alloc = BlockAllocator(
                nb, bs, low_watermark=kv_low_watermark,
                high_watermark=kv_high_watermark,
            )
            #: host-authoritative mirrors of the device cache's pos/bt —
            #: uploaded before EVERY dispatch so rollbacks (speculative
            #: rejection, preemption, vacation) are just mirror edits
            self._pos_host = np.zeros((self.max_batch,), np.int32)
            self._bt_host = np.zeros((self.max_batch, mb), np.int32)
            self._row_blocks: list = [[] for _ in range(self.max_batch)]
            self._cache = llama.init_paged_cache(
                self.cfg, self.max_batch, self.max_seq, nb, bs
            )
            self.spec_draft = spec_draft
            if self.spec_k:
                if spec_draft == "model":
                    from kubedl_tpu.serving.speculative import ModelDraft

                    # early-exit draft carved out of the target's own
                    # stacked weights (views, no copies); depth defaults
                    # to half the target
                    n_draft = spec_draft_layers or max(
                        1, self.cfg.n_layers // 2
                    )
                    self._draft = ModelDraft.from_target(
                        self.params, self.cfg, n_layers=n_draft,
                        max_context=self.max_seq,
                    )
                elif spec_draft.startswith("zoo:"):
                    # trained small-model draft shaped by the planner
                    # MODEL_ZOO; KUBEDL_SPEC_DRAFT_CKPT restores weights
                    # saved after distillation (fresh weights propose
                    # noise — harmless, just zero acceptance)
                    from kubedl_tpu.serving.speculative import ModelDraft

                    ckpt = os.environ.get("KUBEDL_SPEC_DRAFT_CKPT", "")
                    self._draft = ModelDraft.from_zoo(
                        spec_draft.split(":", 1)[1], self.cfg,
                        ckpt_path=ckpt or None,
                        max_context=self.max_seq,
                    )
                else:
                    self._draft = make_draft(spec_draft)
                self._spec_stats = SpecStats()
                self._verify = jax.jit(
                    lambda p, c, t, l, st: llama.paged_verify(
                        p, c, t, l, st, self.cfg,
                        kv_attention=self.kv_attention,
                    ),
                    donate_argnums=(1,),
                )
                #: multi-candidate scorer: READ-ONLY (cache NOT donated
                #: and not returned, so XLA drops every cache write) —
                #: the winner goes back through the standard _verify
                self._verify_multi = jax.jit(
                    lambda p, c, t, l, st: llama.paged_verify_multi(
                        p, c, t, l, st, self.cfg,
                        kv_attention=self.kv_attention,
                    ),
                ) if self.spec_candidates > 1 else None
                #: tree scorer: like _verify_multi, READ-ONLY over the
                #: trie layout; the walked winner goes back through the
                #: standard write-path _verify. Fixed node budget
                #: 1 + N*k -> one compile.
                self._spec_tree_m = 1 + self.spec_candidates * self.spec_k
                self._verify_tree = jax.jit(
                    lambda p, c, t, pos, m, l, st: llama.paged_verify_tree(
                        p, c, t, pos, m, l, st, self.cfg,
                        kv_attention=self.kv_attention,
                    ),
                ) if self.spec_tree else None
            else:
                self._draft = None
                self._spec_stats = None
                self._verify_multi = None
                self._verify_tree = None
        else:
            self._cache = llama.init_batched_cache(
                self.cfg, self.max_batch, self.max_seq
            )
            self._draft = None
            self._spec_stats = None
            self._verify_multi = None
            self._verify_tree = None
        from collections import deque as _deque

        self._slots: list = [None] * self.max_batch
        # deque: admission pops the HEAD (popleft) and shedding peeks head
        # age on every generate() — a plain list made both O(n) in queue
        # depth, which showed up in the scheduler microbench under bursts
        self._waiting: "_deque[_Slot]" = _deque()
        self._cv = threading.Condition()
        #: parked prefill handoffs: id -> {blocks (increfed), pos, meta}.
        #: The handoff holds its OWN block references across the transfer
        #: window — the row frees normally, a fetch (or TTL GC / failure)
        #: decrefs, so conservation holds whatever the transfer does.
        self._handoffs: Dict[str, Dict] = {}
        #: export requests serviced by the scheduler thread (it alone may
        #: touch the donated device cache): (handoff_id, reply box, event)
        self._export_q: "_deque[tuple]" = _deque()
        #: device-resident prefix KV cache (docs/serving.md "Prefix
        #: cache"): admission grafts the longest cached prefix into the
        #: row and prefills only the suffix. 0 MB disables it.
        from kubedl_tpu.serving.prefix_cache import PrefixCache

        #: paged entries hold block REFERENCES, so eviction must give the
        #: refs back to the allocator (the engine callback frees them)
        _on_evict = self._paged_entry_evicted if self._paged else None
        self._pcache: Optional[PrefixCache] = (
            PrefixCache(int(prefix_cache_mb * 1e6), min_len=prefix_min_len,
                        on_evict=_on_evict)
            if prefix_cache_mb > 0 else None
        )
        self._prefix_evictions_seen = 0  # metric delta vs pcache stats
        self._stop = False
        #: graceful drain (docs/serving.md "Router"): once set, NEW
        #: requests are rejected with a distinguishable 503 while every
        #: already-admitted/queued request still runs to completion
        self._draining = False
        #: request_id -> slot for requests that opted into cancellation
        #: (the router's hedge-loser path)
        self._requests: Dict[str, _Slot] = {}
        #: jitted multi-step decode segments keyed by (n_steps, greedy)
        #: + the PRNG chain for on-device sampling — llama.decode_segment
        self._segments: Dict[tuple, object] = {}
        self._key = jax.random.PRNGKey(0)
        #: device-chained feed between segments: (prefill_gen, rows,
        #: last-token device array) where ``rows`` are the rows whose
        #: device token is current. Segment outputs cover the segment's
        #: rows; an interleaved prefill MERGES its sampled first tokens in
        #: (per-row validity) instead of invalidating the whole chain, so
        #: the next segment's input tokens never leave the device even
        #: across admissions.
        self._chain: Optional[tuple] = None
        self._prefill_gen = 0
        #: device copy of the per-row temperatures, re-uploaded only when
        #: they actually change
        self._temps_cache: Optional[tuple] = None
        #: the deferred in-flight decode segment (double buffering):
        #: {"toks": [B, k] device array, "sched": [(row, slot, take)]}.
        #: Dispatched one tick, harvested the next — the device_get and
        #: all host bookkeeping behind it overlap the NEXT segment's
        #: device compute instead of idling the chip between segments.
        self._pending: Optional[Dict] = None
        self._stats = {"requests": 0, "tokens_out": 0, "tokens_in": 0,
                       "shed": 0, "drain_rejects": 0,
                       "kv_preemptions": 0, "kv_sheds": 0,
                       "handoffs_out": 0, "handoffs_in": 0,
                       "handoff_failures": 0,
                       "started_at": time.time()}
        #: load-shedding budget: reject (503) instead of queueing once the
        #: queue is deeper than max_queue_depth or its head has waited
        #: longer than max_queue_age_s (the queue is not draining)
        self.max_queue_depth = max(1, int(max_queue_depth))
        self.max_queue_age_s = float(max_queue_age_s)
        from collections import deque

        from kubedl_tpu.observability.metrics import ServingMetrics

        self.metrics = metrics or ServingMetrics()
        #: per-tick pipeline accounting (sums + lifetime counters); the
        #: recent deque feeds median reporting in stats()/bench
        self._pipe = {
            "ticks": 0, "segments": 0, "deferred_harvests": 0,
            "flushes": 0, "chain_rebuilds": 0, "errors": 0, "inflight": 0,
            "dispatch_ms_sum": 0.0, "harvest_ms_sum": 0.0,
            "host_ms_sum": 0.0, "tick_ms_sum": 0.0, "overlap_ms_sum": 0.0,
        }
        self._pipe_recent: "deque[tuple]" = deque(maxlen=2048)
        #: completion timestamps for windowed QPS (autoscale signal must
        #: track LIVE load, not a lifetime average)
        self._recent: "deque[float]" = deque(maxlen=100_000)
        #: shed timestamps, same window: the autoscaler folds recent sheds
        #: into its backlog signal (rejected demand is still demand)
        self._shed_recent: "deque[float]" = deque(maxlen=100_000)
        #: per-request time-to-first-token samples (ms) for p50/p95
        self._ttft_recent: "deque[float]" = deque(maxlen=4096)
        #: per-request admission queue wait (enqueue -> admission), ms —
        #: the half of TTFT chunked prefill is built to shrink, so it
        #: gets its own p50/p95 in stats() and the Poisson bench arm
        self._queue_wait_recent: "deque[float]" = deque(maxlen=4096)
        self.qps_window_s = 60.0
        self._warmup()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="decode-scheduler"
        )
        self._thread.start()

    def _build_params(self, ckpt_dir: str, require_ckpt: bool = False):
        """Build one servable parameter tree end to end: init → checkpoint
        restore → optional int8 quantization → mesh sharding. The whole
        pipeline runs OFF the dispatch path (init time or a hot-swap
        load), and nothing is committed anywhere until it returns — a
        failure at any stage leaves every already-serving version
        untouched, never a torn tree. The ``serving.weight_swap`` chaos
        site fires at the top so injected corrupt-artifact / mid-swap
        crashes exercise exactly that contract.

        ``require_ckpt`` (hot-swap loads): a version whose artifact is
        missing or torn beyond recovery must FAIL the load — serving
        freshly initialized random weights under a version id would be a
        silent model swap. Init keeps the permissive behaviour (tests and
        cold starts serve the preset without a checkpoint)."""
        from kubedl_tpu.training import checkpoint

        llama, jax = self._llama, self._jax
        chaos.check("serving.weight_swap")
        params = llama.llama_init(jax.random.PRNGKey(0), self.cfg)
        step = checkpoint.latest_step(ckpt_dir) if ckpt_dir else None
        if require_ckpt and step is None:
            raise ValueError(f"no checkpoint found under {ckpt_dir!r}")
        if ckpt_dir and step is not None:
            state = checkpoint.restore_checkpoint(ckpt_dir, {"params": params})
            if state is not None:
                params = state["params"]
                log.info("restored checkpoint from %s", ckpt_dir)
            elif require_ckpt:
                raise ValueError(
                    f"no complete checkpoint step under {ckpt_dir!r} "
                    "(every step torn/incomplete)"
                )
        if self.quantize == "int8":
            # weight-only int8: decode is HBM-bound and weights dominate
            # the bytes — halves the per-token floor (docs/serving.md)
            params = llama.quantize_params(params, self.cfg)
            log.info("serving with int8 weight-only quantization")
        if self.mesh is not None:
            params = llama.shard_serving_params(params, self.cfg, self.mesh)
        return params

    # -- versioned weights / hot swap (docs/serving.md "Model lifecycle") --

    def load_version(self, version: str, ckpt_dir: str) -> None:
        """Load a second (third, …) parameter tree alongside the serving
        ones with ZERO downtime: the build runs entirely off to the side
        on the caller's thread, and only a fully restored/quantized/
        sharded tree is committed under the lock. A failed load (torn
        artifact, injected ``serving.weight_swap`` crash) raises and the
        already-loaded versions keep serving — there is no intermediate
        state a request could observe. Idempotent for an already-loaded
        version."""
        if not self._paged:
            raise ValueError(
                "weight hot-swap requires kv_layout='paged' (per-row "
                "block isolation is what lets rows of the other version "
                "sit a dispatch out safely)"
            )
        version = str(version)
        if not version:
            raise ValueError("model version id must be non-empty")
        with self._cv:
            if version in self._retiring:
                raise ValueError(
                    f"version {version!r} is retiring; wait for eviction "
                    "before reloading it"
                )
            if version in self._versions:
                return
        params = self._build_params(ckpt_dir, require_ckpt=True)
        with self._cv:
            self._versions[version] = params
        log.info("hot-loaded model version %r from %s", version, ckpt_dir)

    def activate_version(self, version: str) -> str:
        """Make a loaded version the DEFAULT for requests that name no
        version (the rollback/promotion flip is the router's weight
        change; this is the engine-local equivalent). Returns the
        previous default."""
        version = str(version)
        with self._cv:
            if version not in self._versions or version in self._retiring:
                raise UnknownModelVersion(
                    f"cannot activate {version!r} "
                    f"(loaded: {sorted(self._versions)})"
                )
            prev, self._default_version = self._default_version, version
            self.params = self._versions[version]
        log.info("activated model version %r (was %r)", version, prev)
        return prev

    def retire_version(self, version: str) -> bool:
        """Fence a version from NEW requests and evict its tree once the
        last in-flight row referencing it drains — never mid-flight: a
        row dispatching on the tree keeps it alive. The default version
        cannot retire (activate another first). Returns False for a
        version that was never loaded."""
        version = str(version)
        with self._cv:
            if version == self._default_version:
                raise ValueError(
                    f"cannot retire the default version {version!r}; "
                    "activate another version first"
                )
            if version not in self._versions:
                return False
            self._retiring.add(version)
            self._maybe_evict_versions_locked()
        return True

    def versions(self) -> Dict:
        """Live version inventory (feeds /v1/models, stats(), and the
        rollout drive's torn-state assertions)."""
        with self._cv:
            rows: Dict[str, int] = {}
            for s in self._slots:
                if s is not None:
                    v = s.version or self._default_version
                    rows[v] = rows.get(v, 0) + 1
            return {
                "default": self._default_version,
                "loaded": sorted(self._versions),
                "retiring": sorted(self._retiring),
                "active_rows": rows,
            }

    def _resolve_version_locked(self, requested: str) -> str:
        """Admission-gate resolution: "" → the default; anything else
        must be a loaded, non-retiring version. Caller holds cv."""
        v = str(requested or "") or self._default_version
        if v not in self._versions or v in self._retiring:
            raise UnknownModelVersion(
                f"unknown or retiring model version {v!r} "
                f"(loaded: {sorted(set(self._versions) - self._retiring)})"
            )
        return v

    def _version_refs_locked(self, version: str) -> int:
        n = sum(
            1 for s in self._slots
            if s is not None and (s.version or self._default_version) == version
        )
        n += sum(
            1 for s in self._waiting
            if (s.version or self._default_version) == version
        )
        return n

    def _maybe_evict_versions_locked(self) -> None:
        """Drop retiring trees whose last referencing row/queue entry is
        gone (drain-then-evict). Hooked into _admit_locked so every
        admission pass — which follows every row free — re-checks.
        Caller holds cv."""
        for v in list(self._retiring):
            if v == self._default_version:
                continue
            if self._version_refs_locked(v) == 0:
                self._versions.pop(v, None)
                self._retiring.discard(v)
                log.info("evicted retired model version %r", v)

    def _pick_tick_version_locked(self, active) -> str:
        """One version per scheduler tick: dispatch (prefill group,
        decode segment, spec round) never mixes parameter trees. With
        versions co-resident the tick alternates round-robin over those
        with live rows — rows of the others sit the tick out, which is
        safe in paged mode because the host pos/bt mirrors are
        authoritative (re-uploaded before every dispatch, so the skipped
        steps never happened for them). Caller holds cv."""
        vers = sorted({
            (s.version or self._default_version)
            for s in active if s is not None
        })
        if not vers:
            return self._default_version
        if len(vers) == 1:
            return vers[0]
        self._vers_rr = (self._vers_rr + 1) % len(vers)
        return vers[self._vers_rr]

    def _warmup(self) -> None:
        import jax.numpy as jnp

        # cache is donated — reassign, the old buffer is dead after the call
        logits, self._cache = self._decode(
            self.params, self._cache,
            jnp.zeros((self.max_batch, 1), jnp.int32),
        )
        self._jax.block_until_ready(logits)

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=5)

    # -- graceful drain ----------------------------------------------------

    def drain(self, wait: bool = False, timeout_s: float = 30.0) -> bool:
        """Stop ADMISSION, not work: new requests get a 503 whose reason
        is "draining" (vs the shed path's "overloaded" — the router fails
        those over immediately instead of backing off), while every
        queued/in-flight request still runs to completion. The graceful
        half of shutdown that `close()` alone never had — `close()`
        hard-joins with a 5 s timeout and strands in-flight rows."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        if wait:
            return self.wait_drained(timeout_s)
        return True

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        """Block until no request is queued, resident in a row, or in
        flight on device (then `close()` severs nothing). True on idle."""
        deadline = time.perf_counter() + timeout_s
        while True:
            with self._cv:
                idle = (
                    not self._waiting
                    and self._pending is None
                    and all(s is None for s in self._slots)
                )
            if idle:
                return True
            if time.perf_counter() >= deadline:
                return False
            time.sleep(0.01)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- request path ------------------------------------------------------

    def cancel(self, request_id: str) -> bool:
        """Cancel a request by id (the router's hedge-loser path): a
        queued request leaves the admission queue, an in-flight one has
        its row vacated (same mechanics as the generate() timeout path —
        prefix pin released, stale device work masked by the harvest's
        identity check). The waiter wakes with a ``cancelled`` result.
        Returns False for unknown/already-finished ids."""
        with self._cv:
            slot = self._requests.pop(request_id, None)
            if slot is None or slot.done.is_set():
                return False
            try:
                self._waiting.remove(slot)
            except ValueError:
                pass
            for i, s in enumerate(self._slots):
                if s is slot:
                    self._slots[i] = None
                    self._free_row_locked(i)
            self._release_prefix_locked(slot)
            slot.result = {"error": "cancelled", "cancelled": True}
            slot.done.set()
            self._cv.notify_all()
        return True

    # -- distributed tracing (docs/observability.md) -----------------------

    @staticmethod
    def _arm_trace(slot: _Slot, trace: Optional[TraceContext],
                   debug_trace: bool = False) -> None:
        """Give a slot span identity: the caller sent a context, or asked
        for a flight recording without one (mint a fresh trace so the
        recording still has a root). Disarmed tracer: stays a no-op —
        every scheduler-side record guards on ``slot.span_id``."""
        if not TRACER.enabled:
            return
        if trace is None and debug_trace:
            trace = TraceContext(new_trace_id(), "")
        if trace is not None:
            slot.trace = trace
            slot.span_id = new_span_id()

    def _trace_admitted_locked(self, s: _Slot, t_adm: float,
                               row: int) -> None:
        """Record queue wait (enqueue → admission start) — the stats()
        percentile sample and metric for EVERY admission, plus the
        engine.queue_wait/engine.admission spans when the request is
        traced. Caller holds cv. Chunked admission changes nothing
        here: a request is admitted once (the wait ends when its row is
        assigned), however many prefill chunks follow."""
        wait_ms = (t_adm - s.t0) * 1e3
        self._queue_wait_recent.append(wait_ms)
        self.metrics.queue_wait_ms.observe(wait_ms)
        if not s.span_id:
            return
        now = time.perf_counter()
        TRACER.record("engine.queue_wait", start=s.t0,
                      duration=t_adm - s.t0, trace=s.trace,
                      parent_id=s.span_id)
        TRACER.record("engine.admission", start=t_adm,
                      duration=now - t_adm, trace=s.trace,
                      parent_id=s.span_id, row=row)

    def _trace_request_locked(self, s: _Slot, kind: str) -> None:
        """Close the request span (id pre-minted at arm time) BEFORE the
        waiter wakes, so a flight-recorder read right after done.wait()
        already sees the whole tree. Caller holds cv."""
        if s.span_id:
            TRACER.record("engine.request", start=s.t0,
                          duration=time.perf_counter() - s.t0,
                          trace=s.trace, span_id=s.span_id, kind=kind,
                          tokens=len(s.out_ids))

    @staticmethod
    def _trace_result(slot: _Slot, result: Dict,
                      debug_trace: bool) -> Dict:
        """Stamp the trace id on a finished result; with the flight
        recorder armed, attach the request's own span tree inline."""
        if slot.span_id and slot.trace is not None:
            tid = slot.trace.trace_id
            result.setdefault("trace_id", tid)
            if debug_trace:
                result["trace"] = {
                    "trace_id": tid,
                    "spans": TRACER.span_tree(tid),
                }
        return result

    def generate(self, prompt_ids, max_tokens: int = 16,
                 temperature: float = 0.0, timeout_s: float = 600.0,
                 cache_prefix: bool = False, request_id: str = "",
                 trace: Optional[TraceContext] = None,
                 debug_trace: bool = False,
                 model_version: str = "") -> Dict:
        budget = self.max_seq - 1
        prompt = [int(t) for t in list(prompt_ids)[:budget]]
        if not prompt:
            prompt = [0]
        max_tokens = max(0, min(int(max_tokens), budget - len(prompt)))
        slot = _Slot(prompt, max_tokens, float(temperature), cache_prefix,
                     request_id=request_id)
        self._arm_trace(slot, trace, debug_trace)
        with self._cv:
            slot.version = self._resolve_version_locked(model_version)
            if self._draining:
                self._stats["drain_rejects"] += 1
                raise EngineOverloaded(
                    "engine is draining", retry_after_s=1.0,
                    reason="draining",
                )
            depth = len(self._waiting)
            head_age = (
                time.perf_counter() - self._waiting[0].t0 if self._waiting else 0.0
            )
            if depth >= self.max_queue_depth or head_age > self.max_queue_age_s:
                # shed instead of queueing: an over-budget queue serves
                # nobody well — tell the client when to come back and let
                # the autoscaler see the rejected demand as backlog
                self._stats["shed"] += 1
                self._shed_recent.append(time.time())
                self.metrics.shed_requests.inc()
                retry = max(1.0, min(self.max_queue_age_s, 0.25 * depth))
                raise EngineOverloaded(
                    f"queue depth {depth} (budget {self.max_queue_depth}), "
                    f"head age {head_age:.1f}s (budget {self.max_queue_age_s}s)",
                    retry_after_s=retry,
                )
            if self._paged and not self._alloc.admission_open():
                # KV-pool pressure sheds too: below the low watermark a
                # queued request cannot be admitted anyway, so reject at
                # the door (hysteresis reopens at the high watermark)
                self._stats["shed"] += 1
                self._stats["kv_sheds"] += 1
                self._shed_recent.append(time.time())
                self.metrics.shed_requests.inc()
                self.metrics.kv_block_sheds.inc()
                raise EngineOverloaded(
                    f"free KV blocks {self._alloc.free_count}/"
                    f"{self._alloc.total} below low watermark",
                    retry_after_s=1.0,
                )
            self._waiting.append(slot)
            if request_id:
                self._requests[request_id] = slot
            self._cv.notify_all()
        if not slot.done.wait(timeout=timeout_s):
            # free the row/queue entry: an abandoned request must not keep
            # occupying a batch slot (and decode work) under overload
            with self._cv:
                if slot in self._waiting:
                    self._waiting.remove(slot)
                for i, s in enumerate(self._slots):
                    if s is slot:
                        self._slots[i] = None
                        self._free_row_locked(i)
                # a vacated row must not keep its prefix-cache entry
                # pinned forever — the pin would block eviction for good
                self._release_prefix_locked(slot)
        result = slot.result or {"error": "timed out", "timed_out": True}
        with self._cv:
            if request_id:
                self._requests.pop(request_id, None)
            self._stats["requests"] += 1
            self._stats["tokens_in"] += len(prompt)
            self._stats["tokens_out"] += len(result.get("token_ids", []))
            self._recent.append(time.time())
        return self._trace_result(slot, result, debug_trace)

    def stats(self) -> Dict:
        """Live serving counters (feeds autoscaling signals + /v1/stats).

        One snapshot under ONE cv acquisition: the old code re-took the
        lock three times, so counters, the qps window, and the queue
        depth could describe three different moments of a moving engine.
        Derived values are computed outside the lock from the snapshot."""
        now = time.time()
        with self._cv:
            out = dict(self._stats)
            recent = sum(1 for t in self._recent if t > now - self.qps_window_s)
            shed_recent = sum(
                1 for t in self._shed_recent if t > now - self.qps_window_s
            )
            queued = len(self._waiting)
            active = sum(1 for s in self._slots if s is not None)
            ttft = list(self._ttft_recent)
            qwait = list(self._queue_wait_recent)
            draining = self._draining
            parked_handoffs = len(self._handoffs)
        up = max(now - out["started_at"], 1e-9)
        out["role"] = self.role
        out["handoffs_parked"] = parked_handoffs
        # surfaced so both the router (stop picking this replica, don't
        # count its rejections as overload) and the autoscaler see drain
        out["draining"] = draining
        out["uptime_s"] = round(up, 1)
        # windowed rate over min(window, uptime): a fresh engine under a
        # burst reports the burst, a long-idle engine reports ~0
        span = min(self.qps_window_s, up)
        out["qps"] = round(recent / max(span, 1e-9), 3)
        out["lifetime_qps"] = round(out["requests"] / up, 3)
        out["active_slots"] = active
        out["max_batch"] = self.max_batch
        out["queued"] = queued
        out["shed_recent"] = shed_recent
        if ttft:
            srt = sorted(ttft)
            out["ttft_ms_p50"] = round(srt[len(srt) // 2], 3)
            out["ttft_ms_p95"] = round(
                srt[min(len(srt) - 1, int(len(srt) * 0.95))], 3
            )
        if qwait:
            srt = sorted(qwait)
            out["queue_wait_ms_p50"] = round(srt[len(srt) // 2], 3)
            out["queue_wait_ms_p95"] = round(
                srt[min(len(srt) - 1, int(len(srt) * 0.95))], 3
            )
        if self._pcache is not None:
            out["prefix_cache"] = self._pcache.stats()
            # block-aware affinity advertisement: digests of the cached
            # prefixes this replica already holds blocks for, in the same
            # hash the router's ring keys on — the prober folds these into
            # an advertised-prefix map and steers repeats here
            from kubedl_tpu.serving.router_policy import prefix_digest

            plen = self.advertise_prefix_len
            adv = set()
            for key in self._pcache.prefix_keys():
                d = prefix_digest(key, plen)
                if d is not None:
                    adv.add(d)
            out["prefix_cache"]["advertised"] = sorted(adv)
        if self._paged:
            out["kv_blocks"] = self._alloc.stats()
            out["kv_blocks"]["attention_kernel"] = self.kv_attention
            out["kv_blocks"]["role"] = self.role
        if self._spec_stats is not None:
            out["speculative"] = self._spec_stats.snapshot()
            out["speculative"]["draft_kind"] = getattr(
                self._draft, "name", self.spec_draft
            )
            out["speculative"]["candidates"] = self.spec_candidates
        out["pipeline"] = self.pipeline_stats()
        out["versions"] = self.versions()
        return out

    def pipeline_stats(self) -> Dict:
        """Decode-pipeline accounting: per-tick dispatch/harvest/host
        timings (avg + p50 over the recent window), overlap ratio, and
        lifetime segment/harvest counters. Feeds `/v1/stats`, the
        Prometheus family (`observability.metrics.ServingMetrics`), and
        bench.py's serving_engine medians."""
        import statistics

        with self._cv:
            p = dict(self._pipe)
            recent = list(self._pipe_recent)
            queued = len(self._waiting)
        out = {
            "ticks": p["ticks"],
            "segments": p["segments"],
            "deferred_harvests": p["deferred_harvests"],
            "flushes": p["flushes"],
            "chain_rebuilds": p["chain_rebuilds"],
            "errors": p["errors"],
            "inflight": p["inflight"],
            "queued": queued,
        }
        if p["ticks"]:
            n = p["ticks"]
            out["dispatch_ms_avg"] = round(p["dispatch_ms_sum"] / n, 4)
            out["harvest_ms_avg"] = round(p["harvest_ms_sum"] / n, 4)
            out["host_ms_avg"] = round(p["host_ms_sum"] / n, 4)
            out["tick_ms_avg"] = round(p["tick_ms_sum"] / n, 4)
            out["overlap_ratio"] = round(
                p["overlap_ms_sum"] / max(p["tick_ms_sum"], 1e-9), 4
            )
        if recent:
            med = statistics.median
            out["dispatch_ms_p50"] = round(med([r[0] for r in recent]), 4)
            out["harvest_ms_p50"] = round(med([r[1] for r in recent]), 4)
            out["host_ms_p50"] = round(med([r[2] for r in recent]), 4)
            out["tick_ms_p50"] = round(med([r[3] for r in recent]), 4)
        return out

    # -- scheduler loop ----------------------------------------------------

    def _release_prefix_locked(self, slot: _Slot) -> None:
        """Drop a slot's pin on its grafted prefix entry (finalize /
        vacation / error recovery). Idempotent; caller holds cv."""
        if slot.pinned is not None and self._pcache is not None:
            self._pcache.unpin(slot.pinned)
        slot.pinned = None

    def _maybe_insert_prefix_locked(self, i: int, s: _Slot) -> None:
        """After row ``i``'s prefill completes, store its prompt prefix
        when traffic says it is shared (observation trie: >= min_seen
        requests walked it) or the request tagged itself cacheable.
        Extraction is an async device copy dispatched BEFORE any later
        graft into the same row, so the copied span is this prefill's
        output even if the row turns over immediately. Caller holds cv."""
        if self._pcache is None:
            return
        cand = self._pcache.insert_candidate(s.prompt, s.cache_prefix)
        # cap at len-1: a full-prompt entry can never match (the engine
        # always needs >= 1 suffix token for last-token logits), while
        # len-1 serves exact-repeat traffic too
        cand = min(cand, len(s.prompt) - 1)
        if cand <= s.cached_len or cand < self._pcache.min_len:
            return  # nothing new beyond what the matched entry covers
        if self._paged:
            # paged insert is (almost) free: the entry SHARES the row's
            # full prefix blocks by reference (incref), and only the
            # partial tail block is device-copied — the row keeps
            # appending inside its own tail, so the entry needs a
            # frozen copy (the insert-side half of copy-on-write)
            bs = self.kv_block_size
            full = cand // bs
            row_blocks = self._row_blocks[i]
            blocks = list(row_blocks[:full])
            if cand % bs:
                got = self._alloc.alloc(1)
                if got is None:
                    return  # pool pressure: skip the insert
                self._cache = self._copy_block(
                    self._cache, row_blocks[full], got[0]
                )
                blocks.append(got[0])
            self._alloc.incref(blocks[:full])
            ok = self._pcache.insert(
                s.prompt[:cand], None, None, cand,
                blocks=tuple(blocks),
                nbytes=len(blocks) * self._block_bytes,
            )
            if not ok:
                self._alloc.free(blocks)  # duplicate/over-budget: undo
                return
        else:
            k, v = self._extract(self._cache, i, self._prefill_bucket(cand))
            if not self._pcache.insert(s.prompt[:cand], k, v, cand):
                return
        st = self._pcache.stats()
        m = self.metrics
        m.prefix_inserts.inc()
        m.prefix_bytes.set(float(st["bytes"]))
        m.prefix_entries.set(float(st["entries"]))
        ev = st["evictions"] - self._prefix_evictions_seen
        if ev > 0:
            m.prefix_evictions.inc(ev)
        self._prefix_evictions_seen = st["evictions"]

    # -- paged KV bookkeeping (host mirrors + block lifecycle) -------------

    def _upload_mirror(self, arr):
        """Upload a host mirror as an XLA-OWNED device buffer.

        ``jnp.asarray`` zero-copy BORROWS an aligned numpy buffer, and the
        engine donates the cache into every jitted dispatch — donating a
        borrowed buffer lets XLA alias segment outputs onto it, which
        either scribbles sampled tokens into the live mirror or hands the
        harvest a stale view of the block table (both observed on the CPU
        backend; whether a given numpy allocation is 64-byte aligned is
        luck, hence flaky). The no-op add forces materialization into a
        fresh buffer XLA owns outright."""
        return self._jax.numpy.asarray(arr) + 0

    def _free_row_locked(self, i: int) -> None:
        """Return row ``i``'s blocks to the pool and point its table rows
        at the trash block. Any still-in-flight dispatch keeps writing
        through its own bt SNAPSHOT, but the device executes enqueued
        calls in order, so a later owner's writes always land last.
        Caller holds cv; no-op in contiguous mode."""
        if not self._paged:
            return
        blocks = self._row_blocks[i]
        if blocks:
            self._alloc.free(blocks)
        self._row_blocks[i] = []
        self._bt_host[i, :] = 0
        self._pos_host[i] = 0

    def _reserve_locked(self, i: int, n_tokens: int) -> bool:
        """Grow row ``i``'s block list to cover ``n_tokens`` cached
        positions (all-or-nothing). Caller holds cv."""
        need = self._alloc.blocks_for(min(int(n_tokens), self.max_seq))
        blocks = self._row_blocks[i]
        if need <= len(blocks):
            return True
        got = self._alloc.alloc(need - len(blocks))
        if got is None:
            return False
        self._bt_host[i, len(blocks):need] = got
        blocks.extend(got)
        return True

    def _trim_row_locked(self, i: int, n_tokens: int) -> None:
        """Free row blocks beyond what ``n_tokens`` cached positions need
        — how a rejected speculative suffix's KV is freed IN PLACE (its
        positions are beyond the rolled-back pos mirror)."""
        keep = self._alloc.blocks_for(min(int(n_tokens), self.max_seq))
        blocks = self._row_blocks[i]
        if len(blocks) <= keep:
            return
        drop = blocks[keep:]
        del blocks[keep:]
        self._bt_host[i, keep:keep + len(drop)] = 0
        self._alloc.free(drop)

    def _paged_entry_evicted(self, entry) -> None:
        """PrefixCache eviction callback: hand the entry's block
        references back to the allocator. Runs under the pcache lock and
        touches only the allocator (its own lock) — never cv."""
        blocks = getattr(entry, "blocks", None)
        if blocks:
            self._alloc.free(blocks)

    def _reclaim_prefix_locked(self) -> bool:
        """Evict unpinned prefix-cache entries to recover at least one
        block; True when anything came back. The cheapest relief valve —
        cache entries are an optimization, resident rows are work."""
        if self._pcache is None or not self._paged:
            return False
        return self._pcache.reclaim(self._block_bytes) > 0

    def _pick_victim_locked(self, held) -> Optional[int]:
        """Pick the preemption victim: the YOUNGEST resident row (latest
        arrival — least sunk decode work) that is not in ``held`` and has
        nothing in flight (``pending`` rows owe tokens to the deferred
        harvest's count-based accounting)."""
        best = None
        for j, s in enumerate(self._slots):
            if s is None or j in held or s.pending or not self._row_blocks[j]:
                continue
            if best is None or s.t0 > self._slots[best].t0:
                best = j
        return best

    def _preempt_locked(self, j: int) -> None:
        """Preempt-and-requeue row ``j`` under block exhaustion: free its
        blocks, reset the slot to its pre-admission state, and put it at
        the FRONT of the queue (it was admitted first — it re-admits
        first once blocks free up). Greedy requests regenerate the exact
        same tokens from prefill, so preemption never changes output."""
        s = self._slots[j]
        self._slots[j] = None
        self._free_row_locked(j)
        self._release_prefix_locked(s)
        s.fed = 0
        s.cached_len = 0
        s.out_ids = []
        s.pending = 0
        self._waiting.appendleft(s)
        self._stats["kv_preemptions"] += 1
        self.metrics.kv_preemptions.inc()
        log.warning("KV blocks exhausted: preempted row %d (requeued)", j)

    def _reserve_decode_locked(self, decoding, steps: int):
        """Ensure every decoding row can cache ``steps`` more positions,
        preempting victims when the pool runs dry (chaos site
        ``serving.kv_alloc`` injects the failure). Rows that still cannot
        grow sit this dispatch out and retry next tick. Caller holds cv;
        returns the surviving rows."""
        out = []
        inject = chaos.should_fail("serving.kv_alloc")
        for i, s in decoding:
            if self._slots[i] is not s:
                continue  # preempted earlier in this very loop
            need = min(int(self._pos_host[i]) + steps, self.max_seq)
            while True:
                if not inject and self._reserve_locked(i, need):
                    out.append((i, s))
                    break
                inject = False  # one injected failure exercises the path
                if self._reclaim_prefix_locked():
                    continue
                victim = self._pick_victim_locked({i} | {j for j, _ in out})
                if victim is None:
                    break
                self._preempt_locked(victim)
        return out

    def _admit_row_paged_locked(self, i: int, slot: _Slot) -> bool:
        """Admit ``slot`` into row ``i`` under the block allocator: match
        the prefix cache, SHARE the entry's full blocks by reference
        (incref — no device copy at all), copy-on-write its partial tail
        block, and allocate fresh blocks for the suffix. All-or-nothing:
        on pool exhaustion every side effect is rolled back and the slot
        stays queued. Caller holds cv."""
        import jax.numpy as jnp

        a = self._alloc
        bs = self.kv_block_size
        need_total = a.blocks_for(min(len(slot.prompt) + 1, self.max_seq))
        entry, mlen = None, 0
        if self._pcache is not None:
            self._pcache.observe(slot.prompt)
            entry, mlen = self._pcache.match(slot.prompt)
        entry_blocks = (
            getattr(entry, "blocks", None) if entry is not None else None
        )
        shared: list = []
        tail_src = None
        if entry_blocks:
            full = mlen // bs
            shared = list(entry_blocks[:full])
            if mlen % bs:
                tail_src = entry_blocks[full]
        n_alloc = need_total - len(shared)
        t_alloc = time.perf_counter()
        got = a.alloc(n_alloc)
        if got is None and self._reclaim_prefix_locked():
            got = a.alloc(n_alloc)
        if got is None:
            if entry is not None:
                self._pcache.unpin(entry)
            return False
        a.incref(shared)
        blocks = list(shared)
        if tail_src is not None:
            # copy-on-write: the entry's partial tail block is SHARED and
            # this row's suffix prefill appends inside it — copy before
            # any divergent write can land
            tail_copy = got.pop(0)
            self._cache = self._copy_block(self._cache, tail_src, tail_copy)
            blocks.append(tail_copy)
        blocks.extend(got)
        self._row_blocks[i] = blocks
        self._bt_host[i, :] = 0
        self._bt_host[i, :len(blocks)] = blocks
        self._pos_host[i] = mlen
        self._slots[i] = slot
        if slot.span_id:
            TRACER.record("engine.kv_alloc", start=t_alloc,
                          duration=time.perf_counter() - t_alloc,
                          trace=slot.trace, parent_id=slot.span_id,
                          blocks=len(blocks), shared=len(shared))
        if entry is None:
            if self._pcache is not None:
                self.metrics.prefix_misses.inc()
            return True
        self.metrics.prefix_hits.inc()
        slot.cached_len = mlen
        slot.pinned = entry
        if not entry_blocks:
            # array-payload entry (direct insert): scatter its K/V into
            # the row's fresh blocks through the just-updated table
            self._cache["bt"] = self._upload_mirror(self._bt_host)
            self._cache = self._graft(self._cache, entry.k, entry.v, i, mlen)
        return True

    def _admit_locked(self) -> None:
        # retiring versions evict here: every row free is followed by an
        # admission pass, so "last in-flight row drains" is observed at
        # the next admission opportunity
        self._maybe_evict_versions_locked()
        for i in range(self.max_batch):
            if self._slots[i] is None and self._waiting:
                if self._paged:
                    if not self._alloc.admission_open():
                        break  # below low watermark: hysteresis holds
                    head = self._waiting[0]
                    t_adm = time.perf_counter()
                    if head.adopt is not None:
                        r = self._admit_row_adopt_locked(i, head)
                        if r is None:
                            break  # pool dry: wait for frees
                        self._waiting.popleft()
                        if r:
                            self._trace_admitted_locked(head, t_adm, i)
                        continue  # r False: waiter already failed/woken
                    if not self._admit_row_paged_locked(i, head):
                        break  # pool dry: wait for frees / preemption
                    self._waiting.popleft()
                    self._trace_admitted_locked(head, t_adm, i)
                    continue
                slot = self._waiting.popleft()
                t_adm = time.perf_counter()
                self._slots[i] = slot
                self._trace_admitted_locked(slot, t_adm, i)
                # reset this row's position; stale KV is masked by pos
                self._cache["pos"] = self._cache["pos"].at[i].set(0)
                if self._pcache is None:
                    continue
                # prefix reuse: graft the longest cached prefix into the
                # row NOW (its K/V land in HBM, pos := prefix len) so the
                # prefill dispatch only consumes the suffix. Ordering is
                # safe: within a tick, prefill dispatch precedes decode
                # dispatch, and pos = prefix_len keeps decode writes out
                # of the grafted span.
                self._pcache.observe(slot.prompt)
                entry, mlen = self._pcache.match(slot.prompt)
                if entry is None:
                    self.metrics.prefix_misses.inc()
                    continue
                self.metrics.prefix_hits.inc()
                self._cache = self._graft(
                    self._cache, entry.k, entry.v, i, mlen
                )
                slot.cached_len = mlen
                slot.pinned = entry

    def _loop(self) -> None:
        while True:
            try:
                if self._loop_once():
                    return
            except Exception as e:  # the singleton scheduler must survive:
                # fail every in-flight request, keep serving new ones
                log.exception("decode scheduler step failed")
                with self._cv:
                    for i, s in enumerate(self._slots):
                        if s is not None:
                            s.result = {"error": str(e)}
                            self._slots[i] = None
                            self._release_prefix_locked(s)
                            s.done.set()
                    # the cache is DONATED to prefill/decode: a call that
                    # raised after donation leaves self._cache pointing at
                    # deleted buffers — rebuild or every later tick dies.
                    # The PRNG key and token chain are segment OUTPUTS
                    # too: a segment that failed after the assignment
                    # leaves them referencing poisoned buffers, which
                    # would wedge every later request — re-seed/clear.
                    if self._paged:
                        from kubedl_tpu.serving.kv_blocks import (
                            BlockAllocator,
                        )

                        self._cache = self._llama.init_paged_cache(
                            self.cfg, self.max_batch, self.max_seq,
                            self.kv_blocks, self.kv_block_size,
                        )
                        self._alloc = BlockAllocator(
                            self.kv_blocks, self.kv_block_size,
                            low_watermark=self._alloc.low_watermark,
                            high_watermark=self._alloc.high_watermark,
                        )
                        self._pos_host[:] = 0
                        self._bt_host[:] = 0
                        self._row_blocks = [
                            [] for _ in range(self.max_batch)
                        ]
                        if self._pcache is not None:
                            # every entry references the dead pool's
                            # blocks — drop them all (no evict callbacks:
                            # the allocator was just rebuilt)
                            self._pcache.clear()
                        # parked handoffs reference the dead pool too;
                        # fail any fetch waiting on them
                        self._handoffs.clear()
                        for _hid, box, ev in list(self._export_q):
                            box["error"] = (
                                "engine recovered from a scheduler error"
                            )
                            ev.set()
                        self._export_q.clear()
                    else:
                        self._cache = self._llama.init_batched_cache(
                            self.cfg, self.max_batch, self.max_seq
                        )
                    self._key = self._jax.random.PRNGKey(
                        int(time.time()) & 0x7FFFFFFF
                    )
                    self._reset_pipeline_locked()

    def _reset_pipeline_locked(self) -> None:
        """Drop every piece of pipeline state that may reference poisoned
        device buffers or failed slots. The deferred in-flight segment is
        POISONED too (its outputs chain from the donated cache the failed
        call consumed) — discard it UNHARVESTED; its slots were already
        failed above, so no tokens are owed. Latency/queue accounting is
        reset alongside (r5 stats()/error-path drift: the old handler
        left counters describing the crashed pipeline), so post-recovery
        stats describe the recovered engine. Caller holds cv."""
        self._chain = None
        self._temps_cache = None
        self._pending = None
        p = self._pipe
        p["errors"] += 1
        p["inflight"] = 0
        p["ticks"] = 0
        for k in ("dispatch_ms_sum", "harvest_ms_sum", "host_ms_sum",
                  "tick_ms_sum", "overlap_ms_sum"):
            p[k] = 0.0
        self._pipe_recent.clear()
        self.metrics.scheduler_errors.inc()
        self.metrics.queue_depth.set(float(len(self._waiting)))

    def _rem(self, s: _Slot) -> int:
        """Remaining token budget for a slot, counting tokens already
        DISPATCHED on device but not yet harvested (``s.pending``): the
        pipeline schedules purely from counts — values arrive a tick
        later."""
        done = len(s.out_ids) + s.pending
        return min(s.max_tokens - done,
                   (self.max_seq - 1) - (len(s.prompt) + done))

    def _maybe_finalize_locked(self, i: int, s: _Slot) -> None:
        """Completion is token-COUNT based (what lets the scheduler size
        decode segments without seeing token values). A slot with tokens
        still in flight on device can never finalize — its values arrive
        at the next harvest. Caller holds cv."""
        if s.pending:
            return
        if s.handoff is not None and s.fed >= len(s.prompt) and s.out_ids:
            # prefill-pool slot: instead of decoding, park the row's
            # blocks under a handoff id and hand the waiter the ticket
            self._finalize_handoff_locked(i, s)
            return
        if (
            len(s.out_ids) >= s.max_tokens
            or len(s.prompt) + len(s.out_ids) >= self.max_seq - 1
        ):
            ms = (time.perf_counter() - s.t0) * 1e3
            s.result = {
                "token_ids": s.out_ids,
                "prompt_len": len(s.prompt),
                "latency_ms": round(ms, 2),
                "tokens_per_sec": round(
                    len(s.out_ids) / (ms / 1e3), 2
                ) if ms > 0 else 0.0,
                "cached_prefix_len": s.cached_len,
                # which weight version actually served the request — the
                # rollout drive's no-version-mixing assertion reads this
                "model_version": s.version or self._default_version,
            }
            if s.ttft_ms is not None:
                s.result["ttft_ms"] = round(s.ttft_ms, 3)
            self._slots[i] = None
            self._free_row_locked(i)
            self._release_prefix_locked(s)
            self._trace_request_locked(
                s, "adopt" if s.adopt is not None else "generate"
            )
            s.done.set()

    # -- disaggregated prefill/decode (docs/serving.md) --------------------

    def _finalize_handoff_locked(self, i: int, s: _Slot) -> None:
        """Park row ``i``'s blocks under a fresh handoff id: the handoff
        takes its OWN reference on every block (incref) so the row can
        free normally — the blocks stay alive until a fetch exports them
        (or the TTL GC gives up on the transfer). Caller holds cv."""
        import uuid

        hid = uuid.uuid4().hex
        blocks = list(self._row_blocks[i])
        self._alloc.incref(blocks)
        self._handoffs[hid] = {
            "blocks": blocks,
            "pos": int(self._pos_host[i]),
            "prompt": list(s.prompt),
            "first_token": int(s.out_ids[0]),
            "max_tokens": int(s.handoff["max_tokens"]),
            "temperature": float(s.temperature),
            "cache_prefix": bool(s.cache_prefix),
            "request_id": s.request_id,
            "ttft_ms": s.ttft_ms,
            "trace": s.trace,
            "span_id": s.span_id,
            # the adopting decode engine must keep serving the SAME
            # weight version the prefill ran on — rides the KVHandoff
            # header so disagg legs never mix versions
            "model_version": s.version or self._default_version,
            "t": time.time(),
        }
        ms = (time.perf_counter() - s.t0) * 1e3
        s.result = {
            "handoff_id": hid,
            "first_token": int(s.out_ids[0]),
            "prompt_len": len(s.prompt),
            "pos": int(self._pos_host[i]),
            "latency_ms": round(ms, 2),
            "cached_prefix_len": s.cached_len,
        }
        if s.ttft_ms is not None:
            s.result["ttft_ms"] = round(s.ttft_ms, 3)
        self._stats["handoffs_out"] += 1
        self._slots[i] = None
        self._free_row_locked(i)
        self._release_prefix_locked(s)
        self._trace_request_locked(s, "prefill")
        s.done.set()

    def prefill_handoff(self, prompt_ids, max_tokens: int = 16,
                        temperature: float = 0.0, timeout_s: float = 600.0,
                        cache_prefix: bool = False, request_id: str = "",
                        trace: Optional[TraceContext] = None,
                        model_version: str = ""):
        """Prefill-pool entry: run the whole-prompt prefill + on-device
        first-token sample exactly like generate(), then export the row's
        KV blocks instead of decoding. Returns a
        :class:`~kubedl_tpu.serving.disagg.KVHandoff` ready for a decode
        replica's :meth:`adopt_handoff`. The handoff point is the
        colocated engine's own prefill/decode seam, which is what makes
        disaggregated greedy output bit-identical."""
        from kubedl_tpu.serving.disagg import HandoffError

        if not self._paged:
            raise ValueError(
                "disaggregated prefill requires kv_layout='paged'"
            )
        budget = self.max_seq - 1
        prompt = [int(t) for t in list(prompt_ids)[:budget]]
        if not prompt:
            prompt = [0]
        max_tokens = max(0, min(int(max_tokens), budget - len(prompt)))
        if max_tokens < 1:
            raise ValueError(
                "prompt leaves no token budget to hand off "
                f"(len {len(prompt)} of max_seq {self.max_seq})"
            )
        # the prefill row only ever produces the FIRST token (budget 1);
        # the request's real decode budget rides in the handoff meta
        slot = _Slot(prompt, 1, float(temperature), cache_prefix,
                     request_id=request_id)
        slot.handoff = {"max_tokens": max_tokens}
        slot.version = str(model_version or "")
        self._arm_trace(slot, trace)
        self._enqueue_slot_locked_checks(slot)
        if not slot.done.wait(timeout=timeout_s):
            with self._cv:
                if slot in self._waiting:
                    self._waiting.remove(slot)
                for i, s in enumerate(self._slots):
                    if s is slot:
                        self._slots[i] = None
                        self._free_row_locked(i)
                self._release_prefix_locked(slot)
        result = slot.result or {"error": "timed out", "timed_out": True}
        with self._cv:
            if request_id:
                self._requests.pop(request_id, None)
            self._stats["requests"] += 1
            self._stats["tokens_in"] += len(prompt)
            self._recent.append(time.time())
        hid = result.get("handoff_id")
        if hid is None:
            raise HandoffError(result.get("error", "prefill failed"))
        return self.fetch_handoff(hid, timeout_s=min(timeout_s, 60.0))

    def _enqueue_slot_locked_checks(self, slot: _Slot) -> None:
        """Admission gate shared by generate()'s disaggregated siblings:
        drain rejection, queue-depth/age shedding, KV watermark shedding
        — identical budgets, identical 503 reasons. Also resolves the
        slot's weight version (slot.version holds the REQUESTED id on
        entry; unknown/retiring → UnknownModelVersion, a 400 not a
        503)."""
        with self._cv:
            slot.version = self._resolve_version_locked(slot.version)
            if self._draining:
                self._stats["drain_rejects"] += 1
                raise EngineOverloaded(
                    "engine is draining", retry_after_s=1.0,
                    reason="draining",
                )
            depth = len(self._waiting)
            head_age = (
                time.perf_counter() - self._waiting[0].t0
                if self._waiting else 0.0
            )
            if depth >= self.max_queue_depth or head_age > self.max_queue_age_s:
                self._stats["shed"] += 1
                self._shed_recent.append(time.time())
                self.metrics.shed_requests.inc()
                retry = max(1.0, min(self.max_queue_age_s, 0.25 * depth))
                raise EngineOverloaded(
                    f"queue depth {depth} (budget {self.max_queue_depth}), "
                    f"head age {head_age:.1f}s "
                    f"(budget {self.max_queue_age_s}s)",
                    retry_after_s=retry,
                )
            if self._paged and not self._alloc.admission_open():
                self._stats["shed"] += 1
                self._stats["kv_sheds"] += 1
                self._shed_recent.append(time.time())
                self.metrics.shed_requests.inc()
                self.metrics.kv_block_sheds.inc()
                raise EngineOverloaded(
                    f"free KV blocks {self._alloc.free_count}/"
                    f"{self._alloc.total} below low watermark",
                    retry_after_s=1.0,
                )
            self._waiting.append(slot)
            if slot.request_id:
                self._requests[slot.request_id] = slot
            self._cv.notify_all()

    def fetch_handoff(self, hid: str, timeout_s: float = 30.0):
        """Export a parked handoff's block payloads as a KVHandoff and
        release the handoff's block references. The device gather runs on
        the scheduler thread (the only thread that may read the donated
        cache between dispatches); this call just queues the request and
        waits. Raises HandoffError on transfer failure — the blocks are
        freed either way (conservation)."""
        from kubedl_tpu.serving.disagg import HandoffError

        ev = threading.Event()
        box: Dict = {}
        with self._cv:
            if hid not in self._handoffs:
                raise HandoffError(f"unknown or expired handoff {hid!r}")
            self._export_q.append((hid, box, ev))
            self._cv.notify_all()
        if not ev.wait(timeout=timeout_s):
            raise HandoffError(f"handoff export {hid} timed out")
        if "error" in box:
            raise HandoffError(box["error"])
        return box["handoff"]

    def _service_exports(self) -> None:
        """Scheduler-thread half of fetch_handoff: GC expired parked
        handoffs, then export each queued request's blocks (gather →
        host copy → KVHandoff) and free the handoff's references. The
        chaos site ``serving.kv_handoff`` injects a transfer failure
        here — the blocks are freed on that path too."""
        if not self._paged:
            return
        import numpy as np

        from kubedl_tpu.serving.disagg import KVHandoff

        with self._cv:
            if not self._export_q and not self._handoffs:
                return
            now = time.time()
            for hid in [h for h, rec in self._handoffs.items()
                        if now - rec["t"] > self.handoff_ttl_s]:
                rec = self._handoffs.pop(hid)
                self._alloc.free(rec["blocks"])
                self._stats["handoff_failures"] += 1
            work = []
            while self._export_q:
                hid, box, ev = self._export_q.popleft()
                work.append((hid, box, ev, self._handoffs.pop(hid, None)))
        for hid, box, ev, rec in work:
            if rec is None:
                box["error"] = f"unknown or expired handoff {hid!r}"
                ev.set()
                continue
            t0 = time.perf_counter()
            try:
                chaos.check("serving.kv_handoff")
                k, v = self._llama.export_kv_blocks(
                    self._cache, rec["blocks"]
                )
                k = np.array(self._jax.device_get(k))
                v = np.array(self._jax.device_get(v))
                # the handoff carries its trace as a header-format string
                # (parent = the prefill request span) so a decode engine
                # adopting it WITHOUT an HTTP header still joins the trace
                th = ""
                if rec.get("span_id") and rec.get("trace") is not None:
                    th = TraceContext(
                        rec["trace"].trace_id, rec["span_id"]
                    ).to_header()
                h = KVHandoff(
                    model=self.preset_name,
                    prompt_ids=rec["prompt"],
                    first_token=rec["first_token"],
                    pos=rec["pos"],
                    block_size=self.kv_block_size,
                    k=k, v=v,
                    max_tokens=rec["max_tokens"],
                    temperature=rec["temperature"],
                    request_id=rec["request_id"],
                    cache_prefix=rec["cache_prefix"],
                    ttft_ms=rec["ttft_ms"],
                    trace=th,
                    model_version=rec.get("model_version", ""),
                )
                box["handoff"] = h
                m = self.metrics
                m.handoff_total.inc(direction="export")
                m.handoff_bytes.inc(h.nbytes, direction="export")
                m.handoff_ms.observe(
                    (time.perf_counter() - t0) * 1e3, direction="export"
                )
                if rec.get("span_id"):
                    TRACER.record(
                        "engine.handoff_export", start=t0,
                        duration=time.perf_counter() - t0,
                        trace=rec["trace"], parent_id=rec["span_id"],
                        nbytes=h.nbytes,
                    )
            except Exception as e:
                box["error"] = f"handoff export failed: {e}"
                with self._cv:
                    self._stats["handoff_failures"] += 1
            finally:
                self._alloc.free(rec["blocks"])
                ev.set()

    def adopt_handoff(self, h, timeout_s: float = 600.0,
                      request_id: str = "",
                      trace: Optional[TraceContext] = None,
                      debug_trace: bool = False) -> Dict:
        """Decode-pool entry: adopt a prefill replica's KVHandoff —
        allocate blocks from THIS engine's pool (all-or-nothing, same
        watermark admission as generate), scatter the payloads in, and
        resume decoding from the first token. The returned result has the
        same shape as generate()'s, and for greedy requests the token ids
        are bit-identical to a colocated single-engine call."""
        if not self._paged:
            raise ValueError(
                "adopting a KV handoff requires kv_layout='paged'"
            )
        if int(h.block_size) != self.kv_block_size:
            raise ValueError(
                f"handoff block_size {h.block_size} != engine "
                f"{self.kv_block_size}"
            )
        pool = self._cache["k"].shape
        if tuple(h.k.shape[0:1]) + tuple(h.k.shape[2:]) != (
            pool[0], pool[2], pool[3], pool[4]
        ):
            raise ValueError(
                f"handoff KV geometry {h.k.shape} does not fit pool "
                f"{pool} (model mismatch? handoff model={h.model!r})"
            )
        prompt = [int(t) for t in h.prompt_ids]
        budget = self.max_seq - 1
        if len(prompt) >= budget:
            raise ValueError(
                f"handoff prompt len {len(prompt)} exceeds adopter budget "
                f"{budget}"
            )
        max_tokens = max(1, min(int(h.max_tokens), budget - len(prompt)))
        slot = _Slot(prompt, max_tokens, float(h.temperature),
                     h.cache_prefix, request_id=request_id or h.request_id)
        slot.adopt = h
        # version stickiness across the disagg seam: decode on exactly
        # the version that prefilled (rides the handoff header); a decode
        # replica that has not loaded it rejects the adopt cleanly
        slot.version = str(getattr(h, "model_version", "") or "")
        # explicit context (HTTP header) wins; else the handoff's own
        # embedded trace keeps direct engine→engine adoption on-trace
        if trace is None:
            trace = parse_trace_header(getattr(h, "trace", ""))
        self._arm_trace(slot, trace, debug_trace)
        self._enqueue_slot_locked_checks(slot)
        if not slot.done.wait(timeout=timeout_s):
            with self._cv:
                if slot in self._waiting:
                    self._waiting.remove(slot)
                for i, s in enumerate(self._slots):
                    if s is slot:
                        self._slots[i] = None
                        self._free_row_locked(i)
                self._release_prefix_locked(slot)
        result = slot.result or {"error": "timed out", "timed_out": True}
        with self._cv:
            if slot.request_id:
                self._requests.pop(slot.request_id, None)
            self._stats["requests"] += 1
            self._stats["tokens_in"] += len(prompt)
            self._stats["tokens_out"] += len(result.get("token_ids", []))
            self._recent.append(time.time())
        return self._trace_result(slot, result, debug_trace)

    def _admit_row_adopt_locked(self, i: int, slot: _Slot):
        """Admit an adopted slot into row ``i``: allocate the handoff's
        block count from this pool (sharing any prefix-cache match's full
        blocks by reference instead of re-importing them), scatter the
        remaining payloads, and seed the slot at the prefill/decode seam
        (fed = prompt len, out_ids = [first_token], pos = prompt len).
        Returns True (admitted), None (pool dry — slot stays queued), or
        False (transfer failed — waiter woken with an error, blocks all
        returned). Caller holds cv."""
        h = slot.adopt
        a = self._alloc
        bs = self.kv_block_size
        n_blocks = int(h.k.shape[1])
        entry, mlen = None, 0
        if self._pcache is not None:
            self._pcache.observe(slot.prompt)
            entry, mlen = self._pcache.match(slot.prompt)
        entry_blocks = (
            getattr(entry, "blocks", None) if entry is not None else None
        )
        # share only FULL matched blocks: the partial tail needs no COW
        # here because the handoff carries the payload — importing it
        # fresh is cheaper than a device block copy
        shared = list(entry_blocks[:mlen // bs]) if entry_blocks else []
        if len(shared) > n_blocks:
            shared = shared[:n_blocks]
        n_alloc = n_blocks - len(shared)
        got = a.alloc(n_alloc)
        if got is None and self._reclaim_prefix_locked():
            got = a.alloc(n_alloc)
        if got is None:
            if entry is not None:
                self._pcache.unpin(entry)
            return None
        a.incref(shared)
        blocks = shared + got
        if chaos.should_fail("serving.kv_handoff"):
            # transfer failure mid-flight: every reference taken above
            # goes straight back (conservation), the waiter learns why
            a.free(blocks)
            if entry is not None:
                self._pcache.unpin(entry)
            self._stats["handoff_failures"] += 1
            slot.result = {
                "error": "handoff transfer failed (injected)",
                "handoff_failed": True,
            }
            slot.done.set()
            return False
        t0 = time.perf_counter()
        if got:
            start = len(shared)
            self._cache = self._llama.import_kv_blocks(
                self._cache, h.k[:, start:n_blocks],
                h.v[:, start:n_blocks], got,
            )
        self._row_blocks[i] = blocks
        self._bt_host[i, :] = 0
        self._bt_host[i, :len(blocks)] = blocks
        self._pos_host[i] = min(int(h.pos), self.max_seq - 1)
        slot.fed = len(slot.prompt)
        slot.out_ids = [int(h.first_token)]
        slot.cached_len = len(shared) * bs
        if slot.ttft_ms is None and h.ttft_ms is not None:
            slot.ttft_ms = float(h.ttft_ms)
        self._slots[i] = slot
        # the adopted row's first decode input (h.first_token) exists only
        # HOST-side — a device chain left by this row's previous tenant
        # would otherwise pass the chain_ok row check and feed that
        # tenant's stale sampled id instead
        self._chain = None
        if entry is not None:
            self.metrics.prefix_hits.inc()
            # the row is self-contained once the shares are increfed —
            # no prefill will read through the entry, drop the pin now
            self._pcache.unpin(entry)
        elif self._pcache is not None:
            self.metrics.prefix_misses.inc()
        self._stats["handoffs_in"] += 1
        m = self.metrics
        m.handoff_total.inc(direction="adopt")
        m.handoff_bytes.inc(h.nbytes, direction="adopt")
        m.handoff_ms.observe(
            (time.perf_counter() - t0) * 1e3, direction="adopt"
        )
        if slot.span_id:
            TRACER.record("engine.handoff_adopt", start=t0,
                          duration=time.perf_counter() - t0,
                          trace=slot.trace, parent_id=slot.span_id,
                          blocks=len(blocks), shared=len(shared))
        # adopted prompts join this replica's prefix cache so the
        # router's block-aware affinity can steer repeats here
        self._maybe_insert_prefix_locked(i, slot)
        self._maybe_finalize_locked(i, slot)
        return True

    def _segment_fn(self, n_steps: int, greedy: bool):
        """Jitted n-step decode with on-device sampling (cache donated);
        one compile per (segment size, greedy) combination."""
        fn = self._segments.get((n_steps, greedy))
        if fn is None:
            import functools

            seg = (
                self._llama.paged_decode_segment if self._paged
                else self._llama.decode_segment
            )
            kw = {"kv_attention": self.kv_attention} if self._paged else {}
            fn = self._jax.jit(
                functools.partial(
                    seg, cfg=self.cfg, n_steps=n_steps, greedy=greedy,
                    **kw,
                ),
                donate_argnums=(1,),
            )
            self._segments[(n_steps, greedy)] = fn
        return fn

    def _prefill_bucket(self, max_len: int) -> int:
        """Pad prompts to power-of-2 buckets: bounded compile count
        (one per bucket, <= log2(max_seq)) with at most 2x padding."""
        b = 16
        while b < max_len:
            b <<= 1
        return min(b, self.max_seq)

    @staticmethod
    def segment_size(need: int, cap: int,
                     buckets: tuple = SEGMENT_BUCKETS) -> int:
        """Pure host-side bucket policy (unit-testable without a device):
        pick the segment size for a remaining budget of ``need`` tokens.
        Rounds UP to the smallest covering bucket only when the overshoot
        is small (<= a quarter of the bucket: rem=31 runs one 32-segment
        discarding 1), else steps DOWN to the largest bucket below
        (rem=7 runs a 4-segment instead of burning 25 wasted decodes).
        ``cap`` (4 while requests wait) bounds admission latency."""
        need = max(1, min(int(need), int(cap)))
        up = next((b for b in reversed(buckets) if b >= need), buckets[0])
        if up - need <= up // 4:
            return up
        return next((b for b in buckets if b <= need), 1)

    # -- pipeline stages ---------------------------------------------------

    def _harvest_segment(self):
        """Harvest the deferred in-flight decode segment: `device_get` its
        sampled ids (blocks until the device finishes the segment), append
        the values to each slot, finalize completed requests, and admit
        waiters. No-op when nothing is in flight. Returns
        ``(blocked_ms, host_ms)`` for the tick accounting."""
        import numpy as np

        pend, self._pending = self._pending, None
        if pend is None:
            return 0.0, 0.0
        t0 = time.perf_counter()
        # np.array (copy): device_get may return a zero-copy VIEW of the
        # device buffer, which a later donated dispatch can reuse
        rows = np.array(self._jax.device_get(pend["toks"]))  # [B, k]
        t1 = time.perf_counter()
        seg_t0 = pend.get("t0", t0)
        with self._cv:
            self._pipe["inflight"] = 0
            for i, s, take in pend["sched"]:
                s.pending -= take
                if self._slots[i] is not s:
                    continue  # vacated (request timeout) mid-segment
                s.out_ids.extend(int(t) for t in rows[i][:take])
                if s.span_id and take:
                    # segment wall time is SHARED by every scheduled row
                    # (one batched dispatch); each row gets its own span
                    # so per-request trees stay self-contained
                    TRACER.record("engine.decode_segment", start=seg_t0,
                                  duration=t1 - seg_t0, trace=s.trace,
                                  parent_id=s.span_id, tokens=take)
                self._maybe_finalize_locked(i, s)
            self._admit_locked()
            self._cv.notify_all()
        return (t1 - t0) * 1e3, (time.perf_counter() - t1) * 1e3

    def _harvest_prefill(self, pre, ids_dev):
        """Harvest prefill's device-sampled first tokens ([B] int32 — the
        logits never left the device) and record them. Runs AFTER the next
        decode segment is dispatched, so the copy-out overlaps device
        compute. Returns ``(blocked_ms, host_ms)``."""
        import numpy as np

        t0 = time.perf_counter()
        ids = np.array(self._jax.device_get(ids_dev))  # copy: see harvest
        t1 = time.perf_counter()
        now = time.perf_counter()
        with self._cv:
            for i, s, budgeted in pre:
                if budgeted:
                    s.pending -= 1
                if self._slots[i] is not s:
                    # vacated (request timeout) mid-prefill; the vacate
                    # path already released any prefix pin
                    continue
                if budgeted and s.ttft_ms is None:
                    s.ttft_ms = (now - s.t0) * 1e3
                    self._ttft_recent.append(s.ttft_ms)
                    self.metrics.ttft_ms.observe(s.ttft_ms)
                if budgeted:
                    s.out_ids.append(int(ids[i]))
                if s.span_id:
                    p0 = s.prefill_t0 if s.prefill_t0 is not None else t0
                    TRACER.record("engine.prefill", start=p0,
                                  duration=now - p0, trace=s.trace,
                                  parent_id=s.span_id,
                                  prompt_len=len(s.prompt),
                                  cached_len=s.cached_len)
                # the row's prefix KV is now self-contained (prefill has
                # completed) — the grafted entry no longer needs its pin
                self._release_prefix_locked(s)
                self._maybe_insert_prefix_locked(i, s)
                self._maybe_finalize_locked(i, s)
            self._admit_locked()
            self._cv.notify_all()
        return (t1 - t0) * 1e3, (time.perf_counter() - t1) * 1e3

    def _prefill_chunks(self, todo, acct: Dict, params=None):
        """Chunked-admission prefill dispatch (docs/serving.md
        "Continuous batching"): spend at most ``prefill_chunk_tokens``
        prompt tokens this tick across the not-yet-prefilled rows, FIFO
        by arrival time so chunk scheduling preserves admission order at
        chunk granularity. Every chunk goes through the suffix prefill
        (`llama.paged_prefill_from`) at the row's committed position;
        intermediate chunks are block-aligned (no KV block is ever
        written by two dispatches) and touch nothing but the pool and
        the pos mirror, so decode segments keep dispatching between
        them. Only rows whose FINAL chunk lands this tick sample a
        first token, join the device chain, and become decoding rows.
        When the budget runs out mid-prompt the FIFO head keeps the
        leftover — later arrivals never overtake it. Returns the
        ``(pre, prefill_ids)`` pair the caller's deferred
        `_harvest_prefill` consumes (final rows only)."""
        import numpy as np
        import jax.numpy as jnp

        bs = self.kv_block_size
        left = self.prefill_chunk_tokens
        sched = []  # (row, slot, base, take, final)
        for i, s in sorted(todo, key=lambda t: t[1].t0):
            if left <= 0:
                break
            base = s.prefill_pos if s.prefill_pos >= 0 else s.cached_len
            rem = max(0, len(s.prompt) - base)
            take = min(rem, left)
            if take < rem:
                take = (take // bs) * bs
                if take <= 0:
                    break
            sched.append((i, s, base, take, base + take >= len(s.prompt)))
            left -= take
        if not sched:
            return [], None
        # injected chunk-dispatch fault: the scheduler must recover
        # (fail in-flight slots, rebuild the donated cache, keep
        # serving) exactly as for a decode-segment fault
        chaos.check("serving.chunk_admit")
        bucket = self._prefill_bucket(
            max(max(t for _i, _s, _b, t, _f in sched), 1)
        )
        toks = np.zeros((self.max_batch, bucket), np.int32)
        lens = np.zeros((self.max_batch,), np.int32)
        starts = np.zeros((self.max_batch,), np.int32)
        temps0 = np.zeros((self.max_batch,), np.float32)
        saved = 0
        for i, s, base, take, _final in sched:
            toks[i, :take] = s.prompt[base:base + take]
            lens[i] = take
            starts[i] = base
            temps0[i] = max(float(s.temperature), 0.0)
            if s.prefill_pos < 0 and s.cached_len:
                saved += s.cached_len  # first chunk rode a grafted prefix
        self._key, pick_key = self._jax.random.split(self._key)
        # host mirrors are authoritative — same contract as every dispatch
        self._cache["pos"] = self._upload_mirror(self._pos_host)
        self._cache["bt"] = self._upload_mirror(self._bt_host)
        t0 = time.perf_counter()
        logits, self._cache = self._prefill_from(
            self.params if params is None else params, self._cache,
            jnp.asarray(toks), jnp.asarray(lens), jnp.asarray(starts),
        )
        if saved:
            if self._pcache is not None:
                self._pcache.add_tokens_saved(saved)
            self.metrics.prefix_tokens_saved.inc(saved)
        self.metrics.admission_chunks.inc(len(sched))
        prefill_ids = self._sample_logits(
            logits, jnp.asarray(temps0), pick_key
        )
        final_rows = tuple(i for i, _s, _b, _t, f in sched if f)
        if final_rows:
            # only finishing rows carry a token into the device chain;
            # intermediate chunks leave the chain (and its generation)
            # alone, so in-flight decode feeds stay valid between chunks
            self._prefill_gen += 1
            mask = np.zeros((self.max_batch,), bool)
            mask[list(final_rows)] = True
            if self._chain is not None:
                merged = self._merge_chain(
                    self._chain[2], prefill_ids, jnp.asarray(mask)
                )
                self._chain = (
                    self._prefill_gen,
                    tuple(sorted(set(self._chain[1]) | set(final_rows))),
                    merged,
                )
            else:
                self._chain = (
                    self._prefill_gen, final_rows, prefill_ids[:, None]
                )
        acct["dispatch_ms"] += (time.perf_counter() - t0) * 1e3
        pre = []
        with self._cv:
            for i, s, base, take, final in sched:
                # mirror the device's pos advance for dispatched rows
                # (vacated rows get reset at readmission)
                self._pos_host[i] = min(base + take, self.max_seq - 1)
                if self._slots[i] is not s:
                    continue  # vacated (request timeout) mid-chunk
                s.prefill_pos = base + take
                if s.prefill_t0 is None:
                    s.prefill_t0 = t0  # first chunk starts the TTFT span
                if not final:
                    continue
                s.fed = len(s.prompt)
                budgeted = (
                    s.max_tokens > 0
                    and len(s.prompt) + len(s.out_ids)
                    < self.max_seq - 1
                )
                if budgeted:
                    s.pending += 1
                pre.append((i, s, budgeted))
        return pre, (prefill_ids if pre else None)

    def _spec_tick(self, decoding, acct: Dict, params=None) -> None:
        """One draft-k/verify-1 round over every greedy decoding row.

        Per row: the pluggable draft proposes k tokens from the full host
        context; the verify forward consumes ``[next_input, d1..dk]`` in
        ONE batched call (`llama.paged_verify`) and returns the target's
        greedy argmax after each input. The longest prefix where drafts
        agree with those argmaxes is accepted, plus one bonus token —
        every emitted token is the target's own greedy choice given only
        accepted history, so output is bit-identical to plain decode (the
        tier-1 gate); speculation only changes how many sequential
        forwards it takes. The pos mirror then rewinds past the rejected
        suffix and `_trim_row_locked` frees its KV blocks in place.

        With ``spec_candidates > 1`` the draft proposes N candidate
        continuations per row (`propose_candidates`; candidate 0 is
        always the plain greedy proposal). A READ-ONLY scoring forward
        (`llama.paged_verify_multi`) ranks all N against the target in
        one batched call, the longest-agreeing candidate is swapped into
        the verify window, and the standard write-path verify runs on
        the winner — so multi-candidate never emits anything but target
        argmaxes, and never accepts fewer tokens than candidate 0 would
        have. Draft proposal wall time is measured per round
        (`spec_draft_ms`) so dashboards can attribute decode time to
        draft vs verify."""
        import numpy as np
        import jax.numpy as jnp

        from kubedl_tpu.serving.speculative import accept_length, build_tree

        if params is None:
            params = self.params
        k = self.spec_k
        S = k + 1
        N = self.spec_candidates
        multi = N > 1 and self._verify_multi is not None
        tree = multi and self._verify_tree is not None
        draft_kind = getattr(self._draft, "name", self.spec_draft)
        # phase 1 — snapshot contexts under the lock, DRAFT OUTSIDE IT:
        # a model draft's forward must not stall admission/finalize.
        # Only this scheduler thread mutates prompt/out_ids/fed, so the
        # snapshot stays coherent; vacated rows are re-checked by slot
        # identity before anything is committed.
        with self._cv:
            cand = [
                (i, s, list(s.prompt) + list(s.out_ids), s.next_input())
                for i, s in decoding if self._slots[i] is s
            ]
        if not cand:
            return
        t_d = time.perf_counter()
        if multi:
            cand_lists = [
                self._draft.propose_candidates(ctx, k, N)
                for _, _, ctx, _ in cand
            ]
        else:
            cand_lists = [
                [p] for p in self._draft.propose_batch(
                    [ctx for _, _, ctx, _ in cand], k
                )
            ]
        draft_ms = (time.perf_counter() - t_d) * 1e3
        self._spec_stats.record_draft_ms(draft_ms)
        self.metrics.spec_draft_ms.observe(draft_ms, draft=draft_kind)

        def _pad(drafts, ctx):
            d = [int(t) for t in drafts][:k]
            if len(d) < k:
                pad = d[-1] if d else int(ctx[-1])
                d = d + [pad] * (k - len(d))
            return d

        toks = np.zeros((self.max_batch, S), np.int32)
        lens = np.zeros((self.max_batch,), np.int32)
        starts = np.zeros((self.max_batch,), np.int32)
        cand_toks = (
            np.zeros((self.max_batch, N, S), np.int32) if multi else None
        )
        with self._cv:
            rows = []
            for (i, s, ctx, nxt), clists in zip(cand, cand_lists):
                if self._slots[i] is not s:
                    continue
                dl = [_pad(d, ctx) for d in clists[:N]]
                if not dl:
                    dl = [[int(ctx[-1])] * k]
                while len(dl) < N:
                    dl.append(dl[0])
                toks[i, 0] = nxt
                toks[i, 1:] = dl[0]
                lens[i] = S
                starts[i] = self._pos_host[i]
                if multi:
                    cand_toks[i, :, 0] = nxt
                    for c_n, c_d in enumerate(dl):
                        cand_toks[i, c_n, 1:] = c_d
                rows.append((i, s, dl))
            # coverage for S appends per row, preempting on exhaustion;
            # rows the reserve drops sit this verify out entirely
            surviving = self._reserve_decode_locked(
                [(i, s) for i, s, _ in rows], S
            )
            dmap = {i: d for i, _, d in rows}
            rows = [(i, s, dmap[i]) for i, s in surviving]
            for i in set(dmap) - {i for i, _, _ in rows}:
                lens[i] = 0  # dropped/preempted: inactive in the verify
        if not rows:
            return
        chaos.check("serving.dispatch")
        self._cache["pos"] = self._upload_mirror(self._pos_host)
        self._cache["bt"] = self._upload_mirror(self._bt_host)
        t0 = time.perf_counter()
        if tree:
            # trie ranking pass (read-only, like multi): candidates
            # sharing a prefix share trie nodes, one forward scores
            # every node under its ancestor mask, and the deepest
            # accepted root path becomes the write-path verify's draft
            M = self._spec_tree_m
            toks_tr = np.zeros((self.max_batch, M), np.int32)
            pos_tr = np.zeros((self.max_batch, M), np.int32)
            mask_tr = np.zeros((self.max_batch, M, M), bool)
            mask_tr[:, np.arange(M), np.arange(M)] = True  # inactive rows
            lens_tr = np.zeros((self.max_batch,), np.int32)
            trees = {}
            for i, s, dl in rows:
                tr = build_tree(int(toks[i, 0]), dl, k, M)
                trees[i] = tr
                t_toks, t_dep, t_mask = tr.arrays(M)
                toks_tr[i] = t_toks
                pos_tr[i] = int(starts[i]) + t_dep
                mask_tr[i] = t_mask
                lens_tr[i] = tr.size
            ids_tree = np.array(self._jax.device_get(self._verify_tree(
                params, self._cache, jnp.asarray(toks_tr),
                jnp.asarray(pos_tr), jnp.asarray(mask_tr),
                jnp.asarray(lens_tr), jnp.asarray(starts),
            )))  # [B, M]
            for i, s, dl in rows:
                path = trees[i].walk(ids_tree[i])
                # the walk follows unique-token children, so it only
                # leaves the greedy chain where that chain already
                # mismatched — switching can never shorten acceptance
                switched = bool(path) and path != dl[0][:len(path)]
                self._spec_stats.record_candidates(trees[i].size, switched)
                if switched:
                    dl[0] = _pad(path, [toks[i, 0]])
                    toks[i, 1:] = dl[0]
        elif multi:
            # read-only ranking pass (cache neither donated nor written)
            ids_multi = np.array(self._jax.device_get(self._verify_multi(
                params, self._cache, jnp.asarray(cand_toks),
                jnp.asarray(lens), jnp.asarray(starts),
            )))  # [B, N, S]
            for i, s, dl in rows:
                best = 0
                best_a = accept_length(dl[0], ids_multi[i, 0][:k])
                for c_n in range(1, N):
                    a_n = accept_length(dl[c_n], ids_multi[i, c_n][:k])
                    if a_n > best_a:
                        best, best_a = c_n, a_n
                self._spec_stats.record_candidates(N, best != 0)
                if best:
                    dl[0] = dl[best]  # the accept loop reads dl[0]
                    toks[i, 1:] = dl[0]
        ids_dev, self._cache = self._verify(
            params, self._cache, jnp.asarray(toks),
            jnp.asarray(lens), jnp.asarray(starts),
        )
        acct["dispatch_ms"] += (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        ids = np.array(self._jax.device_get(ids_dev))  # [B, S] (copy)
        acct["harvest_ms"] += (time.perf_counter() - t1) * 1e3
        t2 = time.perf_counter()
        with self._cv:
            for i, s, dl in rows:
                drafts = dl[0]
                a = accept_length(drafts, ids[i][:k])
                if self._slots[i] is not s:
                    continue  # vacated mid-verify; writes land in trash
                take = min(a + 1, self._rem(s))
                s.out_ids.extend(int(t) for t in ids[i][:take])
                s.fed += take
                # rewind past the rejected suffix: the device advanced
                # pos by S, the mirror keeps only accepted history and
                # the next upload makes it so
                self._pos_host[i] = min(
                    int(starts[i]) + take, self.max_seq - 1
                )
                self._trim_row_locked(i, int(self._pos_host[i]))
                self._spec_stats.record(k, a, take)
                self.metrics.spec_proposed.inc(k, draft=draft_kind)
                self.metrics.spec_accepted.inc(a, draft=draft_kind)
                if s.span_id:
                    TRACER.record("engine.spec_round", start=t_d,
                                  duration=time.perf_counter() - t_d,
                                  trace=s.trace, parent_id=s.span_id,
                                  k=k, accepted=int(a), emitted=take)
                self._maybe_finalize_locked(i, s)
            self._admit_locked()
            self._cv.notify_all()
        # the verify consumed host-fed tokens: any device chain is stale
        self._chain = None
        acct["segments"] += 1
        acct["host_ms"] += (time.perf_counter() - t2) * 1e3

    def _commit_tick(self, acct: Dict, tick_ms: float) -> None:
        """Fold one tick's accounting into the pipeline stats + metrics."""
        overlap_ms = (
            acct["dispatch_ms"] + acct["host_ms"] if acct["overlapped"]
            else 0.0
        )
        with self._cv:
            p = self._pipe
            p["ticks"] += 1
            p["segments"] += acct["segments"]
            p["deferred_harvests"] += acct["deferred"]
            p["flushes"] += acct["flushes"]
            p["chain_rebuilds"] += acct["rebuilds"]
            p["dispatch_ms_sum"] += acct["dispatch_ms"]
            p["harvest_ms_sum"] += acct["harvest_ms"]
            p["host_ms_sum"] += acct["host_ms"]
            p["tick_ms_sum"] += tick_ms
            p["overlap_ms_sum"] += overlap_ms
            self._pipe_recent.append(
                (acct["dispatch_ms"], acct["harvest_ms"], acct["host_ms"],
                 tick_ms)
            )
            queued = len(self._waiting)
            ratio = p["overlap_ms_sum"] / max(p["tick_ms_sum"], 1e-9)
        m = self.metrics
        if acct["segments"]:
            m.segments.inc(acct["segments"])
        if acct["deferred"]:
            m.deferred_harvests.inc(acct["deferred"])
        if acct["flushes"]:
            m.pipeline_flushes.inc(acct["flushes"])
        if acct["rebuilds"]:
            m.chain_rebuilds.inc(acct["rebuilds"])
        m.dispatch_ms.observe(acct["dispatch_ms"])
        m.harvest_ms.observe(acct["harvest_ms"])
        m.host_ms.observe(acct["host_ms"])
        m.overlap_ratio.set(ratio)
        m.queue_depth.set(float(queued))
        if self._paged:
            st = self._alloc.stats()
            kern = {"attention_kernel": self.kv_attention,
                    "role": self.role}
            m.kv_blocks_total.set(float(st["total"]), **kern)
            m.kv_blocks_free.set(float(st["free"]), **kern)
            m.kv_blocks_shared.set(float(st["shared"]), **kern)
        if self._spec_stats is not None:
            m.spec_acceptance_rate.set(self._spec_stats.acceptance_rate())

    def _loop_once(self) -> bool:
        """One tick of the DOUBLE-BUFFERED decode pipeline; returns True
        when the engine is stopping.

        The old tick was synchronous — dispatch segment, block in
        `device_get` for its tokens, do host bookkeeping, dispatch the
        next — so the chip idled through every copy-out + host round trip
        (~4 ms/token of the r5 b1 engine overhead). Now a tick in steady
        state (segment N-1 already in flight on device):

            dispatch prefill (new rows)        } async: queue behind N-1,
            dispatch decode segment N          } tokens chained ON DEVICE
            harvest segment N-1 (device_get)   — blocks until N-1 done...
            bookkeeping/finalize/admission     } ...then everything here
            harvest prefill first tokens       } overlaps N's device time

        Freshly prefilled rows join segment N in the SAME tick: their
        first sampled ids are grafted into the device chain
        (`llama.merge_chain_tokens`) before the segment is dispatched, so
        TTFT never serializes behind an in-flight segment's harvest.
        Scheduling is count-based (``_rem`` includes in-flight tokens);
        values land one tick later and completed slots finalize at
        harvest, when their token values exist host-side."""
        import numpy as np
        import jax.numpy as jnp

        with self._cv:
            self._admit_locked()
            while (
                not self._stop and self._pending is None
                and not self._export_q and not self._handoffs
                and not any(s is not None for s in self._slots)
            ):
                self._cv.wait(timeout=0.2)
                self._admit_locked()
            stop = self._stop
            waiting = bool(self._waiting)
        # handoff exports run on THIS thread (sole owner of the donated
        # cache between dispatches) before the tick's own dispatches
        self._service_exports()
        if stop:
            self._harvest_segment()  # flush: deliver in-flight tokens
            return True

        t_tick = time.perf_counter()
        acct = {"dispatch_ms": 0.0, "harvest_ms": 0.0, "host_ms": 0.0,
                "overlapped": False, "segments": 0, "deferred": 0,
                "flushes": 0, "rebuilds": 0}

        if waiting and self._pending is not None:
            # requests queued: harvest FIRST so finished rows free up and
            # admission waits for at most ONE (small) segment instead of
            # queueing behind a freshly dispatched one — trades this
            # tick's overlap for bounded admission latency
            h, b = self._harvest_segment()
            acct["harvest_ms"] += h
            acct["host_ms"] += b
            acct["flushes"] += 1

        with self._cv:
            self._admit_locked()
            active = list(self._slots)
            # one weight version per tick: every dispatch below (prefill
            # group, decode segment, spec round) uses THIS tree only;
            # rows of co-resident versions sit the tick out (round-robin
            # alternation — a host-mirror no-op for them) so a forward
            # never mixes parameter trees
            tick_version = self._pick_tick_version_locked(active)
            vp = self._versions[tick_version]

        if tick_version != self._default_version:
            # seeded canary degradation (``serving.canary_dispatch``):
            # hits ONLY non-default-version ticks, so a drill can make
            # a deliberately-degraded canary burn its own SLO partition
            # while baseline traffic on the same replica stays healthy
            chaos.check("serving.canary_dispatch")

        def _mine(s: _Slot) -> bool:
            return (s.version or self._default_version) == tick_version

        # ---- prefill DISPATCH: newly admitted rows consume their WHOLE
        # prompt in one batched forward (TTFT = one forward, not
        # prompt_len decode steps); the first token is sampled on device
        # and its copy-out DEFERRED until after the next segment dispatch
        pre: list = []
        prefill_ids = None
        todo = [(i, s) for i, s in enumerate(active)
                if s is not None and s.fed == 0 and _mine(s)]
        if todo and self.prefill_chunk_tokens:
            # chunked admission: bounded prefill work per tick, rows
            # join the running decode batch chunk by chunk
            pre, prefill_ids = self._prefill_chunks(todo, acct, vp)
            with self._cv:
                active = list(self._slots)
        elif todo:
            # suffix-only prefill: rows with a grafted prefix consume only
            # prompt[cached_len:]. The bucket is sized by the LONGEST
            # suffix; `lax.dynamic_update_slice` CLAMPS out-of-bounds
            # starts, so any graft whose start + bucket would spill past
            # max_seq is dropped (full prefill for that row) and the
            # bucket recomputed — terminates because starts=0 always fits.
            while True:
                bucket = self._prefill_bucket(
                    max(len(s.prompt) - s.cached_len for _, s in todo)
                )
                if self._paged:
                    # no overflow fixup needed: the paged suffix prefill
                    # routes pad/clamped writes to the trash block, so a
                    # graft whose start + bucket spills past max_seq is
                    # harmless by construction (proven in test_kv_blocks)
                    break
                bad = [(i, s) for i, s in todo
                       if s.cached_len and s.cached_len + bucket > self.max_seq]
                if not bad:
                    break
                with self._cv:
                    for _, s in bad:
                        s.cached_len = 0
                        self._release_prefix_locked(s)
            toks = np.zeros((self.max_batch, bucket), np.int32)
            lens = np.zeros((self.max_batch,), np.int32)
            starts = np.zeros((self.max_batch,), np.int32)
            temps0 = np.zeros((self.max_batch,), np.float32)
            for i, s in todo:
                suffix = s.prompt[s.cached_len:]
                toks[i, : len(suffix)] = suffix
                lens[i] = len(suffix)
                starts[i] = s.cached_len
                temps0[i] = max(float(s.temperature), 0.0)
            self._key, pick_key = self._jax.random.split(self._key)
            if self._paged:
                # the HOST mirrors are authoritative: upload pos + block
                # table before every dispatch so rollbacks (speculative
                # rejection, preemption, vacation) are plain mirror edits
                self._cache["pos"] = self._upload_mirror(self._pos_host)
                self._cache["bt"] = self._upload_mirror(self._bt_host)
            t0 = time.perf_counter()
            if np.any(starts > 0):
                logits, self._cache = self._prefill_from(
                    vp, self._cache, jnp.asarray(toks),
                    jnp.asarray(lens), jnp.asarray(starts),
                )
                saved = int(starts.sum())
                if self._pcache is not None:
                    self._pcache.add_tokens_saved(saved)
                self.metrics.prefix_tokens_saved.inc(saved)
            else:
                logits, self._cache = self._prefill(
                    vp, self._cache, jnp.asarray(toks),
                    jnp.asarray(lens),
                )
            prefill_ids = self._sample_logits(
                logits, jnp.asarray(temps0), pick_key
            )  # [B] int32, stays on device until after the next dispatch
            self._prefill_gen += 1
            # graft the sampled first tokens into the device chain so the
            # new rows can join THIS tick's decode segment with zero
            # host->device traffic (per-row chain validity: untouched
            # rows keep the in-flight segment's output tokens)
            rows = tuple(i for i, _ in todo)
            mask = np.zeros((self.max_batch,), bool)
            mask[list(rows)] = True
            if self._chain is not None:
                merged = self._merge_chain(
                    self._chain[2], prefill_ids, jnp.asarray(mask)
                )
                self._chain = (
                    self._prefill_gen,
                    tuple(sorted(set(self._chain[1]) | set(rows))),
                    merged,
                )
            else:
                self._chain = (self._prefill_gen, rows, prefill_ids[:, None])
            acct["dispatch_ms"] += (time.perf_counter() - t0) * 1e3
            with self._cv:
                for i, s in todo:
                    if self._paged:
                        # mirror the device's pos update for dispatched
                        # rows (vacated rows get reset at readmission)
                        self._pos_host[i] = min(
                            int(starts[i]) + int(lens[i]), self.max_seq - 1
                        )
                    if self._slots[i] is not s:
                        continue  # vacated (request timeout) mid-prefill
                    s.fed = len(s.prompt)
                    s.prefill_t0 = t0  # dispatch start, for engine.prefill
                    budgeted = (
                        s.max_tokens > 0
                        and len(s.prompt) + len(s.out_ids)
                        < self.max_seq - 1
                    )
                    if budgeted:
                        s.pending += 1
                    pre.append((i, s, budgeted))
                active = list(self._slots)

        if self.spec_k and pre:
            # speculative ticks feed the verify window from HOST context
            # (prompt + harvested tokens), so the deferred prefill
            # harvest has nothing to overlap — collect first tokens now
            # and let fresh rows join this tick's verify
            h, b = self._harvest_prefill(pre, prefill_ids)
            acct["harvest_ms"] += h
            acct["host_ms"] += b
            pre = []
            prefill_ids = None
            with self._cv:
                active = list(self._slots)

        # ---- decode segment DISPATCH: K steps in one jitted call with
        # on-device sampling (llama.decode_segment); rows whose budget
        # ends mid-segment discard the overshoot — they are finished and
        # re-prefilled (pos reset) on slot reuse, so the garbage the
        # extra steps wrote to their cache rows is dead
        decoding = [
            (i, s) for i, s in enumerate(active)
            if s is not None and s.fed >= len(s.prompt) and self._rem(s) > 0
            and _mine(s)
        ]

        # ---- speculative verify (draft-k/verify-1): when every decoding
        # row is greedy, one batched forward scores k drafted tokens +
        # the next input per row; the longest draft/argmax agreement is
        # accepted and the pos mirror simply rewinds past any rejected
        # suffix (its blocks are freed in place). Mixed-temperature
        # traffic falls through to the segment path unchanged.
        if decoding and self.spec_k and all(
            float(s.temperature) <= 0.0 for _, s in decoding
        ):
            if self._pending is not None:
                # a deferred segment still owes tokens the verify's host-
                # side draft context needs — flush it first
                h, b = self._harvest_segment()
                acct["harvest_ms"] += h
                acct["host_ms"] += b
                acct["flushes"] += 1
                with self._cv:
                    decoding = [
                        (i, s) for i, s in decoding
                        if self._slots[i] is s and self._rem(s) > 0
                    ]
            if decoding:
                self._spec_tick(decoding, acct, vp)
            decoding = []

        new_pending = None
        if decoding:
            need = max(self._rem(s) for _, s in decoding)
            with self._cv:
                cap = 4 if self._waiting else self.SEGMENT_BUCKETS[0]
            k = self.segment_size(need, cap)
            temps = np.zeros((self.max_batch,), np.float32)
            for i, s in decoding:
                temps[i] = max(float(s.temperature), 0.0)
            greedy = not np.any(temps > 0.0)
            # feed from the DEVICE chain whenever it covers the decoding
            # rows: long generations never ship tokens host->device
            chain_ok = (
                self._chain is not None
                and self._chain[0] == self._prefill_gen
                and {i for i, _ in decoding} <= set(self._chain[1])
            )
            if chain_ok:
                tokens_dev = self._chain[2]
            else:
                # stale/absent chain (post-error recovery): rebuild the
                # feed from HOST tokens. In-flight values must land
                # first — s.next_input() indexes into out_ids the
                # deferred segment has not delivered yet.
                h, b = self._harvest_segment()
                acct["harvest_ms"] += h
                acct["host_ms"] += b
                if pre:
                    h, b = self._harvest_prefill(pre, prefill_ids)
                    acct["harvest_ms"] += h
                    acct["host_ms"] += b
                    pre = []
                acct["flushes"] += 1
                acct["rebuilds"] += 1
                decoding = [
                    (i, s) for i, s in decoding
                    if self._slots[i] is s and self._rem(s) > 0
                ]
                tokens = np.zeros((self.max_batch, 1), np.int32)
                for i, s in decoding:
                    tokens[i, 0] = s.next_input()
                tokens_dev = jnp.asarray(tokens)
        if decoding and self._paged:
            # block growth for the segment's k appends; on exhaustion the
            # reserve preempts-and-requeues victims, and rows that still
            # cannot grow sit this dispatch out (their device pos mirror
            # stays put, so the skipped steps never happened for them)
            with self._cv:
                decoding = self._reserve_decode_locked(decoding, k)
        if decoding:
            # injected device fault mid-flight: raising here exercises the
            # _loop recovery contract (fail in-flight slots, rebuild the
            # donated cache, reset the pipeline, keep serving)
            chaos.check("serving.dispatch")
            fp = temps.tobytes()
            if self._temps_cache is None or self._temps_cache[0] != fp:
                self._temps_cache = (fp, jnp.asarray(temps))
            if self._paged:
                self._cache["pos"] = self._upload_mirror(self._pos_host)
                self._cache["bt"] = self._upload_mirror(self._bt_host)
            t0 = time.perf_counter()
            toks, last, self._key, self._cache = self._segment_fn(k, greedy)(
                vp, self._cache, tokens_dev,
                self._temps_cache[1], self._key,
            )
            acct["dispatch_ms"] += (time.perf_counter() - t0) * 1e3
            self._chain = (
                self._prefill_gen, tuple(i for i, _ in decoding), last
            )
            sched = []
            with self._cv:
                for i, s in decoding:
                    take = min(k, self._rem(s))
                    s.pending += take
                    s.fed += take
                    sched.append((i, s, take))
                    if self._paged:
                        # scheduled rows advance k steps on device; rows
                        # NOT scheduled keep their mirror (the upload
                        # before the next dispatch rewinds device pos)
                        self._pos_host[i] = min(
                            int(self._pos_host[i]) + k, self.max_seq - 1
                        )
                self._pipe["inflight"] = 1
            new_pending = {"toks": toks, "sched": sched, "k": k, "t0": t0}
            acct["segments"] += 1

        # ---- harvest: segment N-1's ids (then prefill's first tokens)
        # while segment N runs on device — the overlap window
        if self._pending is not None:
            if new_pending is not None:
                acct["overlapped"] = True
                acct["deferred"] += 1
            else:
                acct["flushes"] += 1  # pipeline drains this tick
            h, b = self._harvest_segment()
            acct["harvest_ms"] += h
            acct["host_ms"] += b
        if pre:
            h, b = self._harvest_prefill(pre, prefill_ids)
            acct["harvest_ms"] += h
            acct["host_ms"] += b
        self._pending = new_pending
        self._commit_tick(acct, (time.perf_counter() - t_tick) * 1e3)
        return False


def make_handler(engine: LlamaEngine, model_name: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            log.debug(fmt, *args)

        def _json(self, code: int, payload: dict,
                  headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path, _, qs = self.path.partition("?")
            if path == "/healthz":
                self._json(200, {"status": "ok"})
            elif path == "/v1/stats":
                self._json(200, engine.stats())
            elif path == "/v1/trace":
                # flight-recorder pull: this replica's retained spans,
                # optionally filtered to one trace (the router's
                # _flight_record and scripts/tracemerge.py read this)
                q = urllib.parse.parse_qs(qs)
                tid = (q.get("trace_id") or [""])[0]
                limit = int((q.get("limit") or ["0"])[0] or 0)
                spans = TRACER.trace_spans(tid) if tid else TRACER.spans()
                if limit > 0:
                    spans = spans[-limit:]
                self._json(200, {
                    "enabled": TRACER.enabled,
                    "spans": [span_to_dict(s) for s in spans],
                })
            elif path == "/metrics":
                body = engine.metrics.registry.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/models":
                self._json(200, {
                    "models": [{
                        "name": model_name,
                        "max_seq": engine.max_seq,
                        "params": engine.cfg.num_params(),
                        "versions": engine.versions(),
                    }]
                })
            else:
                self._json(404, {"error": "not found"})

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length", "0"))
            return json.loads(self.rfile.read(length) or b"{}")

        def do_POST(self):
            if self.path == "/v1/cancel":
                # hedge-loser cancellation (router): vacate the request's
                # queue entry / batch row so the loser never holds a slot
                try:
                    req = self._read_json()
                    ok = engine.cancel(str(req.get("request_id", "")))
                    self._json(200, {"cancelled": ok})
                except Exception as e:
                    self._json(400, {"error": str(e)})
                return
            if self.path == "/admin/drain":
                # stop admission, finish in-flight; the router/controller
                # polls /v1/stats "draining" + active_slots to know when
                # deleting the pod severs nothing
                engine.drain()
                self._json(200, {"draining": True})
                return
            if self.path == "/admin/load_version":
                # weight hot-swap: build v(N+1) off to the side, commit
                # only a complete tree; a failed load leaves the serving
                # versions untouched (never a torn state)
                try:
                    req = self._read_json()
                    engine.load_version(
                        str(req.get("version", "")),
                        str(req.get("ckpt_dir", "")),
                    )
                    self._json(200, engine.versions())
                except ValueError as e:
                    self._json(400, {"error": str(e), "load_failed": True})
                except Exception as e:
                    self._json(500, {"error": str(e), "load_failed": True})
                return
            if self.path == "/admin/activate_version":
                try:
                    req = self._read_json()
                    prev = engine.activate_version(
                        str(req.get("version", ""))
                    )
                    out = engine.versions()
                    out["previous"] = prev
                    self._json(200, out)
                except Exception as e:
                    self._json(400, {"error": str(e)})
                return
            if self.path == "/admin/retire_version":
                try:
                    req = self._read_json()
                    known = engine.retire_version(
                        str(req.get("version", ""))
                    )
                    out = engine.versions()
                    out["retired"] = known
                    self._json(200, out)
                except Exception as e:
                    self._json(400, {"error": str(e)})
                return
            if self.path == "/v1/prefill":
                # prefill-pool leg of a disaggregated request: runs the
                # whole-prompt prefill + first-token sample and answers
                # with the serialized KVHandoff (octet-stream)
                from kubedl_tpu.serving.disagg import HandoffError

                try:
                    req = self._read_json()
                    timeout_s = 600.0
                    deadline_hdr = self.headers.get("X-Deadline-Ms")
                    if deadline_hdr is not None:
                        timeout_s = float(deadline_hdr) / 1000.0
                        if timeout_s <= 0:
                            self._json(504, {"error": "deadline exceeded"})
                            return
                    h = engine.prefill_handoff(
                        req.get("prompt_ids", []),
                        int(req.get("max_tokens", 16)),
                        float(req.get("temperature", 0.0)),
                        timeout_s=timeout_s,
                        cache_prefix=bool(req.get("cache_prefix", False)),
                        request_id=str(req.get("request_id", "")),
                        trace=parse_trace_header(
                            self.headers.get(TRACE_HEADER)
                        ),
                        model_version=str(req.get("model_version", "")),
                    )
                    body = h.to_bytes()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except EngineOverloaded as e:
                    self._json(
                        503,
                        {"error": str(e), "shed": True, "reason": e.reason},
                        headers={
                            "Retry-After": str(int(e.retry_after_s + 0.999))
                        },
                    )
                except HandoffError as e:
                    self._json(
                        502, {"error": str(e), "handoff_failed": True}
                    )
                except Exception as e:
                    self._json(400, {"error": str(e)})
                return
            if self.path == "/v1/adopt":
                # decode-pool leg: body is the serialized KVHandoff; the
                # response is a standard generate() result
                from kubedl_tpu.serving.disagg import KVHandoff

                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    h = KVHandoff.from_bytes(self.rfile.read(length))
                    timeout_s = 600.0
                    deadline_hdr = self.headers.get("X-Deadline-Ms")
                    if deadline_hdr is not None:
                        timeout_s = float(deadline_hdr) / 1000.0
                        if timeout_s <= 0:
                            self._json(504, {"error": "deadline exceeded"})
                            return
                    result = engine.adopt_handoff(
                        h, timeout_s=timeout_s,
                        trace=parse_trace_header(
                            self.headers.get(TRACE_HEADER)
                        ),
                    )
                    if result.get("handoff_failed"):
                        self._json(502, result)
                        return
                    if result.get("timed_out") and deadline_hdr is not None:
                        self._json(504, {"error": "deadline exceeded"})
                        return
                    self._json(200, result)
                except EngineOverloaded as e:
                    self._json(
                        503,
                        {"error": str(e), "shed": True, "reason": e.reason},
                        headers={
                            "Retry-After": str(int(e.retry_after_s + 0.999))
                        },
                    )
                except Exception as e:
                    self._json(400, {"error": str(e)})
                return
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                req = self._read_json()
                # end-to-end deadline propagation: the router forwards the
                # client's REMAINING budget in X-Deadline-Ms; an already-
                # expired budget is a 504 without touching the engine
                timeout_s = 600.0
                deadline_hdr = self.headers.get("X-Deadline-Ms")
                if deadline_hdr is not None:
                    timeout_s = float(deadline_hdr) / 1000.0
                    if timeout_s <= 0:
                        self._json(504, {"error": "deadline exceeded"})
                        return
                dbg = req.get("debug")
                result = engine.generate(
                    req.get("prompt_ids", []),
                    int(req.get("max_tokens", 16)),
                    float(req.get("temperature", 0.0)),
                    timeout_s=timeout_s,
                    cache_prefix=bool(req.get("cache_prefix", False)),
                    request_id=str(req.get("request_id", "")),
                    trace=parse_trace_header(
                        self.headers.get(TRACE_HEADER)
                    ),
                    debug_trace=bool(
                        isinstance(dbg, dict) and dbg.get("trace")
                    ),
                    model_version=str(req.get("model_version", "")),
                )
                if result.get("timed_out") and deadline_hdr is not None:
                    self._json(504, {"error": "deadline exceeded"})
                    return
                self._json(200, result)
            except UnknownModelVersion as e:
                self._json(400, {"error": str(e), "unknown_version": True})
            except EngineOverloaded as e:
                self._json(
                    503, {"error": str(e), "shed": True, "reason": e.reason},
                    headers={"Retry-After": str(int(e.retry_after_s + 0.999))},
                )
            except Exception as e:  # serving must not die on a bad request
                self._json(400, {"error": str(e)})

    return Handler


def engine_kwargs(cfg: Dict, ckpt_dir: str) -> Dict:
    """How KUBEDL_SERVE_CONFIG maps onto the engine (kept separate so the
    config->engine plumbing is testable without binding a server)."""
    return {
        "preset": cfg.get(
            "preset", os.environ.get("KUBEDL_SERVE_PRESET", "tiny")
        ),
        "ckpt_dir": ckpt_dir,
        "max_batch": int(cfg.get("max_batch", 4)),
        "quantize": cfg.get(
            "quantize", os.environ.get("KUBEDL_SERVE_QUANTIZE", "")
        ),
        "mesh_axes": cfg.get("mesh") or None,
        "max_queue_depth": int(cfg.get("max_queue_depth", 64)),
        "max_queue_age_s": float(cfg.get("max_queue_age_s", 30.0)),
        "prefix_cache_mb": float(cfg.get("prefix_cache_mb", 64.0)),
        "kv_layout": cfg.get(
            "kv_layout", os.environ.get("KUBEDL_SERVE_KV_LAYOUT", "paged")
        ),
        "kv_block_size": int(cfg.get("kv_block_size", 16)),
        "kv_blocks": int(cfg.get("kv_blocks", 0)),
        "spec_k": int(
            cfg.get("spec_k", os.environ.get("KUBEDL_SERVE_SPEC_K", "0"))
        ),
        "spec_draft": cfg.get(
            "spec_draft", os.environ.get("KUBEDL_SERVE_SPEC_DRAFT", "ngram")
        ),
        "kv_attention": cfg.get(
            "kv_attention",
            os.environ.get("KUBEDL_SERVE_KV_ATTENTION", "gather"),
        ),
        "spec_candidates": int(
            cfg.get(
                "spec_candidates",
                os.environ.get("KUBEDL_SERVE_SPEC_CANDIDATES", "1"),
            )
        ),
        "spec_draft_layers": int(cfg.get("spec_draft_layers", 0)),
        "spec_tree": bool(
            cfg.get(
                "spec_tree",
                os.environ.get("KUBEDL_SERVE_SPEC_TREE", "") == "1",
            )
        ),
        "prefill_chunk_tokens": int(
            cfg.get(
                "prefill_chunk_tokens",
                os.environ.get("KUBEDL_SERVE_PREFILL_CHUNK", "0"),
            )
        ),
        "role": cfg.get(
            "role", os.environ.get("KUBEDL_SERVE_ROLE", "colocated")
        ),
        "advertise_prefix_len": int(cfg.get("advertise_prefix_len", 8)),
        "model_version": cfg.get(
            "model_version",
            os.environ.get("KUBEDL_SERVE_MODEL_VERSION", "base"),
        ),
    }


def serve_main(env: Optional[Dict[str, str]] = None) -> int:
    """Container entrypoint (ThreadRuntime-compatible)."""
    from kubedl_tpu.utils.envguard import apply_env

    # changed-vars only: unconditional environ writes race native getenv
    # from XLA threads on gang restart (utils/envguard.py, rule KTL003)
    apply_env(env)
    from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested

    ensure_cpu_if_requested()
    from kubedl_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    cfg = json.loads(os.environ.get("KUBEDL_SERVE_CONFIG", "{}"))
    ckpt = os.environ.get("KUBEDL_MODEL_PATH", "")
    if ckpt:
        from kubedl_tpu.remote.client import is_remote_root

        if is_remote_root(ckpt):
            # remote artifact: mirror the blob prefix locally, serve that
            # (predictors may run on any host — VERDICT r2 missing #6)
            import hashlib
            import tempfile

            cache = os.path.join(
                tempfile.gettempdir(),
                f"kubedl-serve-cache-{os.getuid()}",
                hashlib.sha256(ckpt.encode()).hexdigest()[:16],
            )
            os.makedirs(cache, exist_ok=True)
            from kubedl_tpu.remote.client import download_tree

            n = download_tree(ckpt, cache)
            log.info("fetched %d blobs from %s", n, ckpt)
            ckpt = cache
    port = int(cfg.get("port", 8080))
    # bind address: loopback by default (process pods), configurable for
    # cross-host deployments (round-2 weak #6: a hard-coded 127.0.0.1
    # contradicted the k8s deployment story)
    host = cfg.get("host") or os.environ.get("KUBEDL_SERVE_HOST", "127.0.0.1")
    if cfg.get("chaos"):
        # seeded fault schedule for THIS replica (chaos drills against
        # subprocess fleets can't share an in-process context manager);
        # same seed -> same fault trace, like every armed plan
        plan = chaos.plan_from_config(cfg["chaos"])
        chaos.arm(plan)
        log.info("armed chaos plan seed=%d sites=%s", plan.seed,
                 sorted(cfg["chaos"].get("sites") or {}))
    kwargs = engine_kwargs(cfg, ckpt)
    engine = LlamaEngine(**kwargs)
    model_name = cfg.get("model_name", kwargs["preset"])
    server = ThreadingHTTPServer(
        (host, port), make_handler(engine, model_name)
    )
    log.info("serving %s on :%d", model_name, port)

    drain_grace = float(cfg.get("drain_grace_s", 10.0))

    def graceful_stop() -> None:
        # graceful drain: stop admission (distinguishable 503), let every
        # queued/in-flight decode finish (bounded by drain_grace_s), THEN
        # stop serving — a SIGTERM from a canary shift or scale-down never
        # severs an in-flight stream
        engine.drain()
        engine.wait_drained(drain_grace)
        server.shutdown()

    try:
        import signal

        signal.signal(
            signal.SIGTERM,
            lambda *_: threading.Thread(
                target=graceful_stop, daemon=True
            ).start(),
        )
    except (ValueError, OSError):
        pass  # not the main thread (ThreadRuntime): cancel event below

    cancel = (env or {}).get("_KUBEDL_CANCEL")
    if cancel is not None:
        def watch():
            cancel.wait()
            graceful_stop()

        threading.Thread(target=watch, daemon=True).start()
    try:
        server.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        engine.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())

"""JAX inference server: the workload a JAX-framework predictor pod runs.

TPU-native serving path (BASELINE.md target 5): loads the checkpoint the
lineage pipeline published (KUBEDL_MODEL_PATH), jit-compiles the static-
shape KV-cache decode step ONCE (`llama.decode_step` — pre-allocated cache,
no retracing), and serves greedy decoding over HTTP:

- GET  /healthz            -> {"status": "ok"}
- GET  /v1/models          -> model metadata
- POST /v1/generate        -> {"prompt_ids": [...], "max_tokens": N}
                              -> {"token_ids": [...], "latency_ms": ...}

Runs under either container runtime: entrypoint
"kubedl_tpu.serving.server:serve_main" (ThreadRuntime) or
`python -m kubedl_tpu.serving.server` (SubprocessRuntime).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

log = logging.getLogger("kubedl_tpu.serving.server")


class LlamaEngine:
    """Single-model greedy-decode engine around llama.decode_step."""

    def __init__(self, preset: str = "tiny", ckpt_dir: str = "",
                 batch: int = 1, max_seq: int = 0) -> None:
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.training import checkpoint

        self.cfg = llama.preset(preset)
        self.max_seq = max_seq or min(self.cfg.max_seq, 512)
        self.batch = batch
        params = llama.llama_init(jax.random.PRNGKey(0), self.cfg)
        if ckpt_dir and checkpoint.latest_step(ckpt_dir) is not None:
            state = checkpoint.restore_checkpoint(ckpt_dir, {"params": params})
            if state is not None:
                params = state["params"]
                log.info("restored checkpoint from %s", ckpt_dir)
        self.params = params
        self._llama = llama
        self._jax = jax
        self._decode = jax.jit(
            lambda p, c, t: llama.decode_step(p, c, t, self.cfg)
        )
        self._lock = threading.Lock()  # one sequence at a time per engine
        # warm the compile cache so first request isn't a compile stall
        self._warmup()

    def _warmup(self) -> None:
        import jax.numpy as jnp

        cache = self._llama.init_cache(self.cfg, self.batch, self.max_seq)
        logits, cache = self._decode(
            self.params, cache, jnp.zeros((self.batch, 1), jnp.int32)
        )
        self._jax.block_until_ready(logits)

    def generate(self, prompt_ids, max_tokens: int = 16) -> Dict:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        with self._lock:
            cache = self._llama.init_cache(self.cfg, self.batch, self.max_seq)
            budget = self.max_seq - 1
            prompt = list(prompt_ids)[:budget]
            out_ids = []
            logits = None
            # prefill token-by-token through the decode step (static shapes;
            # a chunked prefill kernel is a later optimization)
            for tok in prompt:
                tokens = jnp.full((self.batch, 1), int(tok), jnp.int32)
                logits, cache = self._decode(self.params, cache, tokens)
            n_new = max(0, min(max_tokens, budget - len(prompt)))
            for _ in range(n_new):
                if logits is None:
                    break
                nxt = int(logits[0].argmax())
                out_ids.append(nxt)
                tokens = jnp.full((self.batch, 1), nxt, jnp.int32)
                logits, cache = self._decode(self.params, cache, tokens)
        ms = (time.perf_counter() - t0) * 1e3
        return {
            "token_ids": out_ids,
            "prompt_len": len(prompt),
            "latency_ms": round(ms, 2),
            "tokens_per_sec": round(len(out_ids) / (ms / 1e3), 2) if ms > 0 else 0.0,
        }


def make_handler(engine: LlamaEngine, model_name: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            log.debug(fmt, *args)

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._json(200, {"status": "ok"})
            elif self.path == "/v1/models":
                self._json(200, {
                    "models": [{
                        "name": model_name,
                        "max_seq": engine.max_seq,
                        "params": engine.cfg.num_params(),
                    }]
                })
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                result = engine.generate(
                    req.get("prompt_ids", []),
                    int(req.get("max_tokens", 16)),
                )
                self._json(200, result)
            except Exception as e:  # serving must not die on a bad request
                self._json(400, {"error": str(e)})

    return Handler


def serve_main(env: Optional[Dict[str, str]] = None) -> int:
    """Container entrypoint (ThreadRuntime-compatible)."""
    if env:
        os.environ.update({k: v for k, v in env.items() if isinstance(v, str)})
    from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested

    ensure_cpu_if_requested()

    cfg = json.loads(os.environ.get("KUBEDL_SERVE_CONFIG", "{}"))
    ckpt = os.environ.get("KUBEDL_MODEL_PATH", "")
    port = int(cfg.get("port", 8080))
    preset = cfg.get("preset", os.environ.get("KUBEDL_SERVE_PRESET", "tiny"))
    engine = LlamaEngine(preset=preset, ckpt_dir=ckpt)
    server = ThreadingHTTPServer(
        ("127.0.0.1", port), make_handler(engine, cfg.get("model_name", preset))
    )
    log.info("serving %s on :%d", cfg.get("model_name", preset), port)

    cancel = (env or {}).get("_KUBEDL_CANCEL")
    if cancel is not None:
        def watch():
            cancel.wait()
            server.shutdown()

        threading.Thread(target=watch, daemon=True).start()
    try:
        server.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())

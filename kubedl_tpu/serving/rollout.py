"""SLO-burn-gated canary rollout with auto-rollback.

The model-lifecycle tentpole's control loop (docs/serving.md "Model
lifecycle"): shifting traffic to a new model version is a *rollout*, not
a weight edit. The controller walks the canary through a weight ladder
(1 -> 10 -> 50 -> 100 by default), soaking at each step, and gates every
advance on the canary's OWN error-budget burn — the router partitions
its SLO tracking per model version (``ServingRouter.version_tracker``),
so a canary melting down at 1% weight cannot hide inside a healthy
aggregate. The decision rules:

- **Advance** — the soak timer elapsed at the current step and no
  gating burn alert fires on the canary partition.
- **Promote** — the soak at the final step (100) elapsed clean: the
  canary owns all traffic and the rollout is Complete.
- **Rollback** — the canary partition's burn alert at the gating
  severity fires (BOTH windows above threshold — the same SRE
  multi-window rule the fleet pages on). Rollback is ONE weight flip
  back to the baseline: in-flight canary requests finish (version
  stickiness — a request never changes version mid-flight), new
  requests route to the baseline, and the engines' drain-then-evict
  hot-swap reclaims the canary weights once the last row drains.

A rolled-back version is **fenced**: ``begin()`` refuses to promote it
again until an operator calls ``clear_fence`` — an auto-rollback that
could be auto-retried would flap the fleet against a genuinely bad
model. The ``RolledBack`` condition carries the burning severity, the
offending window pair with their burn rates, and the tracker's
last-bad-trace-id exemplar, so the postmortem starts from the condition
itself (``/v1/trace?trace_id=...``), not from log archaeology.

Everything is clock-injectable; the verify drive
(scripts/verify-drives/drive_rollout.py) runs the loop in real time over
a real subprocess fleet with a seeded latency fault in the canary.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("kubedl_tpu.serving.rollout")

#: The default canary weight ladder (percent of traffic).
DEFAULT_STEPS: Tuple[int, ...] = (1, 10, 50, 100)

#: RolloutController.phase values.
PENDING = "Pending"
PROGRESSING = "Progressing"
COMPLETE = "Complete"
ROLLED_BACK = "RolledBack"


class RolloutFenced(Exception):
    """begin() refused: the canary version was auto-rolled-back before
    and its fence has not been manually cleared."""


class RolloutController:
    """Drives one canary rollout of ``canary_version`` against
    ``baseline_version`` on a :class:`ServingRouter`.

    ``tick()`` is the whole control loop — call it on any cadence (the
    drive uses ~1s; a k8s controller would hang it off its resync). Each
    tick refreshes the canary's SLO partition, publishes the per-version
    burning gauges, and takes at most one action: rollback, advance, or
    promote. ``severity`` picks which burn-alert pair gates the rollout
    (default ``page`` — the 14.4x 5m+1h pair under default alerts).
    """

    def __init__(
        self,
        router,
        canary_version: str,
        baseline_version: str,
        steps: Sequence[int] = DEFAULT_STEPS,
        soak_s: float = 60.0,
        severity: str = "page",
        clock=time.monotonic,
    ) -> None:
        if not steps or list(steps) != sorted(set(int(s) for s in steps)):
            raise ValueError(f"steps must be increasing, got {steps!r}")
        if int(steps[-1]) != 100:
            raise ValueError(f"final step must be 100, got {steps!r}")
        if any(not 0 < int(s) <= 100 for s in steps):
            raise ValueError(f"steps must be in (0,100], got {steps!r}")
        if canary_version == baseline_version:
            raise ValueError("canary and baseline must differ")
        self.router = router
        self.canary = str(canary_version)
        self.baseline = str(baseline_version)
        self.steps = tuple(int(s) for s in steps)
        self.soak_s = float(soak_s)
        self.severity = str(severity)
        self.clock = clock
        self.phase = PENDING
        self.step_idx = -1
        self.conditions: List[Dict] = []
        self._step_started = 0.0
        #: version -> the RolledBack condition that fenced it; survives
        #: phase resets on this controller, cleared only by clear_fence()
        self._fenced: Dict[str, Dict] = {}

    # -- lifecycle ---------------------------------------------------------

    def begin(self) -> None:
        """Start the rollout at the first ladder step. Raises
        :class:`RolloutFenced` if the canary was rolled back before and
        nobody cleared the fence."""
        if self.canary in self._fenced:
            raise RolloutFenced(
                f"version {self.canary} was auto-rolled-back "
                f"({self._fenced[self.canary].get('message', '')}); "
                f"clear_fence() to re-promote"
            )
        if self.phase == PROGRESSING:
            return
        self.phase = PROGRESSING
        self.step_idx = 0
        self._step_started = self.clock()
        self._apply_step()
        self._condition("Progressing", "True", "RolloutStarted",
                        f"canary {self.canary} at weight {self.steps[0]}")

    def _apply_step(self) -> None:
        w = self.steps[self.step_idx]
        self.router.set_version_weights({
            self.baseline: 100 - w, self.canary: w,
        })
        log.info("rollout: %s at weight %d (baseline %s at %d)",
                 self.canary, w, self.baseline, 100 - w)

    # -- the control loop --------------------------------------------------

    def tick(self) -> str:
        """One decision: returns ``rolled_back`` | ``advanced`` |
        ``promoted`` | ``soaking`` | ``idle``."""
        if self.phase != PROGRESSING:
            return "idle"
        tracker = self.router.version_tracker(self.canary)
        tracker.refresh()
        burning = self._publish_burning(tracker)
        if burning is not None:
            self._rollback(tracker, burning)
            return "rolled_back"
        if self.clock() - self._step_started < self.soak_s:
            return "soaking"
        if self.step_idx + 1 < len(self.steps):
            self.step_idx += 1
            self._step_started = self.clock()
            self._apply_step()
            self.router.metrics.rollout_events.inc(event="advance")
            self._condition("Progressing", "True", "StepAdvanced",
                            f"canary {self.canary} at weight "
                            f"{self.steps[self.step_idx]}")
            return "advanced"
        # soaked clean at 100: the canary IS the fleet now
        self.phase = COMPLETE
        self.router.metrics.rollout_events.inc(event="promote")
        self._condition("Complete", "True", "Promoted",
                        f"{self.canary} serving 100% after clean soak")
        log.info("rollout: promoted %s", self.canary)
        return "promoted"

    def _publish_burning(self, tracker):
        """Export per-version burning gauges; return the gating alert if
        it fires on the canary partition (both windows above threshold)."""
        gating = None
        m = self.router.metrics
        base_tr = self.router.version_tracker(self.baseline)
        for alert in tracker.alerts:
            hot = tracker.burning(alert)
            m.version_burning.set(1.0 if hot else 0.0,
                                  version=self.canary,
                                  severity=alert.severity)
            m.version_burning.set(
                1.0 if base_tr.burning(alert) else 0.0,
                version=self.baseline, severity=alert.severity)
            if hot and alert.severity == self.severity and gating is None:
                gating = alert
        return gating

    def _rollback(self, tracker, alert) -> None:
        """ONE weight flip back to the baseline, then fence the canary."""
        short_rate = tracker.burn_rate(alert.short_s)
        long_rate = tracker.burn_rate(alert.long_s)
        self.router.set_version_weights({self.baseline: 100, self.canary: 0})
        self.router.metrics.rollout_events.inc(event="rollback")
        self.phase = ROLLED_BACK
        cond = self._condition(
            "RolledBack", "True", "SLOBurn",
            f"canary {self.canary} burning at severity {alert.severity}: "
            f"burn {short_rate:.1f}x over {int(alert.short_s)}s and "
            f"{long_rate:.1f}x over {int(alert.long_s)}s "
            f"(threshold {alert.threshold}x); "
            f"exemplar trace_id={tracker.last_bad_trace_id or 'none'}",
            severity=alert.severity,
            short_s=alert.short_s, long_s=alert.long_s,
            short_burn=round(short_rate, 2), long_burn=round(long_rate, 2),
            threshold=alert.threshold,
            trace_id=tracker.last_bad_trace_id,
        )
        self._fenced[self.canary] = cond
        log.warning("rollout: rolled back %s (%s)",
                    self.canary, cond["message"])

    # -- fencing -----------------------------------------------------------

    def fenced(self) -> Dict[str, Dict]:
        """Version -> the RolledBack condition that fenced it."""
        return dict(self._fenced)

    def clear_fence(self, version: Optional[str] = None) -> bool:
        """Manual operator action: allow a rolled-back version to be
        promoted again. Returns whether a fence was cleared."""
        version = str(version or self.canary)
        if self._fenced.pop(version, None) is None:
            return False
        self.router.metrics.rollout_events.inc(event="fence_cleared")
        if self.phase == ROLLED_BACK and version == self.canary:
            self.phase = PENDING
            self.step_idx = -1
        log.info("rollout: fence cleared for %s", version)
        return True

    # -- introspection -----------------------------------------------------

    def _condition(self, ctype: str, status: str, reason: str,
                   message: str, **extra) -> Dict:
        cond = {"type": ctype, "status": status, "reason": reason,
                "message": message, "clock": self.clock(), **extra}
        self.conditions.append(cond)
        return cond

    def status(self) -> Dict:
        weight = (self.steps[self.step_idx]
                  if 0 <= self.step_idx < len(self.steps) else 0)
        return {
            "phase": self.phase,
            "canary": self.canary,
            "baseline": self.baseline,
            "step": self.step_idx,
            "weight": weight if self.phase in (PROGRESSING, COMPLETE) else 0,
            "steps": list(self.steps),
            "soak_s": self.soak_s,
            "severity": self.severity,
            "fenced": sorted(self._fenced),
            "conditions": list(self.conditions),
        }

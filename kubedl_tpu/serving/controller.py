"""Inference controller: predictor deployments + canary traffic.

Reference: controllers/serving/inference_controller.go — reconcile flow:
entry Service (:279-336) -> per-predictor Deployment gated on the model
image being built (:149-204, predictor.go:37-115) -> weighted VirtualService
across predictors (:206-274). Here "Deployment" is a replicated pod set the
controller levels itself (the engine's diff-by-index pattern, scoped to
predictors), and the VirtualService is a TrafficPolicy object.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from kubedl_tpu.api import constants
from kubedl_tpu.core.manager import ControllerManager, EventRecorder
from kubedl_tpu.core.objects import (
    BaseObject,
    OwnerRef,
    Pod,
    PodPhase,
    Port,
    Service,
)
from kubedl_tpu.core.store import AlreadyExists, NotFound, ObjectStore
from kubedl_tpu.lineage.types import Model, ModelVersion, ModelVersionPhase
from kubedl_tpu.serving.framework import apply_setter
from kubedl_tpu.serving.types import (
    Inference,
    Predictor,
    PredictorStatus,
    TrafficPolicy,
    TrafficRoute,
)

log = logging.getLogger("kubedl_tpu.serving")

LABEL_INFERENCE = constants.API_GROUP + "/inference-name"
LABEL_PREDICTOR = constants.API_GROUP + "/predictor-name"

#: entry service ports (reference: :279-336 — 8080 http / 9000 grpc)
HTTP_PORT = 8080
GRPC_PORT = 9000


def http_qps_probe(port: int = 8080, timeout: float = 2.0):
    """Default load probe for real deployments: GET the engine's /v1/stats
    on the pod's IP (falls back to loopback for process pods). Returns the
    full stats dict so the autoscaler sees queue depth alongside QPS —
    a replica with requests WAITING for a batch slot must never be judged
    idle just because its completion rate is momentarily low."""
    import json as _json
    import urllib.request

    def probe(pod) -> Optional[Dict]:
        host = getattr(pod.status, "pod_ip", "") or "127.0.0.1"
        with urllib.request.urlopen(
            f"http://{host}:{port}/v1/stats", timeout=timeout
        ) as r:
            return _json.loads(r.read())

    return probe


class InferenceController:
    NAME = "inference-controller"

    #: seconds between autoscale changes for one predictor (flap damping)
    AUTOSCALE_COOLDOWN = 30.0

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        local_addresses: bool = False,
        cluster_domain: str = "",
        qps_probe=None,
        clock=None,
        compile_cache_dir: str = "",
    ) -> None:
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        self.local_addresses = local_addresses
        self.cluster_domain = cluster_domain
        #: injected into predictor pods so replica scale-ups / restarts
        #: deserialize the decode/prefill programs instead of recompiling
        self.compile_cache_dir = compile_cache_dir
        #: qps_probe(pod) -> Optional[float]: live QPS of one predictor
        #: replica (the /v1/stats "qps" field). Transport is
        #: deployment-specific, so it's injected; None disables
        #: target_qps-driven scaling (min/max clamping still applies).
        self.qps_probe = qps_probe
        import time as _time

        self.clock = clock or _time.time
        self._last_scale: Dict[tuple, float] = {}

    def setup(self, manager: ControllerManager) -> None:
        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Inference", "Pod", "Service", "ModelVersion"],
            mapper=self._mapper,
        )

    def _mapper(self, event: str, obj: BaseObject, old):
        if obj.kind == "Inference":
            return [(obj.metadata.namespace, obj.metadata.name)]
        if obj.kind in ("Pod", "Service"):
            name = obj.metadata.labels.get(LABEL_INFERENCE)
            return [(obj.metadata.namespace, name)] if name else []
        if obj.kind == "ModelVersion":
            # an artifact finishing its build may unblock predictors
            return [
                (inf.metadata.namespace, inf.metadata.name)
                for inf in self.store.list("Inference", obj.metadata.namespace)
            ]
        return []

    # ------------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        inf = self.store.try_get("Inference", name, namespace)
        if inf is None:
            for key in [k for k in self._last_scale
                        if k[0] == namespace and k[1] == name]:
                self._last_scale.pop(key, None)
            return None
        assert isinstance(inf, Inference)

        self._sync_entry_service(inf)
        pods = self._pods_of(inf)
        statuses: Dict[str, PredictorStatus] = {}
        ready_weights: Dict[str, int] = {}
        for pred in inf.predictors:
            status = self._sync_predictor(inf, pred, pods)
            statuses[pred.name] = status
            if status.ready_replicas > 0:
                ready_weights[pred.name] = max(pred.traffic_weight, 0)
        self._gc_removed_predictors(inf, pods)
        self._sync_traffic(inf, ready_weights)
        self._update_status(inf, statuses)
        if self.qps_probe is not None and any(
            p.autoscale is not None and p.autoscale.target_qps
            for p in inf.predictors
        ):
            return 10.0  # autoscale needs a periodic signal sweep
        return None

    # ---------------------------------------------------------- services

    def _entry_host(self, inf: Inference) -> str:
        if self.local_addresses:
            return "127.0.0.1"
        base = f"{inf.metadata.name}.{inf.metadata.namespace}.svc"
        return f"{base}.{self.cluster_domain}" if self.cluster_domain else base

    def _sync_entry_service(self, inf: Inference) -> None:
        """Entry service fronting every predictor (reference :279-336)."""
        existing = self.store.try_get(
            "Service", inf.metadata.name, inf.metadata.namespace
        )
        if existing is not None:
            return
        svc = Service()
        svc.metadata.name = inf.metadata.name
        svc.metadata.namespace = inf.metadata.namespace
        svc.metadata.labels = {LABEL_INFERENCE: inf.metadata.name}
        svc.metadata.owner_refs.append(self._owner(inf))
        svc.spec.selector = {LABEL_INFERENCE: inf.metadata.name}
        svc.spec.ports = [Port("http", HTTP_PORT), Port("grpc", GRPC_PORT)]
        try:
            self.store.create(svc)
        except AlreadyExists:
            pass

    # --------------------------------------------------------- predictors

    def _resolve_model_version(
        self, inf: Inference, pred: Predictor
    ) -> Optional[ModelVersion]:
        ns = inf.metadata.namespace
        if pred.model_version:
            mv = self.store.try_get("ModelVersion", pred.model_version, ns)
            return mv if isinstance(mv, ModelVersion) else None
        if pred.model_name:
            model = self.store.try_get("Model", pred.model_name, ns)
            if isinstance(model, Model) and model.latest_version:
                mv = self.store.try_get("ModelVersion", model.latest_version, ns)
                return mv if isinstance(mv, ModelVersion) else None
        return None

    def _sync_predictor(
        self, inf: Inference, pred: Predictor, pods: List[Pod]
    ) -> PredictorStatus:
        """One predictor = a leveled replica set, gated on the artifact
        being built (reference :149-204)."""
        mv = self._resolve_model_version(inf, pred)
        if mv is None:
            return PredictorStatus(message="model version not found")
        if mv.phase != ModelVersionPhase.SUCCEEDED:
            # reference: predictor deployment waits for the image build
            return PredictorStatus(
                message=f"waiting for artifact build ({mv.phase.value})"
            )

        self._sync_predictor_service(inf, pred)
        replicas = self._desired_replicas(inf, pred, pods)
        mine = [
            p for p in pods
            if p.metadata.labels.get(LABEL_PREDICTOR) == pred.name
        ]
        have = {
            int(p.metadata.labels.get(constants.LABEL_REPLICA_INDEX, "-1")): p
            for p in mine
        }
        for i in range(replicas):
            if i in have:
                continue
            pod = self._new_predictor_pod(inf, pred, mv, i)
            try:
                self.store.create(pod)
            except AlreadyExists:
                pass
        for i, p in have.items():
            if i >= replicas:
                self.store.try_delete("Pod", p.metadata.name, p.metadata.namespace)
        ready = sum(1 for p in mine if p.status.phase == PodPhase.RUNNING)
        return PredictorStatus(
            replicas=replicas, ready_replicas=ready, image=mv.image
        )

    def _desired_replicas(self, inf: Inference, pred: Predictor,
                          pods: List[Pod]) -> int:
        """Replica target: spec count, clamped to the autoscale window, and
        — when a QPS probe is wired and target_qps is set — driven by the
        live load (ceil(total_qps / target_qps)) with a scale-down
        cooldown. The reference only STUBS autoScale in its API
        (inference_types.go:96-104); here it closes the loop."""
        import math

        a = pred.autoscale
        if a is None:
            return pred.replicas
        clamped = min(max(pred.replicas, a.min_replicas), a.max_replicas)
        if self.qps_probe is None or not a.target_qps:
            return clamped
        mine_running = [
            p for p in pods
            if p.metadata.labels.get(LABEL_PREDICTOR) == pred.name
            and p.status.phase == PodPhase.RUNNING
        ]
        prev = inf.predictor_statuses.get(pred.name)
        current = prev.replicas if prev is not None and prev.replicas else clamped
        if not mine_running:
            return current
        # probe all replicas CONCURRENTLY (reconcile shares a worker pool
        # with every other controller; sequential 2s timeouts would starve
        # it) and keep failures distinct from zero load
        from concurrent.futures import ThreadPoolExecutor

        def safe_probe(p):
            # probes may return a bare QPS float (legacy) or the engine's
            # full /v1/stats dict (qps + queued queue depth). Shed requests
            # count as backlog: a replica rejecting 503s is saturated even
            # when its queue reads shallow (it never let the demand in)
            try:
                v = self.qps_probe(p)
                if v is None:
                    return None
                if isinstance(v, dict):
                    return (float(v.get("qps", 0.0)),
                            int(v.get("queued", 0))
                            + int(v.get("shed_recent", 0)))
                return (float(v), 0)
            except Exception:
                return None

        with ThreadPoolExecutor(max_workers=min(8, len(mine_running))) as ex:
            readings = list(ex.map(safe_probe, mine_running))
        healthy = [v for v in readings if v is not None]
        if not healthy:
            return current  # no signal: never act blind
        qps = sum(v[0] for v in healthy)
        queued = sum(v[1] for v in healthy)
        desired = max(1, math.ceil(qps / a.target_qps))
        desired = min(max(desired, a.min_replicas), a.max_replicas)
        key = (inf.metadata.namespace, inf.metadata.name, pred.name)
        now = self.clock()
        if desired == current:
            return current
        if desired < current and len(healthy) < len(readings):
            # HPA rule: missing metrics never justify a scale-DOWN — an
            # overloaded replica that can't answer its probe is the worst
            # moment to delete capacity
            return current
        if desired < current and queued > 0:
            # requests are waiting for batch slots somewhere in the fleet:
            # completion-rate QPS understates offered load exactly when
            # replicas saturate, so backlog vetoes the scale-down
            return current
        if desired < current and (
            now - self._last_scale.get(key, 0.0) < self.AUTOSCALE_COOLDOWN
        ):
            return current  # damp scale-down flapping
        self._last_scale[key] = now
        self.recorder.event(
            inf, "Normal", "Autoscaled",
            f"predictor {pred.name}: {current} -> {desired} replicas "
            f"(qps {qps:.2f}, target {a.target_qps})",
        )
        return desired

    def _new_predictor_pod(
        self, inf: Inference, pred: Predictor, mv: ModelVersion, index: int
    ) -> Pod:
        template = pred.template.deep_copy()
        pod = Pod(spec=template.spec)
        pod.metadata.name = f"{inf.metadata.name}-{pred.name}-{index}"
        pod.metadata.namespace = inf.metadata.namespace
        pod.metadata.labels = {
            **template.labels,
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
            constants.LABEL_REPLICA_INDEX: str(index),
        }
        pod.metadata.owner_refs.append(self._owner(inf))
        apply_setter(inf, pred, pod, mv, HTTP_PORT)
        if self.compile_cache_dir:
            main = pod.spec.main_container()
            if main.get_env(constants.ENV_COMPILE_CACHE_DIR) is None:
                main.set_env(
                    constants.ENV_COMPILE_CACHE_DIR, self.compile_cache_dir
                )
        return pod

    def _sync_predictor_service(self, inf: Inference, pred: Predictor) -> None:
        """Per-predictor backing service — the canary routes' targets
        (reference: predictor.go:37-115 Deployment+Service per predictor;
        the entry service alone cannot enforce a weighted split)."""
        name = f"{inf.metadata.name}-{pred.name}"
        if self.store.try_get("Service", name, inf.metadata.namespace) is not None:
            return
        svc = Service()
        svc.metadata.name = name
        svc.metadata.namespace = inf.metadata.namespace
        svc.metadata.labels = {
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
        }
        svc.metadata.owner_refs.append(self._owner(inf))
        svc.spec.selector = {
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
        }
        svc.spec.ports = [Port("http", HTTP_PORT)]
        try:
            self.store.create(svc)
        except AlreadyExists:
            pass

    def _gc_removed_predictors(self, inf: Inference, pods: List[Pod]) -> None:
        names = {p.name for p in inf.predictors}
        for key in [k for k in self._last_scale
                    if k[0] == inf.metadata.namespace
                    and k[1] == inf.metadata.name and k[2] not in names]:
            self._last_scale.pop(key, None)
        for pod in pods:
            pname = pod.metadata.labels.get(LABEL_PREDICTOR, "")
            if pname and pname not in names:
                self.store.try_delete("Pod", pod.metadata.name, pod.metadata.namespace)
        for svc in self.store.list(
            "Service", inf.metadata.namespace, {LABEL_INFERENCE: inf.metadata.name}
        ):
            pname = svc.metadata.labels.get(LABEL_PREDICTOR, "")
            if pname and pname not in names:
                self.store.try_delete(
                    "Service", svc.metadata.name, svc.metadata.namespace
                )

    # ------------------------------------------------------------ traffic

    def _sync_traffic(self, inf: Inference, ready_weights: Dict[str, int]) -> None:
        """Normalize weights over READY predictors into a TrafficPolicy
        (reference VirtualService :206-274: canary split must never route
        to a predictor with no backing pods)."""
        total = sum(ready_weights.values())
        routes = []
        if total > 0:
            acc = 0
            items = sorted(ready_weights.items())
            for i, (pname, w) in enumerate(items):
                pct = (100 - acc) if i == len(items) - 1 else round(w * 100 / total)
                acc += pct
                routes.append(
                    TrafficRoute(
                        predictor=pname,
                        weight=pct,
                        service=f"{inf.metadata.name}-{pname}",
                    )
                )

        def mutate(tp: TrafficPolicy) -> None:  # type: ignore[type-arg]
            tp.host = self._entry_host(inf)
            tp.routes = routes

        try:
            self.store.update_with_retry(
                "TrafficPolicy", inf.metadata.name, inf.metadata.namespace, mutate
            )
        except NotFound:
            tp = TrafficPolicy(host=self._entry_host(inf), routes=routes)
            tp.metadata.name = inf.metadata.name
            tp.metadata.namespace = inf.metadata.namespace
            tp.metadata.owner_refs.append(self._owner(inf))
            try:
                self.store.create(tp)
            except AlreadyExists:
                pass

    # ------------------------------------------------------------- status

    def _update_status(
        self, inf: Inference, statuses: Dict[str, PredictorStatus]
    ) -> None:
        endpoint = f"{self._entry_host(inf)}:{HTTP_PORT}"

        def mutate(obj: Inference) -> None:  # type: ignore[type-arg]
            obj.predictor_statuses = statuses
            obj.endpoint = endpoint

        try:
            self.store.update_with_retry(
                "Inference", inf.metadata.name, inf.metadata.namespace, mutate
            )
        except NotFound:
            pass

    # ------------------------------------------------------------ helpers

    def _pods_of(self, inf: Inference) -> List[Pod]:
        return self.store.list(  # type: ignore[return-value]
            "Pod", inf.metadata.namespace, {LABEL_INFERENCE: inf.metadata.name}
        )

    def _owner(self, inf: Inference) -> OwnerRef:
        return OwnerRef(kind=inf.kind, name=inf.metadata.name, uid=inf.metadata.uid)

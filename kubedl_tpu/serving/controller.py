"""Inference controller: predictor deployments + canary traffic.

Reference: controllers/serving/inference_controller.go — reconcile flow:
entry Service (:279-336) -> per-predictor Deployment gated on the model
image being built (:149-204, predictor.go:37-115) -> weighted VirtualService
across predictors (:206-274). Here "Deployment" is a replicated pod set the
controller levels itself (the engine's diff-by-index pattern, scoped to
predictors), and the VirtualService is a TrafficPolicy object.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from kubedl_tpu.api import constants
from kubedl_tpu.core.manager import ControllerManager, EventRecorder
from kubedl_tpu.core.objects import (
    BaseObject,
    OwnerRef,
    Pod,
    PodPhase,
    Port,
    Service,
)
from kubedl_tpu.core.store import AlreadyExists, NotFound, ObjectStore
from kubedl_tpu.lineage.types import Model, ModelVersion, ModelVersionPhase
from kubedl_tpu.serving.framework import apply_setter
from kubedl_tpu.serving.types import (
    Inference,
    Predictor,
    PredictorStatus,
    TrafficPolicy,
    TrafficRoute,
)

log = logging.getLogger("kubedl_tpu.serving")

LABEL_INFERENCE = constants.API_GROUP + "/inference-name"
LABEL_PREDICTOR = constants.API_GROUP + "/predictor-name"
#: disaggregated serving role (prefill|decode|colocated) — the router's
#: sync_from_store partitions its replica pools by this label
LABEL_ROLE = constants.API_GROUP + "/serving-role"

#: entry service ports (reference: :279-336 — 8080 http / 9000 grpc)
HTTP_PORT = 8080
GRPC_PORT = 9000


def http_qps_probe(port: int = 8080, timeout: float = 2.0):
    """Default load probe for real deployments: GET the engine's /v1/stats
    on the pod's IP (falls back to loopback for process pods). Returns the
    full stats dict so the autoscaler sees queue depth alongside QPS —
    a replica with requests WAITING for a batch slot must never be judged
    idle just because its completion rate is momentarily low."""
    import json as _json
    import urllib.request

    def probe(pod) -> Optional[Dict]:
        host = getattr(pod.status, "pod_ip", "") or "127.0.0.1"
        with urllib.request.urlopen(
            f"http://{host}:{port}/v1/stats", timeout=timeout
        ) as r:
            return _json.loads(r.read())

    return probe


def http_drain_hook(port: int = 8080, timeout: float = 2.0):
    """Default drain trigger for real deployments: POST the engine's
    /admin/drain on the pod's IP. The engine stops admission (503 with
    ``reason: draining``) but finishes in-flight decodes — the controller
    deletes the pod only once it reports idle (or the grace expires)."""
    import urllib.request

    def drain(pod) -> None:
        host = getattr(pod.status, "pod_ip", "") or "127.0.0.1"
        req = urllib.request.Request(
            f"http://{host}:{port}/admin/drain", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=timeout).read()

    return drain


class InferenceController:
    NAME = "inference-controller"

    #: seconds between autoscale changes for one predictor (flap damping)
    AUTOSCALE_COOLDOWN = 30.0

    #: consecutive stats-probe failures before a RUNNING pod surfaces as
    #: NotReady in the predictor status (+ event + metric)
    PROBE_NOTREADY_THRESHOLD = 3

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        local_addresses: bool = False,
        cluster_domain: str = "",
        qps_probe=None,
        clock=None,
        compile_cache_dir: str = "",
        metrics=None,
        drain_grace_s: float = 0.0,
        drain_hook=None,
    ) -> None:
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        self.local_addresses = local_addresses
        self.cluster_domain = cluster_domain
        #: injected into predictor pods so replica scale-ups / restarts
        #: deserialize the decode/prefill programs instead of recompiling
        self.compile_cache_dir = compile_cache_dir
        #: qps_probe(pod) -> Optional[float]: live QPS of one predictor
        #: replica (the /v1/stats "qps" field). Transport is
        #: deployment-specific, so it's injected; None disables
        #: target_qps-driven scaling (min/max clamping still applies).
        self.qps_probe = qps_probe
        #: ServingMetrics-compatible sink for probe_failures /
        #: replicas_not_ready; None disables the metric side
        self.metrics = metrics
        #: graceful drain window for scale-down/GC: > 0 means a retiring
        #: RUNNING pod is first told to drain (drain_hook + annotation)
        #: and deleted only once idle or past the grace — a canary shift
        #: never severs an in-flight stream. 0 preserves delete-on-sight.
        self.drain_grace_s = float(drain_grace_s)
        #: drain_hook(pod): tell one replica to stop admission (e.g.
        #: http_drain_hook). None with drain_grace_s > 0 still delays
        #: deletion by the grace/idle check — the router's probe sees
        #: the pod disappear only after its streams finish.
        self.drain_hook = drain_hook
        import time as _time

        self.clock = clock or _time.time
        self._last_scale: Dict[tuple, float] = {}
        #: pod name -> consecutive stats-probe failures (the silent
        #: swallowing fix: failures surface instead of dropping replicas
        #: out of the QPS math unnoticed)
        self._probe_failures: Dict[str, int] = {}
        #: set by _retire_pod during a reconcile when a pod is mid-drain
        #: (the reconcile returns a short requeue to finish the job)
        self._drain_wait = False

    def setup(self, manager: ControllerManager) -> None:
        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Inference", "Pod", "Service", "ModelVersion"],
            mapper=self._mapper,
        )

    def _mapper(self, event: str, obj: BaseObject, old):
        if obj.kind == "Inference":
            return [(obj.metadata.namespace, obj.metadata.name)]
        if obj.kind in ("Pod", "Service"):
            name = obj.metadata.labels.get(LABEL_INFERENCE)
            return [(obj.metadata.namespace, name)] if name else []
        if obj.kind == "ModelVersion":
            # an artifact finishing its build may unblock predictors
            return [
                (inf.metadata.namespace, inf.metadata.name)
                for inf in self.store.list("Inference", obj.metadata.namespace)
            ]
        return []

    # ------------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        inf = self.store.try_get("Inference", name, namespace)
        if inf is None:
            for key in [k for k in self._last_scale
                        if k[0] == namespace and k[1] == name]:
                self._last_scale.pop(key, None)
            return None
        assert isinstance(inf, Inference)

        self._sync_entry_service(inf)
        pods = self._pods_of(inf)
        # probe-failure bookkeeping follows the pod set: a deleted pod
        # must not leave a stale NotReady count behind
        live = {p.metadata.name for p in pods}
        prefix = f"{inf.metadata.name}-"
        for k in [k for k in self._probe_failures
                  if k.startswith(prefix) and k not in live]:
            self._probe_failures.pop(k, None)
        self._drain_wait = False
        statuses: Dict[str, PredictorStatus] = {}
        ready_weights: Dict[str, int] = {}
        for pred in inf.predictors:
            status = self._sync_predictor(inf, pred, pods)
            statuses[pred.name] = status
            if status.ready_replicas > 0:
                ready_weights[pred.name] = max(pred.traffic_weight, 0)
        self._gc_removed_predictors(inf, pods)
        self._sync_traffic(inf, ready_weights)
        self._update_status(inf, statuses)
        if self.metrics is not None:
            self.metrics.replicas_not_ready.set(
                float(sum(len(s.not_ready) for s in statuses.values())),
                inference=inf.metadata.name,
            )
        if self._drain_wait:
            return 1.0  # a retiring pod is mid-drain: come back soon
        if self.qps_probe is not None and any(
            p.autoscale is not None and p.autoscale.target_qps
            for p in inf.predictors
        ):
            return 10.0  # autoscale needs a periodic signal sweep
        return None

    # ---------------------------------------------------------- services

    def _entry_host(self, inf: Inference) -> str:
        if self.local_addresses:
            return "127.0.0.1"
        base = f"{inf.metadata.name}.{inf.metadata.namespace}.svc"
        return f"{base}.{self.cluster_domain}" if self.cluster_domain else base

    def _sync_entry_service(self, inf: Inference) -> None:
        """Entry service fronting every predictor (reference :279-336)."""
        existing = self.store.try_get(
            "Service", inf.metadata.name, inf.metadata.namespace
        )
        if existing is not None:
            return
        svc = Service()
        svc.metadata.name = inf.metadata.name
        svc.metadata.namespace = inf.metadata.namespace
        svc.metadata.labels = {LABEL_INFERENCE: inf.metadata.name}
        svc.metadata.owner_refs.append(self._owner(inf))
        svc.spec.selector = {LABEL_INFERENCE: inf.metadata.name}
        svc.spec.ports = [Port("http", HTTP_PORT), Port("grpc", GRPC_PORT)]
        try:
            self.store.create(svc)
        except AlreadyExists:
            pass

    # --------------------------------------------------------- predictors

    def _resolve_model_version(
        self, inf: Inference, pred: Predictor
    ) -> Optional[ModelVersion]:
        ns = inf.metadata.namespace
        if pred.model_version:
            mv = self.store.try_get("ModelVersion", pred.model_version, ns)
            return mv if isinstance(mv, ModelVersion) else None
        if pred.model_name:
            model = self.store.try_get("Model", pred.model_name, ns)
            if isinstance(model, Model) and model.latest_version:
                mv = self.store.try_get("ModelVersion", model.latest_version, ns)
                return mv if isinstance(mv, ModelVersion) else None
        return None

    def _sync_predictor(
        self, inf: Inference, pred: Predictor, pods: List[Pod]
    ) -> PredictorStatus:
        """One predictor = a leveled replica set, gated on the artifact
        being built (reference :149-204)."""
        mv = self._resolve_model_version(inf, pred)
        if mv is None:
            return PredictorStatus(message="model version not found")
        if mv.phase != ModelVersionPhase.SUCCEEDED:
            # reference: predictor deployment waits for the image build
            return PredictorStatus(
                message=f"waiting for artifact build ({mv.phase.value})"
            )

        self._sync_predictor_service(inf, pred)
        replicas = self._desired_replicas(inf, pred, pods)
        mine = [
            p for p in pods
            if p.metadata.labels.get(LABEL_PREDICTOR) == pred.name
        ]
        have = {
            int(p.metadata.labels.get(constants.LABEL_REPLICA_INDEX, "-1")): p
            for p in mine
        }
        for i in range(replicas):
            if i in have:
                continue
            pod = self._new_predictor_pod(inf, pred, mv, i)
            try:
                self.store.create(pod)
            except AlreadyExists:
                pass
        for i, p in have.items():
            if i >= replicas:
                self._retire_pod(inf, p)
        ready = sum(1 for p in mine if p.status.phase == PodPhase.RUNNING)
        not_ready = sorted(
            p.metadata.name for p in mine
            if p.status.phase == PodPhase.RUNNING
            and self._probe_failures.get(p.metadata.name, 0)
            >= self.PROBE_NOTREADY_THRESHOLD
        )
        return PredictorStatus(
            replicas=replicas, ready_replicas=ready, image=mv.image,
            not_ready=not_ready,
            message=(
                f"{len(not_ready)} replica(s) NotReady (stats probe "
                f"failing)" if not_ready else ""
            ),
        )

    def _retire_pod(self, inf: Inference, pod: Pod) -> bool:
        """Remove a pod that scale-down/GC no longer wants — gracefully
        when a drain window is configured: first sight stamps a drain
        annotation and triggers ``drain_hook`` (the engine stops admission
        but finishes in-flight decodes); the pod is deleted only once its
        stats report idle, or the grace expires. Returns True once the
        pod is actually deleted."""
        from kubedl_tpu.federation.actuation import assert_fenced_actuation

        # fenced actuation (KTL011): scale-down/GC reaps kill processes
        assert_fenced_actuation(
            self.store, inf.metadata.namespace, inf.metadata.name,
            action="pod delete",
        )
        if (self.drain_grace_s <= 0
                or pod.status.phase != PodPhase.RUNNING):
            self.store.try_delete(
                "Pod", pod.metadata.name, pod.metadata.namespace
            )
            return True
        started = pod.metadata.annotations.get(
            constants.ANNOTATION_DRAIN_STARTED
        )
        now = self.clock()
        if started is None:
            if self.drain_hook is not None:
                try:
                    self.drain_hook(pod)
                except Exception:
                    log.warning("drain hook failed for %s",
                                pod.metadata.name, exc_info=True)

            def mutate(p: Pod) -> None:
                p.metadata.annotations[
                    constants.ANNOTATION_DRAIN_STARTED
                ] = repr(now)

            try:
                self.store.update_with_retry(
                    "Pod", pod.metadata.name, pod.metadata.namespace, mutate
                )
            except NotFound:
                return True
            self.recorder.event(
                inf, "Normal", "Draining",
                f"pod {pod.metadata.name} draining before removal "
                f"(grace {self.drain_grace_s:.0f}s)",
            )
            self._drain_wait = True
            return False
        drained = False
        if self.qps_probe is not None:
            try:
                st = self.qps_probe(pod)
                if isinstance(st, dict):
                    drained = (int(st.get("active_slots", 0)) == 0
                               and int(st.get("queued", 0)) == 0)
            except Exception:
                drained = True  # unreachable: nothing left to sever
        if drained or now - float(started) >= self.drain_grace_s:
            self.store.try_delete(
                "Pod", pod.metadata.name, pod.metadata.namespace
            )
            return True
        self._drain_wait = True
        return False

    def _desired_replicas(self, inf: Inference, pred: Predictor,
                          pods: List[Pod]) -> int:
        """Replica target: spec count, clamped to the autoscale window, and
        — when a QPS probe is wired and target_qps is set — driven by the
        live load (ceil(total_qps / target_qps)) with a scale-down
        cooldown. The reference only STUBS autoScale in its API
        (inference_types.go:96-104); here it closes the loop."""
        import math

        a = pred.autoscale
        if a is None:
            return pred.replicas
        clamped = min(max(pred.replicas, a.min_replicas), a.max_replicas)
        if self.qps_probe is None or not a.target_qps:
            return clamped
        mine_running = [
            p for p in pods
            if p.metadata.labels.get(LABEL_PREDICTOR) == pred.name
            and p.status.phase == PodPhase.RUNNING
        ]
        prev = inf.predictor_statuses.get(pred.name)
        current = prev.replicas if prev is not None and prev.replicas else clamped
        if not mine_running:
            return current
        # probe all replicas CONCURRENTLY (reconcile shares a worker pool
        # with every other controller; sequential 2s timeouts would starve
        # it) and keep failures distinct from zero load
        from concurrent.futures import ThreadPoolExecutor

        def safe_probe(p):
            # probes may return a bare QPS float (legacy) or the engine's
            # full /v1/stats dict (qps + queued queue depth). Shed requests
            # count as backlog: a replica rejecting 503s is saturated even
            # when its queue reads shallow (it never let the demand in)
            try:
                v = self.qps_probe(p)
                if v is None:
                    return None
                if isinstance(v, dict):
                    return (float(v.get("qps", 0.0)),
                            int(v.get("queued", 0))
                            + int(v.get("shed_recent", 0)))
                return (float(v), 0)
            except Exception:
                return None

        with ThreadPoolExecutor(max_workers=min(8, len(mine_running))) as ex:
            readings = list(ex.map(safe_probe, mine_running))
        # failures SURFACE instead of silently dropping out of the QPS
        # math: consecutive failures per pod feed a NotReady predictor
        # condition (threshold crossing fires one event) + metric
        for p, v in zip(mine_running, readings):
            pname = p.metadata.name
            if v is None:
                n = self._probe_failures.get(pname, 0) + 1
                self._probe_failures[pname] = n
                if self.metrics is not None:
                    self.metrics.probe_failures.inc(pod=pname)
                if n == self.PROBE_NOTREADY_THRESHOLD:
                    self.recorder.event(
                        inf, "Warning", "ReplicaNotReady",
                        f"predictor {pred.name} pod {pname}: {n} "
                        f"consecutive stats-probe failures",
                    )
            else:
                self._probe_failures.pop(pname, None)
        healthy = [v for v in readings if v is not None]
        if not healthy:
            return current  # no signal: never act blind
        qps = sum(v[0] for v in healthy)
        queued = sum(v[1] for v in healthy)
        desired = max(1, math.ceil(qps / a.target_qps))
        desired = min(max(desired, a.min_replicas), a.max_replicas)
        key = (inf.metadata.namespace, inf.metadata.name, pred.name)
        now = self.clock()
        if desired == current:
            return current
        if desired < current and len(healthy) < len(readings):
            # HPA rule: missing metrics never justify a scale-DOWN — an
            # overloaded replica that can't answer its probe is the worst
            # moment to delete capacity
            return current
        if desired < current and queued > 0:
            # requests are waiting for batch slots somewhere in the fleet:
            # completion-rate QPS understates offered load exactly when
            # replicas saturate, so backlog vetoes the scale-down
            return current
        if desired < current and (
            now - self._last_scale.get(key, 0.0) < self.AUTOSCALE_COOLDOWN
        ):
            return current  # damp scale-down flapping
        self._last_scale[key] = now
        self.recorder.event(
            inf, "Normal", "Autoscaled",
            f"predictor {pred.name}: {current} -> {desired} replicas "
            f"(qps {qps:.2f}, target {a.target_qps})",
        )
        return desired

    def _new_predictor_pod(
        self, inf: Inference, pred: Predictor, mv: ModelVersion, index: int
    ) -> Pod:
        template = pred.template.deep_copy()
        pod = Pod(spec=template.spec)
        pod.metadata.name = f"{inf.metadata.name}-{pred.name}-{index}"
        pod.metadata.namespace = inf.metadata.namespace
        pod.metadata.labels = {
            **template.labels,
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
            constants.LABEL_REPLICA_INDEX: str(index),
        }
        if getattr(pred, "role", ""):
            pod.metadata.labels[LABEL_ROLE] = pred.role
        pod.metadata.owner_refs.append(self._owner(inf))
        apply_setter(inf, pred, pod, mv, HTTP_PORT)
        if self.compile_cache_dir:
            main = pod.spec.main_container()
            if main.get_env(constants.ENV_COMPILE_CACHE_DIR) is None:
                main.set_env(
                    constants.ENV_COMPILE_CACHE_DIR, self.compile_cache_dir
                )
        return pod

    def _sync_predictor_service(self, inf: Inference, pred: Predictor) -> None:
        """Per-predictor backing service — the canary routes' targets
        (reference: predictor.go:37-115 Deployment+Service per predictor;
        the entry service alone cannot enforce a weighted split)."""
        name = f"{inf.metadata.name}-{pred.name}"
        if self.store.try_get("Service", name, inf.metadata.namespace) is not None:
            return
        svc = Service()
        svc.metadata.name = name
        svc.metadata.namespace = inf.metadata.namespace
        svc.metadata.labels = {
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
        }
        svc.metadata.owner_refs.append(self._owner(inf))
        svc.spec.selector = {
            LABEL_INFERENCE: inf.metadata.name,
            LABEL_PREDICTOR: pred.name,
        }
        svc.spec.ports = [Port("http", HTTP_PORT)]
        try:
            self.store.create(svc)
        except AlreadyExists:
            pass

    def _gc_removed_predictors(self, inf: Inference, pods: List[Pod]) -> None:
        names = {p.name for p in inf.predictors}
        for key in [k for k in self._last_scale
                    if k[0] == inf.metadata.namespace
                    and k[1] == inf.metadata.name and k[2] not in names]:
            self._last_scale.pop(key, None)
        for pod in pods:
            pname = pod.metadata.labels.get(LABEL_PREDICTOR, "")
            if pname and pname not in names:
                # GC takes the same graceful path as scale-down: a canary
                # being withdrawn still finishes its in-flight streams
                self._retire_pod(inf, pod)
        for svc in self.store.list(
            "Service", inf.metadata.namespace, {LABEL_INFERENCE: inf.metadata.name}
        ):
            pname = svc.metadata.labels.get(LABEL_PREDICTOR, "")
            if pname and pname not in names:
                self.store.try_delete(
                    "Service", svc.metadata.name, svc.metadata.namespace
                )

    # ------------------------------------------------------------ traffic

    def _sync_traffic(self, inf: Inference, ready_weights: Dict[str, int]) -> None:
        """Normalize weights over READY predictors into a TrafficPolicy
        (reference VirtualService :206-274: canary split must never route
        to a predictor with no backing pods)."""
        total = sum(ready_weights.values())
        routes = []
        if total > 0:
            acc = 0
            items = sorted(ready_weights.items())
            for i, (pname, w) in enumerate(items):
                pct = (100 - acc) if i == len(items) - 1 else round(w * 100 / total)
                acc += pct
                routes.append(
                    TrafficRoute(
                        predictor=pname,
                        weight=pct,
                        service=f"{inf.metadata.name}-{pname}",
                    )
                )

        def mutate(tp: TrafficPolicy) -> None:  # type: ignore[type-arg]
            tp.host = self._entry_host(inf)
            tp.routes = routes

        try:
            self.store.update_with_retry(
                "TrafficPolicy", inf.metadata.name, inf.metadata.namespace, mutate
            )
        except NotFound:
            tp = TrafficPolicy(host=self._entry_host(inf), routes=routes)
            tp.metadata.name = inf.metadata.name
            tp.metadata.namespace = inf.metadata.namespace
            tp.metadata.owner_refs.append(self._owner(inf))
            try:
                self.store.create(tp)
            except AlreadyExists:
                pass

    # ------------------------------------------------------------- status

    def _update_status(
        self, inf: Inference, statuses: Dict[str, PredictorStatus]
    ) -> None:
        endpoint = f"{self._entry_host(inf)}:{HTTP_PORT}"

        def mutate(obj: Inference) -> None:  # type: ignore[type-arg]
            obj.predictor_statuses = statuses
            obj.endpoint = endpoint

        try:
            self.store.update_with_retry(
                "Inference", inf.metadata.name, inf.metadata.namespace, mutate
            )
        except NotFound:
            pass

    # ------------------------------------------------------------ helpers

    def _pods_of(self, inf: Inference) -> List[Pod]:
        return self.store.list(  # type: ignore[return-value]
            "Pod", inf.metadata.namespace, {LABEL_INFERENCE: inf.metadata.name}
        )

    def _owner(self, inf: Inference) -> OwnerRef:
        return OwnerRef(kind=inf.kind, name=inf.metadata.name, uid=inf.metadata.uid)

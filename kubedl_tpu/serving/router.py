"""Fault-tolerant serving router: N replicas behind one endpoint.

The routing tier ROADMAP open item 1 calls for: an HTTP front that makes
single-replica death a non-event. One `LlamaEngine` server is a single
point of failure — a crash loses every queued request and a canary shift
severs in-flight streams. The router owns the tail-at-scale mechanics
(PAPERS.md; docs/serving.md "Router"):

- **Health + circuit breakers** — an active prober GETs each replica's
  `/v1/stats` (the same signal `http_qps_probe` reads); K consecutive
  failures eject the replica (breaker OPEN), a half-open probe readmits
  it when it answers again. Request-path transport errors feed the same
  breaker, so detection is bounded by the probe interval, not by it.
- **Deadline propagation** — the client budget rides `X-Deadline-Ms` to
  the engine (mapped onto `generate(timeout_s=...)`); every retry/hedge
  re-computes the REMAINING budget and an expired budget is a 504
  without ever dispatching.
- **Retry budgets** — failovers honor the engine's 503 + `Retry-After`
  shed contract (no dispatch to a shedding replica before its window)
  and spend a token-bucket budget (`router_policy.RetryBudget`), so
  retries cannot amplify a fleet-wide overload.
- **Hedging** — after a p95-based delay the request is duplicated to a
  second replica; first answer wins, the loser is cancelled via the
  engine's `/v1/cancel` so it releases its queue slot.
- **Graceful drain** — SIGTERM (or `drain()`) stops admission with a
  distinguishable 503 (`reason: draining`), finishes in-flight requests,
  then the server exits; replicas that report `"draining"` stop
  receiving new work but keep their in-flight streams.
- **Prefix affinity** — consistent hashing on the observed prompt prefix
  (falling back to least-loaded) keeps PR 4's per-engine prefix KV cache
  hot across the fleet; replicas that ADVERTISE a prompt's prefix digest
  (``stats()["prefix_cache"]["advertised"]``) outrank the ring owner —
  block-aware affinity routes to where the KV is resident, not where it
  would hash.
- **Disaggregated dispatch** — when the fleet has both ``prefill``- and
  ``decode``-role replicas (Predictor ``role:``, docs/serving.md), a
  request runs as two legs: ``/v1/prefill`` on the prefill pool returns
  a serialized ``KVHandoff``; ``/v1/adopt`` on a decode replica resumes
  it. Any leg failure falls back to the role-blind colocated path —
  prefill/decode roles are advisory, every engine still serves
  ``/v1/generate`` — so a decode-pool outage degrades, never 503s the
  fleet.
- **Per-tenant QoS** — the ``X-Tenant`` header maps to a class
  (``qos:`` config block); a weighted-fair queue arbitrates dispatch
  slots (smooth weighted round-robin) and sheds lowest-priority-first
  on overflow with a distinguishable 503 (``reason: qos_shed``),
  composing with the engines' own KV-watermark sheds.
- **Model-version canary split** — ``set_version_weights`` declares a
  per-version traffic split (the TrafficPolicy weight idea, one level
  down); untagged requests get a version from a deterministic smooth
  weighted round-robin, the tag rides ``body["model_version"]`` into
  every retry/hedge/disagg leg (sticky: a request never flips version
  mid-flight), and each version feeds its OWN SLOTracker partition so
  the rollout controller (kubedl_tpu/serving/rollout.py) can gate
  promotion on the canary's burn rate alone.

Routing and hedging never change RESULTS: greedy outputs through the
router are bit-identical to direct engine calls (tier-1 enforced), and
the disagg path is bit-identical by the handoff-seam argument
(kubedl_tpu/serving/disagg.py).

Chaos sites (kubedl_tpu/chaos/plan.py): ``router.forward`` fails a
request forward at the transport, ``router.probe`` fails a health probe,
``router.hedge`` suppresses a hedge dispatch (degradation: the primary
still owns the request).
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from kubedl_tpu import chaos
from kubedl_tpu.observability.metrics import RouterMetrics, SLOMetrics
from kubedl_tpu.observability.slo import SLOTracker, alerts_from_config
from kubedl_tpu.observability.tracing import (
    TRACE_HEADER,
    TRACER,
    TraceContext,
    build_span_tree,
    parse_trace_header,
    span_to_dict,
)
from kubedl_tpu.serving import router_policy as policy
from kubedl_tpu.serving.disagg import QoSShed, qos_from_config

log = logging.getLogger("kubedl_tpu.serving.router")


class ReplicaDown(Exception):
    """Transport-level failure talking to a replica (crash/partition)."""


class DeadlineExceeded(Exception):
    """The request's end-to-end budget expired."""


class ReplicaShedding(Exception):
    """The replica answered 503: alive but refusing admission."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 reason: str = "overloaded") -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.reason = reason


class UpstreamError(Exception):
    """Non-retryable upstream HTTP error — passed through verbatim."""

    def __init__(self, code: int, payload: dict) -> None:
        super().__init__(f"upstream {code}")
        self.code = code
        self.payload = payload


class Replica:
    """Router-side view of one engine replica: address, breaker, and the
    load/health signals the selection policy reads."""

    def __init__(self, name: str, host: str, port: int, weight: int = 100,
                 fail_threshold: int = 3, cooldown_s: float = 2.0,
                 role: str = "colocated", model: str = "",
                 clock=time.monotonic) -> None:
        self.name = name
        self.host = host
        self.port = int(port)
        self.weight = int(weight)
        self.role = role or "colocated"
        self.model = model
        self.breaker = policy.CircuitBreaker(
            fail_threshold=fail_threshold, cooldown_s=cooldown_s, clock=clock
        )
        self._lock = threading.Lock()
        self.inflight = 0           # router-side dispatched, unanswered
        self.draining = False       # replica reported/returned draining
        self.shed_until = 0.0       # honor Retry-After: no dispatch before
        self.probe_failures = 0     # consecutive
        self.stats: Dict = {}       # last /v1/stats snapshot
        self.advertised: set = set()  # prefix digests the replica holds

    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1

    def end(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)

    def load(self) -> int:
        """Least-loaded signal: router-side in-flight plus the engine's
        own queue depth and recent sheds (a replica rejecting 503s is
        saturated even when its queue reads shallow)."""
        with self._lock:
            inflight = self.inflight
        st = self.stats
        return inflight + int(st.get("queued", 0)) + int(st.get("shed_recent", 0))


class ServingRouter:
    """The routing tier. Construct with replica specs (``(name, host,
    port)`` or ``(name, host, port, weight)`` tuples), `start()` the
    prober, and serve `handle_generate` — directly (tests) or through
    :func:`make_router_handler` (HTTP)."""

    def __init__(
        self,
        replicas: Sequence = (),
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
        eject_threshold: int = 3,
        readmit_cooldown_s: float = 2.0,
        hedge_enabled: bool = True,
        hedge_floor_ms: float = 50.0,
        hedge_default_ms: float = 1000.0,
        retry_budget_ratio: float = 0.2,
        max_retries: int = 1,
        default_deadline_ms: float = 30_000.0,
        affinity_prefix_len: int = 8,
        qos: Optional[Dict] = None,
        disagg_enabled: bool = True,
        qos_timeout_s: float = 30.0,
        slo: Optional[Dict] = None,
        version_weights: Optional[Dict[str, int]] = None,
        metrics: Optional[RouterMetrics] = None,
        clock=time.monotonic,
    ) -> None:
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.eject_threshold = int(eject_threshold)
        self.readmit_cooldown_s = float(readmit_cooldown_s)
        self.hedge_enabled = bool(hedge_enabled)
        self.hedge_floor_ms = float(hedge_floor_ms)
        #: at most ONE failover retry per request by default — the
        #: acceptance contract: only in-flight-on-a-dead-replica work is
        #: re-dispatched, at most once, inside its deadline
        self.max_retries = int(max_retries)
        self.default_deadline_ms = float(default_deadline_ms)
        self.affinity_prefix_len = int(affinity_prefix_len)
        self.disagg_enabled = bool(disagg_enabled)
        self.qos_timeout_s = float(qos_timeout_s)
        #: per-tenant QoS: None means no arbitration (every request is
        #: dispatched immediately, exactly the pre-QoS behavior)
        self.qos = qos_from_config(qos)
        self.qos_tenants: Dict[str, str] = dict(
            (qos or {}).get("tenants") or {}
        )
        self.metrics = metrics or RouterMetrics()
        self.clock = clock
        #: rolling SLO view over every handle_generate outcome, exported
        #: as kubedl_tpu_slo_* in the same registry /metrics renders
        slo_cfg = slo or {}
        self.slo = SLOTracker(
            objective=float(slo_cfg.get("objective", 0.999)),
            latency_objective_ms=slo_cfg.get(
                "latency_objective_ms", self.default_deadline_ms
            ),
            alerts=alerts_from_config(slo_cfg.get("alerts")),
            clock=clock,
            metrics=SLOMetrics(self.metrics.registry),
        )
        #: model-version canary split (rollout.py drives this): version ->
        #: traffic weight; empty means version-blind routing (requests
        #: carry whatever model_version the client set, or none)
        self._slo_cfg = dict(slo_cfg)
        self._version_weights: Dict[str, int] = {}
        self._version_wrr: Dict[str, float] = {}  # smooth-WRR current
        #: per-version SLO partition: each version gets its OWN tracker on
        #: a private SLOMetrics registry (sharing the router registry
        #: would need a version label on every kubedl_tpu_slo_* family —
        #: a label-keyset change for every existing dashboard); the
        #: aggregate self.slo keeps feeding the exported families
        self._version_slo: Dict[str, SLOTracker] = {}
        self.retry_budget = policy.RetryBudget(ratio=retry_budget_ratio)
        self.latency = policy.LatencyTracker(default_ms=hedge_default_ms)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._ring = policy.ConsistentHashRing()
        self._inflight = 0
        self._idle = threading.Condition(self._lock)
        self._draining = False
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self.set_replicas(replicas)
        if version_weights:
            self.set_version_weights(version_weights)

    # -- model-version canary split ----------------------------------------

    def set_version_weights(self, weights: Dict[str, int]) -> None:
        """Declare the model-version traffic split (``{"v1": 90, "v2":
        10}``). Reuses the TrafficPolicy weight idea one level down: the
        router tags each untagged request with a version chosen by smooth
        weighted round-robin, and the engines serve that version's weight
        tree. An empty dict turns version tagging off. Weight changes are
        atomic under the router lock — a request sees exactly one split."""
        parsed = {str(v): int(w) for v, w in (weights or {}).items()}
        if any(w < 0 for w in parsed.values()):
            raise ValueError(f"negative version weight in {parsed}")
        with self._lock:
            self._version_weights = parsed
            self._version_wrr = {v: 0.0 for v in parsed}
            for v in parsed:
                self._version_tracker_locked(v)
        for v, w in parsed.items():
            self.metrics.rollout_weight.set(float(w), version=v)

    def version_weights(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._version_weights)

    def _version_tracker_locked(self, version: str) -> SLOTracker:
        tr = self._version_slo.get(version)
        if tr is None:
            cfg = self._slo_cfg
            tr = SLOTracker(
                objective=float(cfg.get("objective", 0.999)),
                latency_objective_ms=cfg.get(
                    "latency_objective_ms", self.default_deadline_ms
                ),
                alerts=alerts_from_config(cfg.get("alerts")),
                clock=self.clock,
                metrics=SLOMetrics(),  # private registry: see __init__
            )
            self._version_slo[version] = tr
        return tr

    def version_tracker(self, version: str) -> SLOTracker:
        """The version's own SLO partition (rollout.py gates on its burn
        rates; created on first use)."""
        with self._lock:
            return self._version_tracker_locked(str(version))

    def _choose_version(self) -> str:
        """Deterministic smooth weighted round-robin over the configured
        split — the same interleave every run at the same weights, so
        canary tests are reproducible without seeding."""
        with self._lock:
            weights = self._version_weights
            total = sum(weights.values())
            if total <= 0:
                return ""
            cur = self._version_wrr
            for v, w in weights.items():
                cur[v] = cur.get(v, 0.0) + w
            # max by current, name-tiebreak for determinism across dicts
            best = max(sorted(cur), key=lambda v: cur[v])
            cur[best] -= total
            return best

    # -- fleet membership --------------------------------------------------

    def set_replicas(self, specs: Sequence) -> None:
        """Declare the replica set. Existing replicas keep their breaker/
        health state (a resync must not mass-readmit ejected replicas);
        removed names are deregistered, new ones start CLOSED. Dict specs
        may carry ``role`` (prefill|decode|colocated) and ``model``;
        tuple specs are always colocated."""
        parsed: List[Tuple[str, str, int, int, str, str]] = []
        for s in specs:
            if isinstance(s, dict):
                parsed.append((s["name"], s.get("host", "127.0.0.1"),
                               int(s["port"]), int(s.get("weight", 100)),
                               str(s.get("role", "") or "colocated"),
                               str(s.get("model", ""))))
            else:
                name, host, port = s[0], s[1], int(s[2])
                weight = int(s[3]) if len(s) > 3 else 100
                parsed.append((name, host, port, weight, "colocated", ""))
        with self._lock:
            keep = {p[0] for p in parsed}
            for name in [n for n in self._replicas if n not in keep]:
                del self._replicas[name]
            for name, host, port, weight, role, model in parsed:
                rep = self._replicas.get(name)
                if rep is None:
                    self._replicas[name] = Replica(
                        name, host, port, weight,
                        fail_threshold=self.eject_threshold,
                        cooldown_s=self.readmit_cooldown_s,
                        role=role, model=model,
                        clock=self.clock,
                    )
                else:
                    rep.host, rep.port, rep.weight = host, port, weight
                    rep.role, rep.model = role, model
            # only DECODE-capable replicas join the affinity ring: a
            # prefix pinned to a prefill-pool replica would never serve
            # a decode there
            self._ring.rebuild(sorted(
                n for n, r in self._replicas.items()
                if r.role != "prefill"
            ))

    def sync_from_store(self, store, inference_name: str,
                        namespace: str = "default") -> int:
        """Build the replica set from the control plane: RUNNING predictor
        pods of an Inference, weighted by its TrafficPolicy canary routes
        (a predictor at weight 0 stays registered but unroutable),
        PARTITIONED by (model, role) — each pod carries its Predictor's
        ``role:`` as a pod label (serving controller) and its model preset
        in KUBEDL_SERVE_CONFIG, so the router knows its prefill/decode
        pools without probing. Duplicate (host, port) endpoints are
        deduped (first pod wins — a restarted pod must not register its
        address twice). Returns the number of replicas registered."""
        from kubedl_tpu.core.objects import PodPhase
        from kubedl_tpu.serving.controller import (
            LABEL_INFERENCE, LABEL_PREDICTOR, LABEL_ROLE,
        )

        weights: Dict[str, int] = {}
        tp = store.try_get("TrafficPolicy", inference_name, namespace)
        if tp is not None:
            weights = {r.predictor: r.weight for r in tp.routes}
        specs = []
        seen_endpoints: set = set()
        for pod in store.list("Pod", namespace,
                              {LABEL_INFERENCE: inference_name}):
            if pod.status.phase != PodPhase.RUNNING:
                continue
            pred = pod.metadata.labels.get(LABEL_PREDICTOR, "")
            role = pod.metadata.labels.get(LABEL_ROLE, "") or "colocated"
            port = 8080
            model = ""
            main = pod.spec.main_container()
            cfg = main.get_env("KUBEDL_SERVE_CONFIG")
            if cfg:
                parsed = json.loads(cfg)
                port = int(parsed.get("port", port))
                model = str(parsed.get("preset", ""))
                role = str(parsed.get("role", role) or role)
            pod_ip = getattr(pod.status, "pod_ip", "")
            host = pod_ip or "127.0.0.1"
            # dedupe real endpoints only: process pods without a pod_ip
            # all share loopback but are still distinct replicas
            if pod_ip:
                if (host, port) in seen_endpoints:
                    continue
                seen_endpoints.add((host, port))
            # with a TrafficPolicy armed, absence from its routes means
            # weight 0 — NOT 100: a predictor the controller pulled from
            # rotation (weight-0 canary, not-ready) must stay registered
            # but unroutable through router restarts and breaker
            # half-open readmissions alike
            specs.append({
                "name": pod.metadata.name, "host": host, "port": port,
                "weight": weights.get(pred, 0) if tp is not None else 100,
                "role": role, "model": model,
            })
        self.set_replicas(specs)
        return len(specs)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._prober is not None:
            return
        self._stop.clear()
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True, name="router-prober"
        )
        self._prober.start()

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5)
            self._prober = None

    def drain(self, wait: bool = False, timeout_s: float = 30.0) -> bool:
        """Stop admitting (503 ``reason: draining``); with ``wait``,
        block until in-flight requests finish — then shutdown severs
        nothing."""
        with self._lock:
            self._draining = True
        if not wait:
            return True
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(timeout=min(left, 0.1))
        return True

    @property
    def draining(self) -> bool:
        return self._draining

    # -- health probing ----------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:
                log.exception("router probe sweep failed")
            self._stop.wait(self.probe_interval_s)

    def _fetch_stats(self, rep: Replica) -> Dict:
        with urllib.request.urlopen(
            f"{rep.base_url()}/v1/stats", timeout=self.probe_timeout_s
        ) as r:
            return json.loads(r.read())

    def probe_once(self) -> None:
        """One active health sweep: every replica whose breaker admits a
        call gets a `/v1/stats` GET. Success closes the breaker (readmits
        an ejected replica via its half-open trial) and refreshes the
        load/draining view; failure counts toward ejection."""
        m = self.metrics
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            br = rep.breaker
            if br.state != policy.CLOSED and not br.allow():
                continue  # OPEN and still cooling down
            try:
                chaos.check("router.probe")
                st = self._fetch_stats(rep)
            except Exception:
                rep.probe_failures += 1
                m.probe_failures.inc(replica=rep.name)
                self._record_failure(rep)
                continue
            rep.probe_failures = 0
            rep.stats = st
            rep.draining = bool(st.get("draining", False))
            st_role = st.get("role")
            if st_role:  # the engine's own view of its role wins
                rep.role = str(st_role)
            rep.advertised = set(
                st.get("prefix_cache", {}).get("advertised", []) or ()
            )
            readmitted = br.readmissions
            br.record_success()
            if br.readmissions > readmitted:
                m.readmissions.inc(replica=rep.name)
                log.info("router: readmitted replica %s", rep.name)
        avail = sum(
            1 for r in reps
            if r.breaker.state == policy.CLOSED and not r.draining
        )
        m.replicas_available.set(float(avail))
        m.replicas_draining.set(float(sum(1 for r in reps if r.draining)))

    def _record_failure(self, rep: Replica) -> None:
        ejected = rep.breaker.ejections
        rep.breaker.record_failure()
        if rep.breaker.ejections > ejected:
            self.metrics.ejections.inc(replica=rep.name)
            log.warning("router: ejected replica %s (%d consecutive failures)",
                        rep.name, rep.breaker.consecutive_failures)

    # -- request path ------------------------------------------------------

    def _select(self, body: Dict, tried: set,
                role: Optional[str] = None) -> Optional[Replica]:
        """Next replica for this request: routable (breaker CLOSED, not
        draining, not inside a Retry-After window, weight > 0, not
        already tried), ordered block-aware-affinity-first (replicas
        advertising the prompt's prefix digest), then ring owner, then
        least-loaded (router_policy.pick_replicas). ``role`` restricts
        to one pool (disagg legs); None is role-blind — the colocated
        path routes to every replica because roles are advisory."""
        now = self.clock()
        with self._lock:
            reps = list(self._replicas.values())
        candidates = {
            r.name: r.load() for r in reps
            if r.name not in tried
            and r.weight > 0
            and not r.draining
            and r.shed_until <= now
            and r.breaker.state == policy.CLOSED
            and (role is None or r.role == role)
        }
        advertised = {
            r.name: r.advertised for r in reps
            if r.name in candidates and r.advertised
        }
        order = policy.pick_replicas(
            candidates, body.get("prompt_ids", []), self._ring,
            self.affinity_prefix_len, advertised=advertised or None,
        )
        with self._lock:
            return self._replicas.get(order[0]) if order else None

    def _forward(self, rep: Replica, rid: str, body: Dict,
                 deadline: float,
                 trace: Optional[TraceContext] = None) -> Dict:
        rem = policy.remaining_ms(deadline, self.clock)
        if rem <= 0:
            raise DeadlineExceeded("budget expired before dispatch")
        try:
            chaos.check("router.forward")
        except chaos.FaultInjected as e:
            raise ReplicaDown(str(e))
        data = json.dumps({**body, "request_id": rid}).encode()
        headers = {
            "Content-Type": "application/json",
            # the engine maps this onto generate(timeout_s=...) — the
            # whole deadline story end to end
            "X-Deadline-Ms": str(int(rem)),
        }
        if trace is not None:
            # the forward span's own context: engine-side spans parent
            # under THIS attempt, so hedges stay distinguishable
            headers[TRACE_HEADER] = trace.to_header()
        req = urllib.request.Request(
            f"{rep.base_url()}/v1/generate", data=data, headers=headers,
        )
        try:
            # transport timeout slightly past the deadline: the ENGINE
            # owns deadline enforcement (504); the transport cap only
            # bounds a dead-but-connected socket
            with urllib.request.urlopen(
                req, timeout=rem / 1000.0 + 2.0
            ) as r:
                payload = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read() or b"{}")
            except Exception:
                detail = {}
            if e.code == 503:
                rep.breaker.record_success()  # alive — just refusing
                raise ReplicaShedding(
                    detail.get("error", "shed"),
                    retry_after_s=float(e.headers.get("Retry-After", "1")),
                    reason=detail.get("reason", "overloaded"),
                )
            if e.code == 504:
                rep.breaker.record_success()
                raise DeadlineExceeded(detail.get("error", "deadline"))
            raise UpstreamError(e.code, detail)
        except (OSError, urllib.error.URLError) as e:
            raise ReplicaDown(str(e))
        rep.breaker.record_success()
        return payload

    def _attempt(self, rep: Replica, rid: str, body: Dict, deadline: float,
                 out: "queue.Queue", span=None) -> None:
        try:
            res = self._forward(rep, rid, body, deadline,
                                trace=span.ctx if span is not None else None)
            if span is not None:
                span.finish(result="ok")
            out.put((rid, rep, res))
        except Exception as e:
            if span is not None:
                span.finish(result=type(e).__name__)
            out.put((rid, rep, e))
        finally:
            rep.end()

    def _cancel_attempt(self, rep: Replica, rid: str) -> None:
        """Best-effort loser cancellation: frees the loser's engine queue
        slot/row so a hedge never doubles steady-state load."""
        self.metrics.cancellations.inc()

        def go():
            try:
                data = json.dumps({"request_id": rid}).encode()
                req = urllib.request.Request(
                    f"{rep.base_url()}/v1/cancel", data=data,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=2.0).read()
            except Exception:
                pass

        threading.Thread(target=go, daemon=True).start()

    def handle_generate(self, body: Dict,
                        deadline_ms: Optional[float] = None,
                        tenant: Optional[str] = None,
                        trace: Optional[TraceContext] = None
                        ) -> Tuple[int, Dict, Dict]:
        """Route one generate request. Returns ``(status, payload,
        extra_headers)`` so it serves both the HTTP handler and direct
        in-process callers (tests/bench). ``tenant`` is the ``X-Tenant``
        header value; with a ``qos`` config it maps to a class whose
        weighted-fair queue arbitrates the dispatch slot. ``trace`` is the
        caller's parsed ``X-Trace-Context``: the whole request runs under
        a ``router.request`` root span parented beneath it, every leg
        carries the context onward, and the outcome feeds the SLO tracker
        (latency exemplar = this trace id)."""
        m = self.metrics
        if self._draining:
            m.drain_rejects.inc()
            return (503, {"error": "router draining", "shed": True,
                          "reason": "draining"}, {"Retry-After": "1"})
        debug_trace = bool(
            isinstance(body.get("debug"), dict) and body["debug"].get("trace")
        )
        # version tagging happens ONCE, here: a client-set model_version
        # is sticky as-is; an untagged request under a canary split gets
        # the WRR pick. Every retry/hedge/disagg leg below shares this
        # body dict, so the version never flips mid-request — a hedge
        # answering with different weights would be a silent model swap.
        version = str(body.get("model_version", "") or "")
        if not version and self._version_weights:
            version = self._choose_version()
            if version:
                body = dict(body)
                body["model_version"] = version
        root = TRACER.span("router.request", parent=trace)
        t0 = self.clock()
        code = 0
        try:
            with root as rattrs:
                if version:
                    rattrs["model_version"] = version
                code, payload, extra = self._dispatch(
                    body, deadline_ms, tenant, root.ctx, t0)
                rattrs["status"] = code
            if debug_trace and root.ctx is not None and code == 200:
                payload = dict(payload)
                payload["trace"] = self._flight_record(root.ctx.trace_id)
            return code, payload, extra
        finally:
            lat_ms = (self.clock() - t0) * 1e3
            tid = root.ctx.trace_id if root.ctx is not None else ""
            ok = code == 200
            self.slo.observe(ok=ok, latency_ms=lat_ms, trace_id=tid)
            if version:
                m.version_requests.inc(version=version,
                                       result="ok" if ok else "error")
                self.version_tracker(version).observe(
                    ok=ok, latency_ms=lat_ms, trace_id=tid)
            m.request_ms.observe(lat_ms, exemplar=tid or None)

    def _dispatch(self, body: Dict, deadline_ms: Optional[float],
                  tenant: Optional[str], ctx: Optional[TraceContext],
                  t0: float) -> Tuple[int, Dict, Dict]:
        m = self.metrics
        m.requests.inc()
        self.retry_budget.on_request()
        qos_cls: Optional[str] = None
        if self.qos is not None:
            cls = self.qos.resolve(tenant, self.qos_tenants)
            budget_s = (float(deadline_ms) / 1000.0
                        if deadline_ms is not None else self.qos_timeout_s)
            try:
                qos_cls = self.qos.acquire(
                    cls, timeout_s=min(budget_s, self.qos_timeout_s)
                )
            except QoSShed as e:
                m.qos_sheds.inc(qos_class=e.qos_class)
                self._update_qos_gauges()
                return (503, {"error": str(e), "shed": True,
                              "reason": "qos_shed",
                              "qos_class": e.qos_class},
                        {"Retry-After": "1"})
            self._update_qos_gauges()
        with self._lock:
            self._inflight += 1
        try:
            if self._disagg_eligible(body):
                out = self._run_disagg(body, deadline_ms, t0, ctx)
                if out is not None:
                    return out
                # colocated fallback spends the REMAINING budget, not a
                # fresh one — the failed leg's time is gone
                m.disagg_fallbacks.inc()
                TRACER.record("router.fallback", duration=0.0, trace=ctx,
                              reason="disagg_leg_failed")
                if deadline_ms is not None:
                    deadline_ms = max(
                        1.0, deadline_ms - (self.clock() - t0) * 1e3
                    )
            return self._run(body, deadline_ms, t0, ctx)
        finally:
            if qos_cls is not None:
                self.qos.release(qos_cls)
                self._update_qos_gauges()
            with self._idle:
                self._inflight -= 1
                self._idle.notify_all()

    def _update_qos_gauges(self) -> None:
        if self.qos is None:
            return
        for cls, depth in self.qos.queue_depths().items():
            self.metrics.qos_queue_depth.set(float(depth), qos_class=cls)

    def _disagg_eligible(self, body: Dict) -> bool:
        """Two-leg dispatch needs BOTH pools routable right now; anything
        else (all-colocated fleet, decode-pool outage, missing prompt)
        uses the role-blind path."""
        if not self.disagg_enabled or not body.get("prompt_ids"):
            return False
        now = self.clock()
        with self._lock:
            reps = list(self._replicas.values())
        roles = {
            r.role for r in reps
            if r.weight > 0 and not r.draining and r.shed_until <= now
            and r.breaker.state == policy.CLOSED
        }
        return "prefill" in roles and "decode" in roles

    def _run(self, body: Dict, deadline_ms: Optional[float], t0: float,
             ctx: Optional[TraceContext] = None) -> Tuple[int, Dict, Dict]:
        m = self.metrics
        budget = float(deadline_ms if deadline_ms is not None
                       else self.default_deadline_ms)
        deadline = policy.deadline_at(budget, self.clock)
        results: "queue.Queue" = queue.Queue()
        outstanding: Dict[str, Tuple[Replica, bool]] = {}
        spans: Dict[str, object] = {}  # rid -> forward span handle
        tried: set = set()
        retries = 0
        hedged = False
        last_shed: Optional[ReplicaShedding] = None

        def launch(rep: Replica, hedge: bool = False, retry: int = 0) -> None:
            rid = uuid.uuid4().hex
            # span identity exists BEFORE dispatch so the context rides the
            # forward's X-Trace-Context header; finished in _attempt, and
            # tagged winner/loser at hedge resolution
            spans[rid] = TRACER.begin("router.forward", parent=ctx,
                                      replica=rep.name, hedge=hedge,
                                      retry=retry)
            outstanding[rid] = (rep, hedge)
            tried.add(rep.name)
            rep.begin()
            threading.Thread(
                target=self._attempt,
                args=(rep, rid, body, deadline, results, spans[rid]),
                daemon=True,
            ).start()

        def tag_attempt(rid: str, outcome: str) -> None:
            sp = spans.get(rid)
            if sp is None or sp.ctx is None:
                return
            # both orders are safe: mutate the live handle (pre-finish)
            # then patch the recorded span (post-finish)
            sp.attrs["outcome"] = outcome
            TRACER.tag(sp.ctx.span_id, outcome=outcome)

        first = self._select(body, tried)
        if first is None:
            m.no_replica.inc()
            return (503, {"error": "no replica available", "shed": True,
                          "reason": "no_replica"}, {"Retry-After": "1"})
        if policy.remaining_ms(deadline, self.clock) <= 0:
            # expired budget: NEVER dispatched, not even once
            m.deadline_exceeded.inc()
            return 504, {"error": "deadline exceeded"}, {}
        launch(first)
        hedge_delay_s = (
            self.latency.hedge_delay_ms(self.hedge_floor_ms) / 1000.0
            if self.hedge_enabled else None
        )

        while True:
            rem_s = policy.remaining_ms(deadline, self.clock) / 1000.0
            if rem_s <= 0:
                # out of budget with attempts still in flight: the
                # client's answer is 504 NOW; cancel what remains
                for rid, (rep, _) in outstanding.items():
                    self._cancel_attempt(rep, rid)
                m.deadline_exceeded.inc()
                return 504, {"error": "deadline exceeded"}, {}
            timeout = rem_s
            if hedge_delay_s is not None and not hedged:
                timeout = min(
                    timeout, max(0.0, (t0 + hedge_delay_s) - self.clock())
                )
            try:
                rid, rep, outcome = results.get(timeout=timeout + 0.002)
            except queue.Empty:
                if hedge_delay_s is not None and not hedged:
                    hedged = True
                    self._maybe_hedge(body, tried, deadline, launch)
                continue
            was_hedge = outstanding.pop(rid, (rep, False))[1]

            if isinstance(outcome, dict):
                self.latency.record((self.clock() - t0) * 1e3)
                if was_hedge:
                    m.hedge_wins.inc()
                if hedged or was_hedge:
                    tag_attempt(rid, "winner")
                for orid, (orep, _) in outstanding.items():
                    tag_attempt(orid, "loser")
                    self._cancel_attempt(orep, orid)
                return 200, outcome, {}

            if isinstance(outcome, ReplicaShedding):
                if outcome.reason == "draining":
                    # deterministic signal, request never admitted: fail
                    # over for free (no budget spend, no breaker penalty)
                    rep.draining = True
                    nxt = self._select(body, tried)
                    if (nxt is not None
                            and policy.remaining_ms(deadline, self.clock) > 0):
                        launch(nxt, retry=retries)
                        continue
                else:
                    m.upstream_sheds.inc()
                    rep.shed_until = self.clock() + outcome.retry_after_s
                    last_shed = outcome
                    nxt = self._select(body, tried)
                    if (nxt is not None
                            and policy.remaining_ms(deadline, self.clock) > 0
                            and retries < self.max_retries
                            and self.retry_budget.try_spend()):
                        retries += 1
                        m.retries.inc()
                        launch(nxt, retry=retries)
                        continue
                if outstanding:
                    continue  # a hedge may still answer
                ra = last_shed.retry_after_s if last_shed else 1.0
                reason = outcome.reason
                return (503, {"error": str(outcome), "shed": True,
                              "reason": reason},
                        {"Retry-After": str(int(math.ceil(ra)))})

            if isinstance(outcome, DeadlineExceeded):
                if outstanding:
                    continue
                m.deadline_exceeded.inc()
                return 504, {"error": "deadline exceeded"}, {}

            if isinstance(outcome, UpstreamError):
                # non-retryable (bad request): the replica is fine, the
                # request is not — pass the upstream verdict through
                for orid, (orep, _) in outstanding.items():
                    self._cancel_attempt(orep, orid)
                return outcome.code, outcome.payload, {}

            # transport failure (ReplicaDown / unexpected): the replica
            # may be gone — feed the breaker, fail over within budget
            m.transport_errors.inc(replica=rep.name)
            self._record_failure(rep)
            nxt = self._select(body, tried)
            if (nxt is not None
                    and policy.remaining_ms(deadline, self.clock) > 0
                    and retries < self.max_retries
                    and self.retry_budget.try_spend()):
                retries += 1
                m.retries.inc()
                launch(nxt, retry=retries)
                continue
            if outstanding:
                continue
            return (502, {"error": f"replica {rep.name} unavailable: "
                                   f"{outcome}"}, {})

    # -- disaggregated two-leg dispatch ------------------------------------

    def _post_leg(self, rep: Replica, path: str, data: bytes,
                  content_type: str, deadline: float,
                  trace: Optional[TraceContext] = None) -> Tuple[int, bytes]:
        """One handoff leg POST. Returns (status, body bytes); raises
        ReplicaDown on transport failure, DeadlineExceeded on an expired
        budget. Non-200s come back as (code, body) for the caller to
        interpret — leg errors fall back, they never retry-storm."""
        rem = policy.remaining_ms(deadline, self.clock)
        if rem <= 0:
            raise DeadlineExceeded("budget expired before dispatch")
        try:
            chaos.check("router.forward")
        except chaos.FaultInjected as e:
            raise ReplicaDown(str(e))
        headers = {"Content-Type": content_type,
                   "X-Deadline-Ms": str(int(rem))}
        if trace is not None:
            headers[TRACE_HEADER] = trace.to_header()
        req = urllib.request.Request(
            f"{rep.base_url()}{path}", data=data, headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=rem / 1000.0 + 2.0) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            rep.breaker.record_success()  # spoke HTTP: alive
            return e.code, e.read() or b"{}"
        except (OSError, urllib.error.URLError) as e:
            raise ReplicaDown(str(e))
        rep.breaker.record_success()
        return 200, payload

    def _run_disagg(self, body: Dict, deadline_ms: Optional[float],
                    t0: float, ctx: Optional[TraceContext] = None
                    ) -> Optional[Tuple[int, Dict, Dict]]:
        """The two-leg dispatch: ``/v1/prefill`` on the prefill pool
        streams back a serialized KVHandoff; ``/v1/adopt`` on a
        block-aware-affine decode replica resumes it. Returns None
        whenever the colocated path should take over (leg transport
        failure, pool emptied mid-request, engine-side handoff failure)
        — the caller counts the fallback and re-runs role-blind. Only a
        definitive verdict (200 result, 400 bad request, expired budget)
        is returned from here."""
        m = self.metrics
        budget = float(deadline_ms if deadline_ms is not None
                       else self.default_deadline_ms)
        deadline = policy.deadline_at(budget, self.clock)

        pre = self._select(body, set(), role="prefill")
        if pre is None:
            return None
        leg1 = json.dumps({
            k: body[k] for k in
            ("prompt_ids", "max_tokens", "temperature", "cache_prefix",
             "request_id", "model_version") if k in body
        }).encode()
        pre.begin()
        leg = TRACER.span("router.prefill_leg", parent=ctx,
                          replica=pre.name)
        try:
            with leg as la:
                code, raw = self._post_leg(
                    pre, "/v1/prefill", leg1, "application/json", deadline,
                    trace=leg.ctx)
                la["status"] = code
        except DeadlineExceeded:
            m.deadline_exceeded.inc()
            return 504, {"error": "deadline exceeded"}, {}
        except ReplicaDown as e:
            m.transport_errors.inc(replica=pre.name)
            self._record_failure(pre)
            log.warning("disagg prefill leg to %s failed: %s", pre.name, e)
            return None
        finally:
            pre.end()
        if code != 200:
            self._note_leg_error(pre, code, raw)
            if code == 400:  # the request is bad, not the fleet
                return self._leg_payload(code, raw)
            return None

        dec = self._select(body, set(), role="decode")
        if dec is None:
            return None
        dec.begin()
        leg = TRACER.span("router.adopt_leg", parent=ctx, replica=dec.name)
        try:
            with leg as la:
                code, raw = self._post_leg(
                    dec, "/v1/adopt", raw, "application/octet-stream",
                    deadline, trace=leg.ctx)
                la["status"] = code
        except DeadlineExceeded:
            m.deadline_exceeded.inc()
            return 504, {"error": "deadline exceeded"}, {}
        except ReplicaDown as e:
            m.transport_errors.inc(replica=dec.name)
            self._record_failure(dec)
            log.warning("disagg adopt leg to %s failed: %s", dec.name, e)
            return None
        finally:
            dec.end()
        if code != 200:
            self._note_leg_error(dec, code, raw)
            if code == 400:
                return self._leg_payload(code, raw)
            return None
        m.disagg_requests.inc()
        self.latency.record((self.clock() - t0) * 1e3)
        return 200, json.loads(raw), {}

    def _note_leg_error(self, rep: Replica, code: int, raw: bytes) -> None:
        """Feed a leg's HTTP error into the same health signals the
        colocated path uses (shed windows, metrics) before falling back."""
        try:
            detail = json.loads(raw or b"{}")
        except Exception:
            detail = {}
        if code == 503:
            self.metrics.upstream_sheds.inc()
            rep.shed_until = self.clock() + float(
                detail.get("retry_after_s", 1.0))
        elif code == 504:
            self.metrics.deadline_exceeded.inc()

    @staticmethod
    def _leg_payload(code: int, raw: bytes) -> Tuple[int, Dict, Dict]:
        try:
            return code, json.loads(raw or b"{}"), {}
        except Exception:
            return code, {"error": raw.decode("utf-8", "replace")}, {}

    def _maybe_hedge(self, body: Dict, tried: set, deadline: float,
                     launch) -> None:
        """Fire the tail-latency hedge: a second replica gets a duplicate
        once the primary is slower than p95. Budget-gated (hedges share
        the retry budget) and chaos-testable: an injected ``router.hedge``
        fault suppresses the hedge, never the request."""
        rep = self._select(body, tried)
        if rep is None:
            return
        if policy.remaining_ms(deadline, self.clock) <= 0:
            return
        if not self.retry_budget.try_spend():
            return
        try:
            chaos.check("router.hedge")
        except chaos.FaultInjected:
            return  # degradation: no hedge this request, primary runs on
        self.metrics.hedges.inc()
        launch(rep, hedge=True)

    # -- flight recorder ---------------------------------------------------

    def _flight_record(self, trace_id: str) -> Dict:
        """The request's own span tree, inline: router-side spans from the
        local ring plus engine-side spans pulled from every replica this
        trace touched (their names ride the forward/leg span attrs) via
        ``/v1/trace?trace_id=``. Best-effort — a replica that died mid-
        request simply contributes no spans."""
        spans = [span_to_dict(s) for s in TRACER.trace_spans(trace_id)]
        touched = {
            s["attrs"].get("replica") for s in spans
            if s["attrs"].get("replica")
        }
        with self._lock:
            reps = [self._replicas[n] for n in touched if n in self._replicas]
        for rep in reps:
            try:
                with urllib.request.urlopen(
                    f"{rep.base_url()}/v1/trace?trace_id={trace_id}",
                    timeout=2.0,
                ) as r:
                    spans.extend(json.loads(r.read()).get("spans", []))
            except Exception:
                pass
        return {"trace_id": trace_id, "spans": build_span_tree(spans)}

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            reps = list(self._replicas.values())
            inflight = self._inflight
            draining = self._draining
        out: Dict = {
            "draining": draining,
            "inflight": inflight,
            "retry_budget_tokens": round(self.retry_budget.tokens, 2),
            "retries_spent": self.retry_budget.spent,
            "retries_denied": self.retry_budget.denied,
            "hedge_delay_ms": round(
                self.latency.hedge_delay_ms(self.hedge_floor_ms), 2
            ),
            "replicas": {},
            "pools": {},
        }
        for r in reps:
            out["replicas"][r.name] = {
                "url": r.base_url(),
                "state": r.breaker.state,
                "draining": r.draining,
                "weight": r.weight,
                "role": r.role,
                "model": r.model,
                "inflight": r.inflight,
                "load": r.load(),
                "advertised_prefixes": len(r.advertised),
                "probe_failures": r.probe_failures,
                "ejections": r.breaker.ejections,
                "readmissions": r.breaker.readmissions,
            }
            pool = out["pools"].setdefault(r.role, 0)
            out["pools"][r.role] = pool + 1
        if self.qos is not None:
            out["qos"] = {
                "queue_depths": self.qos.queue_depths(),
                "sheds": dict(self.qos.sheds),
                "admits": dict(self.qos.admits),
            }
        out["slo"] = self.slo.snapshot()
        with self._lock:
            vweights = dict(self._version_weights)
            vslo = dict(self._version_slo)
        if vweights or vslo:
            out["versions"] = {
                "weights": vweights,
                "slo": {v: tr.snapshot() for v, tr in vslo.items()},
            }
        return out


def make_router_handler(router: ServingRouter):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            log.debug(fmt, *args)

        def _json(self, code: int, payload: dict,
                  headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path, _, qs = self.path.partition("?")
            if path == "/healthz":
                if router.draining:
                    self._json(503, {"status": "draining"})
                else:
                    self._json(200, {"status": "ok"})
            elif path == "/v1/stats":
                self._json(200, router.stats())
            elif path == "/v1/trace":
                params = urllib.parse.parse_qs(qs)
                tid = (params.get("trace_id") or [""])[0]
                limit = int((params.get("limit") or ["1024"])[0])
                spans = (TRACER.trace_spans(tid) if tid
                         else TRACER.spans()[-limit:])
                self._json(200, {
                    "enabled": TRACER.enabled,
                    "spans": [span_to_dict(s) for s in spans],
                })
            elif self.path == "/metrics":
                body = router.metrics.registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path == "/admin/drain":
                router.drain()
                self._json(200, {"draining": True})
                return
            if self.path == "/admin/version_weights":
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    router.set_version_weights(req.get("weights") or {})
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {"weights": router.version_weights()})
                return
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
            except Exception as e:
                self._json(400, {"error": str(e)})
                return
            deadline_ms: Optional[float] = None
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr is not None:
                deadline_ms = float(hdr)
            elif "deadline_ms" in req:
                deadline_ms = float(req.pop("deadline_ms"))
            tenant = self.headers.get("X-Tenant")
            trace = parse_trace_header(self.headers.get(TRACE_HEADER))
            code, payload, extra = router.handle_generate(
                req, deadline_ms, tenant=tenant, trace=trace)
            self._json(code, payload, headers=extra)

    return Handler


def router_kwargs(cfg: Dict) -> Dict:
    """KUBEDL_ROUTER_CONFIG -> ServingRouter kwargs (separate so the
    config plumbing is testable without binding a server)."""
    out: Dict = {}
    for key, cast in (
        ("probe_interval_s", float), ("probe_timeout_s", float),
        ("eject_threshold", int), ("readmit_cooldown_s", float),
        ("hedge_enabled", bool), ("hedge_floor_ms", float),
        ("hedge_default_ms", float), ("retry_budget_ratio", float),
        ("max_retries", int), ("default_deadline_ms", float),
        ("affinity_prefix_len", int), ("disagg_enabled", bool),
        ("qos_timeout_s", float),
    ):
        if key in cfg:
            out[key] = cast(cfg[key])
    if isinstance(cfg.get("qos"), dict):
        out["qos"] = cfg["qos"]
    if isinstance(cfg.get("slo"), dict):
        out["slo"] = cfg["slo"]
    if isinstance(cfg.get("version_weights"), dict):
        out["version_weights"] = {
            str(v): int(w) for v, w in cfg["version_weights"].items()
        }
    out["replicas"] = [
        {"name": r["name"], "host": r.get("host", "127.0.0.1"),
         "port": int(r["port"]), "weight": int(r.get("weight", 100)),
         "role": r.get("role", ""), "model": r.get("model", "")}
        for r in cfg.get("replicas", [])
    ]
    return out


def serve_router_main(env: Optional[Dict[str, str]] = None) -> int:
    """Router container entrypoint (ThreadRuntime-compatible). Reads
    KUBEDL_ROUTER_CONFIG: ``{"port": ..., "replicas": [{"name": ...,
    "host": ..., "port": ...}, ...], <router knobs>}``. SIGTERM drains
    gracefully (distinguishable 503, finish in-flight, then exit)."""
    from kubedl_tpu.utils.envguard import apply_env

    # changed-vars only: unconditional environ writes race native getenv
    # from XLA threads on gang restart (utils/envguard.py, rule KTL003)
    apply_env(env)
    cfg = json.loads(os.environ.get("KUBEDL_ROUTER_CONFIG", "{}"))
    router = ServingRouter(**router_kwargs(cfg))
    router.start()
    port = int(cfg.get("port", 8081))
    host = cfg.get("host") or os.environ.get("KUBEDL_SERVE_HOST", "127.0.0.1")
    server = ThreadingHTTPServer((host, port), make_router_handler(router))
    log.info("routing %d replicas on :%d", len(cfg.get("replicas", [])), port)

    drain_grace = float(cfg.get("drain_grace_s", 10.0))

    def graceful_stop() -> None:
        router.drain(wait=True, timeout_s=drain_grace)
        server.shutdown()

    try:
        import signal

        signal.signal(
            signal.SIGTERM,
            lambda *_: threading.Thread(
                target=graceful_stop, daemon=True
            ).start(),
        )
    except (ValueError, OSError):
        pass  # not the main thread: the cancel event below drains

    cancel = (env or {}).get("_KUBEDL_CANCEL")
    if cancel is not None:
        def watch():
            cancel.wait()
            graceful_stop()

        threading.Thread(target=watch, daemon=True).start()
    try:
        server.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        router.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_router_main())

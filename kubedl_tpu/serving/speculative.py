"""Draft-k / verify-1 speculative decoding for the paged serving engine.

Decode is memory-bandwidth bound: every generated token re-reads the
whole weight set for ONE row of matmul work. Speculative decoding buys
back that bandwidth by making each target-model forward score ``k+1``
positions at once: a cheap *draft* proposes k tokens, the target scores
the whole proposal in one batched forward over the paged cache
(`llama.paged_verify`), the longest prefix where the draft agrees with
the target's own greedy choice is accepted, and one "bonus" token — the
target's argmax after the last accepted position — is emitted for free.
Every emitted token is the target's argmax given only accepted history,
so GREEDY outputs are bit-identical to plain decode by construction (the
tier-1 gate); the only thing speculation changes is how many sequential
forwards it takes to produce them. Rejected-suffix KV lands beyond the
rolled-back position and its blocks are freed in place by the engine
(docs/serving.md "Speculative decoding").

Drafts are PLUGGABLE: anything with ``propose(context, k) -> list[int]``
works. Shipped drafts:

- :class:`NgramDraft` ("ngram", the default): self-speculative prompt-
  lookup — match the tail n-gram of the context against its own earlier
  tokens and propose whatever followed the most recent match. Zero
  model cost; strong on the repetitive traffic (templated output,
  retried generations, code) where speculation pays most.
- :class:`RepeatDraft` ("repeat"): propose the last token k times — the
  degenerate baseline that still wins on run-length-heavy output.
- :class:`ScriptedDraft`: tests force exact proposal streams to pin the
  acceptance-length distribution.
- :class:`ModelDraft` ("model"): a real small-model draft — greedy
  decode k tokens from its own (smaller) weights in its own contiguous
  cache, batched across verifying rows in one prefill + one decode
  segment. :meth:`ModelDraft.from_target` carves an early-exit draft out
  of the target's own stacked layer weights (first n layers + shared
  embed/norm/head) — with the `tiny-deep` preset's zero-init deep
  residuals that pairing agrees with the target at init, the CPU-scale
  proxy for a trained draft/target pair.

Multi-candidate verification rides on :meth:`DraftModel
.propose_candidates`: N candidate continuations per row, scored by the
target in ONE read-only forward (`llama.paged_verify_multi`); the engine
re-verifies only the winner through the standard write path, so emitted
tokens stay the target's own argmax. The default implementation returns
the single `propose()` list; ModelDraft branches candidates at the first
token (top-N draft logits, greedy continuations), with candidate 0
always the pure-greedy proposal — which is why multi-candidate accepts
at least as much as single-candidate on the same seeds.

Tree speculation (:class:`DraftTree`) folds those N chains into a
prefix TRIE before verification: chains sharing a prefix share trie
nodes, so the verify window is the trie size (≤ 1 + N*k, typically far
smaller) instead of the flat N*(k+1) multi-verify rows. The target
scores every node in one read-only forward (`llama.paged_verify_tree`,
per-node ancestor mask), the host walks the deepest accepted root path
(:meth:`DraftTree.walk` — `accept_length` generalized to trees), and
the engine re-verifies that winning path through the standard write
path. Emission always comes from the write-path verify, so greedy
output stays bit-identical to plain decode at every tree shape.

A wrong draft can never corrupt output — it only wastes the verify
forward — so draft quality is purely a throughput knob, measured by the
acceptance rate the engine exports (`stats()["speculative"]` and the
``kubedl_tpu_serving_spec_*`` metrics).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence


class DraftModel:
    """Protocol for draft proposers (duck-typed; subclassing optional)."""

    name = "draft"

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        """Return up to ``k`` proposed continuation tokens for
        ``context`` (prompt + generated so far). Shorter lists are
        allowed — the engine pads the verify window with repeats of the
        last proposal and simply accepts less."""
        raise NotImplementedError

    def propose_batch(
        self, contexts: Sequence[Sequence[int]], k: int
    ) -> List[List[int]]:
        """Batched :meth:`propose` (one call per verify tick). Model
        drafts override this to amortize their forward across rows."""
        return [self.propose(ctx, k) for ctx in contexts]

    def propose_candidates(
        self, context: Sequence[int], k: int, n: int
    ) -> List[List[int]]:
        """Up to ``n`` candidate continuations for multi-candidate
        verify. Candidate 0 MUST be the plain :meth:`propose` output —
        the engine relies on that to guarantee multi-candidate never
        accepts fewer tokens than the single-candidate path."""
        return [self.propose(context, k)]


class RepeatDraft(DraftModel):
    """Propose the last context token k times: the zero-knowledge
    baseline. Wins exactly on run-length repetition."""

    name = "repeat"

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if not context:
            return []
        return [int(context[-1])] * k


class NgramDraft(DraftModel):
    """Self-speculative prompt-lookup decoding: find the most recent
    earlier occurrence of the context's tail ``n``-gram (longest match
    first, down to 1) and propose the tokens that followed it. The
    context IS the draft model — no weights, no device time."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, window: int = 1024) -> None:
        self.max_ngram = max(1, int(max_ngram))
        self.window = max(8, int(window))

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ctx = [int(t) for t in context[-self.window:]]
        n_ctx = len(ctx)
        if n_ctx < 2:
            return []
        for n in range(min(self.max_ngram, n_ctx - 1), 0, -1):
            tail = ctx[n_ctx - n:]
            # scan for the most recent PRIOR occurrence of the tail
            for i in range(n_ctx - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    out = ctx[i + n:i + n + k]
                    if out:
                        return out
                    break
        # no lookup hit: fall back to run-length repetition
        return [ctx[-1]] * k


class ScriptedDraft(DraftModel):
    """Deterministic proposal stream for tests: pops pre-seeded
    proposals in order, then falls back to repeats."""

    name = "scripted"

    def __init__(self, proposals: Sequence[Sequence[int]]) -> None:
        self._q = deque([list(p) for p in proposals])

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if self._q:
            return [int(t) for t in self._q.popleft()][:k]
        return RepeatDraft().propose(context, k)


class ModelDraft(DraftModel):
    """Small-model draft: greedy-decode ``k`` tokens from its own
    weights. Each proposal round is one batched prefill over the rows'
    recent context windows plus one greedy decode segment in a FRESH
    contiguous cache (the draft is small enough that re-prefilling a
    bounded window every round beats keeping per-row draft caches
    coherent with the target's accept/rewind churn). All jitted closures
    cache by shape; context lengths are padded to ``pad_to`` multiples
    and batch to powers of two so the engine's varying row counts reuse
    a handful of compiles."""

    name = "model"

    def __init__(self, params, cfg, max_context: int = 512,
                 pad_to: int = 32) -> None:
        import jax

        from kubedl_tpu.models import llama

        self.params = params
        self.cfg = cfg
        self.max_context = min(int(max_context), cfg.max_seq)
        self.pad_to = max(8, int(pad_to))
        self._llama = llama
        self._jnp = jax.numpy
        self._prefill = jax.jit(
            lambda p, c, t, l: llama.prefill_batched(p, c, t, l, cfg)
        )
        self._segments: Dict[int, object] = {}
        self._key = jax.random.PRNGKey(0)  # greedy: never consumed

    @classmethod
    def from_target(cls, params, cfg, n_layers: int,
                    **kwargs) -> "ModelDraft":
        """Early-exit draft: the target's first ``n_layers`` decoder
        layers (sliced off the stacked [L, ...] arrays — views, no
        copies) with the shared embedding / final norm / lm head. With
        `zero_init_deep_from <= n_layers` the deep layers are identity
        residuals and the slice IS the target; in general it is the
        standard early-exit approximation."""
        import dataclasses

        import jax

        n = max(1, min(int(n_layers), cfg.n_layers))
        draft_params = {k: v for k, v in params.items() if k != "layers"}
        # tree_map, not a dict comprehension: quantized layer leaves are
        # nested {"w", "scale"} dicts, all stacked [L, ...] on axis 0
        draft_params["layers"] = jax.tree_util.tree_map(
            lambda a: a[:n], params["layers"]
        )
        draft_cfg = dataclasses.replace(cfg, n_layers=n)
        return cls(draft_params, draft_cfg, **kwargs)

    @classmethod
    def from_zoo(cls, name: str, target_cfg, seed: int = 0,
                 ckpt_path: Optional[str] = None, **kwargs) -> "ModelDraft":
        """A *trainable* small draft shaped by the planner MODEL_ZOO
        entry ``name`` — its own weights, not a slice of the target's.
        Vocab / max_seq / dtype come from the target (the draft proposes
        target tokens); depth and widths from the zoo descriptor. Fresh
        weights propose noise — ``ckpt_path`` restores a checkpoint
        saved by :meth:`save` (e.g. after :func:`distill_draft`), which
        is what makes this the trained-draft arm of the decode bench."""
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.planner.costmodel import MODEL_ZOO

        try:
            desc = MODEL_ZOO[name]
        except KeyError:
            raise ValueError(
                f"unknown zoo draft {name!r} (have: {sorted(MODEL_ZOO)})"
            ) from None
        heads = max(1, desc.hidden // 64)
        cfg = llama.LlamaConfig(
            vocab_size=target_cfg.vocab_size, dim=desc.hidden,
            n_layers=desc.layers, n_heads=heads, n_kv_heads=heads,
            ffn_dim=desc.ffn, max_seq=target_cfg.max_seq,
            dtype=target_cfg.dtype, remat=False,
        )
        params = llama.llama_init(jax.random.PRNGKey(seed), cfg)
        draft = cls(params, cfg, **kwargs)
        draft.name = f"zoo:{name}"
        if ckpt_path:
            draft.load(ckpt_path)
        return draft

    def save(self, path: str) -> None:
        """Flat-npz draft checkpoint (leaves in tree order). The draft
        is one process's worth of small arrays — the sharded trainer
        checkpoint machinery would be pure overhead here."""
        import numpy as np

        import jax

        leaves = jax.tree_util.tree_leaves(self.params)
        np.savez(path, **{
            f"leaf_{i}": np.asarray(jax.device_get(l))
            for i, l in enumerate(leaves)
        })

    def load(self, path: str) -> None:
        """Restore :meth:`save` output into the existing param tree
        (shapes must match — the zoo descriptor pins them)."""
        import numpy as np

        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        with np.load(path) as z:
            new = []
            for i, old in enumerate(leaves):
                arr = z[f"leaf_{i}"]
                if tuple(arr.shape) != tuple(old.shape):
                    raise ValueError(
                        f"draft checkpoint leaf {i} shape {arr.shape} != "
                        f"model shape {tuple(old.shape)}"
                    )
                new.append(jnp.asarray(arr, old.dtype))
        self.params = jax.tree_util.tree_unflatten(treedef, new)

    def _segment_fn(self, n_steps: int):
        import jax

        llama, cfg = self._llama, self.cfg
        fn = self._segments.get(n_steps)
        if fn is None:
            fn = jax.jit(
                lambda p, c, t, z, key: llama.decode_segment(
                    p, c, t, z, key, cfg, n_steps, greedy=True
                )
            )
            self._segments[n_steps] = fn
        return fn

    def _prefill_padded(self, contexts: Sequence[Sequence[int]], k: int):
        """Left-truncate each context to the draft window, right-pad to
        a shape bucket, run one batched prefill. Returns (last-token
        logits [Bp, V], cache, B)."""
        jnp, llama = self._jnp, self._llama
        B = len(contexts)
        Bp = 1
        while Bp < B:
            Bp *= 2
        win = max(1, self.max_context - k - 1)
        ctxs = [list(map(int, c))[-win:] for c in contexts]
        P = max(max((len(c) for c in ctxs), default=1), 1)
        P = ((P + self.pad_to - 1) // self.pad_to) * self.pad_to
        toks = [c + [0] * (P - len(c)) for c in ctxs]
        toks += [[0] * P] * (Bp - B)
        lens = [len(c) for c in ctxs] + [0] * (Bp - B)
        cache = llama.init_batched_cache(self.cfg, Bp, self.max_context)
        logits, cache = self._prefill(
            self.params, cache,
            jnp.asarray(toks, jnp.int32), jnp.asarray(lens, jnp.int32),
        )
        return logits, cache, B

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        return self.propose_batch([context], k)[0]

    def propose_batch(
        self, contexts: Sequence[Sequence[int]], k: int
    ) -> List[List[int]]:
        if k <= 0 or not contexts:
            return [[] for _ in contexts]
        jnp = self._jnp
        logits, cache, B = self._prefill_padded(contexts, k)
        first = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [Bp]
        if k == 1:
            import numpy as np

            return [[int(t)] for t in np.asarray(first)[:B]]
        Bp = first.shape[0]
        toks, _, _, _ = self._segment_fn(k - 1)(
            self.params, cache, first[:, None],
            jnp.zeros((Bp,), jnp.float32), self._key,
        )
        import numpy as np

        first_h = np.asarray(first)
        toks_h = np.asarray(toks)
        return [
            [int(first_h[b])] + [int(t) for t in toks_h[b]]
            for b in range(B)
        ]

    def propose_candidates(
        self, context: Sequence[int], k: int, n: int
    ) -> List[List[int]]:
        """Branch at the first token: the draft's top-``n`` first tokens
        (descending — candidate 0 is the greedy proposal), each continued
        greedily. One prefill over n identical rows, one segment."""
        if n <= 1 or k <= 0:
            return [self.propose(context, k)]
        import numpy as np

        import jax

        jnp = self._jnp
        logits, cache, _ = self._prefill_padded([context] * n, k)
        _, top = jax.lax.top_k(logits[0], n)
        firsts = top.astype(jnp.int32)  # [n], descending score
        Bp = logits.shape[0]
        firsts_full = jnp.concatenate(
            [firsts, jnp.zeros((Bp - n,), jnp.int32)]
        )
        if k == 1:
            return [[int(t)] for t in np.asarray(firsts)]
        toks, _, _, _ = self._segment_fn(k - 1)(
            self.params, cache, firsts_full[:, None],
            jnp.zeros((Bp,), jnp.float32), self._key,
        )
        firsts_h, toks_h = np.asarray(firsts), np.asarray(toks)
        return [
            [int(firsts_h[i])] + [int(t) for t in toks_h[i]]
            for i in range(n)
        ]


class DraftTree:
    """Prefix trie over candidate draft chains for tree speculation.

    Node 0 is the ROOT: the row's next verify input (its last accepted
    token), depth 0. Every other node is one proposed draft token; a
    node's root path spells one draft prefix, and chains that share a
    prefix share nodes — the whole reason the trie beats the flat
    multi-candidate layout. :meth:`arrays` emits the fixed-size
    (tokens, depth, ancestor-mask) layout `llama.paged_verify_tree`
    consumes; :meth:`walk` follows the target's greedy ids down the
    trie to the deepest accepted path."""

    __slots__ = ("tokens", "parents", "depth", "children")

    def __init__(self, root_token: int) -> None:
        self.tokens: List[int] = [int(root_token)]
        self.parents: List[int] = [-1]
        self.depth: List[int] = [0]
        self.children: List[Dict[int, int]] = [{}]

    @property
    def size(self) -> int:
        return len(self.tokens)

    def insert(self, chain: Sequence[int], m_max: int) -> None:
        """Merge one candidate chain into the trie, capped at ``m_max``
        total nodes (excess suffix tokens are dropped — never verified,
        never emitted, so the cap only costs acceptance length)."""
        cur = 0
        for t in chain:
            t = int(t)
            nxt = self.children[cur].get(t)
            if nxt is None:
                if len(self.tokens) >= m_max:
                    return
                nxt = len(self.tokens)
                self.tokens.append(t)
                self.parents.append(cur)
                self.depth.append(self.depth[cur] + 1)
                self.children.append({})
                self.children[cur][t] = nxt
            cur = nxt

    def arrays(self, m_max: int):
        """Fixed-shape verify inputs: ``(tokens [m_max], depth [m_max],
        mask [m_max, m_max])`` numpy arrays. ``mask[m, t]`` is True iff
        t is m or an ancestor of m. Pad nodes repeat the root token as
        depth-1 children of the root: well-formed rows whose outputs the
        walk never reads, and — the masks being per-node — invisible to
        every live node's attention."""
        import numpy as np

        M = len(self.tokens)
        if M > m_max:
            raise ValueError(f"trie size {M} exceeds m_max {m_max}")
        toks = np.full((m_max,), self.tokens[0], np.int32)
        dep = np.ones((m_max,), np.int32)
        mask = np.zeros((m_max, m_max), bool)
        toks[:M] = self.tokens
        dep[:M] = self.depth
        for m in range(M):
            a = m
            while a != -1:
                mask[m, a] = True
                a = self.parents[a]
        for m in range(M, m_max):
            mask[m, m] = True
            mask[m, 0] = True
        return toks, dep, mask

    def walk(self, ids: Sequence[int]) -> List[int]:
        """Deepest accepted path: starting at the root, repeatedly step
        to the child whose token equals the target's greedy continuation
        ``ids[cur]`` at the current node; stop when no child matches.
        Returns the accepted DRAFT tokens along that path (root
        excluded) — `accept_length` over a chain trie, exactly."""
        path: List[int] = []
        cur = 0
        while True:
            nxt = self.children[cur].get(int(ids[cur]))
            if nxt is None:
                return path
            path.append(self.tokens[nxt])
            cur = nxt


def build_tree(
    root_token: int, chains: Sequence[Sequence[int]], k: int, m_max: int
) -> DraftTree:
    """Fold candidate ``chains`` (each ≤ k draft tokens) into one
    :class:`DraftTree`, inserting in order so candidate 0 — the greedy
    proposal — is never the one truncated by the node cap."""
    tree = DraftTree(root_token)
    for c in chains:
        tree.insert([int(t) for t in c][:k], m_max)
    return tree


def distill_draft(
    draft: "ModelDraft",
    target_params,
    target_cfg,
    prompts: Sequence[Sequence[int]],
    gen_len: int = 16,
    steps: int = 40,
    lr: float = 1e-2,
) -> List[float]:
    """Train ``draft`` to imitate the target's GREEDY rollouts: generate
    continuations with the target from each prompt, then fit the draft
    with the standard next-token loss on the concatenated sequences
    (hard-label distillation — exactly the objective that maximizes
    greedy acceptance, which is all a draft is scored on). Mutates
    ``draft.params`` in place and returns the per-step losses. CPU-scale
    by design: the zoo drafts this trains are tiny."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubedl_tpu.models import llama

    # the teacher IS a ModelDraft over the target weights: one batched
    # prefill + greedy segment gives every rollout
    teacher = ModelDraft(target_params, target_cfg,
                         max_context=target_cfg.max_seq)
    conts = teacher.propose_batch(prompts, gen_len)
    seqs = [list(map(int, p)) + c for p, c in zip(prompts, conts)]
    L = min(len(s) for s in seqs)
    toks = jnp.asarray([s[:L] for s in seqs], jnp.int32)

    opt = optax.adam(lr)
    params = draft.params
    opt_state = opt.init(params)
    cfg = draft.cfg

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: llama.llama_loss(p, toks, cfg)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(max(1, int(steps))):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    draft.params = params
    return losses


_DRAFTS = {
    "ngram": NgramDraft,
    "repeat": RepeatDraft,
}


def make_draft(name: str, **kwargs) -> DraftModel:
    """Draft factory for the engine's ``spec_draft`` knob. "model"
    needs weights — the engine constructs :class:`ModelDraft` itself
    (`ModelDraft.from_target`) instead of going through here."""
    if name == "model":
        raise ValueError(
            "draft 'model' needs target weights: use "
            "ModelDraft.from_target(...) (the engine's spec_draft="
            "'model' path does this)"
        )
    try:
        return _DRAFTS[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown draft {name!r} (have: {sorted(_DRAFTS)})"
        ) from None


def accept_length(drafts: Sequence[int], greedy_ids: Sequence[int]) -> int:
    """Longest agreeing prefix: number of draft tokens ``a`` such that
    ``drafts[j] == greedy_ids[j]`` for all ``j < a`` (greedy_ids[j] is
    the target's argmax after consuming the j-th verify input). The
    engine emits ``greedy_ids[:a+1]`` — a accepted drafts plus the bonus
    token, every one of them the target's own greedy choice."""
    a = 0
    for d, g in zip(drafts, greedy_ids):
        if int(d) != int(g):
            break
        a += 1
    return a


class SpecStats:
    """Acceptance accounting shared by the engine, stats(), and
    /metrics. ``accepted``/``proposed`` count DRAFT tokens (the bonus
    token is not a draft — a 0-acceptance verify still emits one token);
    ``window`` keeps recent per-verify acceptance lengths for the
    distribution tests and the p50 the autoscaler reads."""

    def __init__(self, maxlen: int = 4096) -> None:
        self._lock = threading.Lock()
        self.proposed = 0
        self.accepted = 0
        self.verifies = 0
        self.emitted = 0
        self.candidates_scored = 0
        self.candidate_switches = 0
        self.draft_ms_total = 0.0
        self.window: "deque[int]" = deque(maxlen=maxlen)
        self.draft_ms_window: "deque[float]" = deque(maxlen=maxlen)

    def record(self, proposed: int, accepted: int, emitted: int) -> None:
        with self._lock:
            self.proposed += int(proposed)
            self.accepted += int(accepted)
            self.verifies += 1
            self.emitted += int(emitted)
            self.window.append(int(accepted))

    def record_draft_ms(self, ms: float) -> None:
        """Wall time of one draft proposal round (all rows)."""
        with self._lock:
            self.draft_ms_total += float(ms)
            self.draft_ms_window.append(float(ms))

    def record_candidates(self, scored: int, switched: bool) -> None:
        """One multi-candidate verify: ``scored`` candidates ranked,
        ``switched`` = the winner was NOT the greedy candidate 0."""
        with self._lock:
            self.candidates_scored += int(scored)
            self.candidate_switches += 1 if switched else 0

    def acceptance_rate(self) -> float:
        with self._lock:
            return self.accepted / self.proposed if self.proposed else 0.0

    def snapshot(self) -> Dict:
        with self._lock:
            win = list(self.window)
            out = {
                "proposed": self.proposed,
                "accepted": self.accepted,
                "verifies": self.verifies,
                "emitted": self.emitted,
                "candidates_scored": self.candidates_scored,
                "candidate_switches": self.candidate_switches,
                "draft_ms_total": round(self.draft_ms_total, 3),
            }
            dwin = list(self.draft_ms_window)
        if dwin:
            out["draft_ms_p50"] = sorted(dwin)[len(dwin) // 2]
        out["acceptance_rate"] = round(
            out["accepted"] / out["proposed"], 4
        ) if out["proposed"] else 0.0
        out["tokens_per_verify"] = round(
            out["emitted"] / out["verifies"], 4
        ) if out["verifies"] else 0.0
        if win:
            srt = sorted(win)
            out["accept_len_p50"] = srt[len(srt) // 2]
            out["accept_len_mean"] = round(sum(win) / len(win), 4)
        return out


__all__ = [
    "DraftModel", "NgramDraft", "RepeatDraft", "ScriptedDraft",
    "ModelDraft", "make_draft", "accept_length", "SpecStats",
    "DraftTree", "build_tree", "distill_draft",
]

"""Draft-k / verify-1 speculative decoding for the paged serving engine.

Decode is memory-bandwidth bound: every generated token re-reads the
whole weight set for ONE row of matmul work. Speculative decoding buys
back that bandwidth by making each target-model forward score ``k+1``
positions at once: a cheap *draft* proposes k tokens, the target scores
the whole proposal in one batched forward over the paged cache
(`llama.paged_verify`), the longest prefix where the draft agrees with
the target's own greedy choice is accepted, and one "bonus" token — the
target's argmax after the last accepted position — is emitted for free.
Every emitted token is the target's argmax given only accepted history,
so GREEDY outputs are bit-identical to plain decode by construction (the
tier-1 gate); the only thing speculation changes is how many sequential
forwards it takes to produce them. Rejected-suffix KV lands beyond the
rolled-back position and its blocks are freed in place by the engine
(docs/serving.md "Speculative decoding").

Drafts are PLUGGABLE: anything with ``propose(context, k) -> list[int]``
works. Shipped drafts:

- :class:`NgramDraft` ("ngram", the default): self-speculative prompt-
  lookup — match the tail n-gram of the context against its own earlier
  tokens and propose whatever followed the most recent match. Zero
  model cost; strong on the repetitive traffic (templated output,
  retried generations, code) where speculation pays most.
- :class:`RepeatDraft` ("repeat"): propose the last token k times — the
  degenerate baseline that still wins on run-length-heavy output.
- :class:`ScriptedDraft`: tests force exact proposal streams to pin the
  acceptance-length distribution.

A wrong draft can never corrupt output — it only wastes the verify
forward — so draft quality is purely a throughput knob, measured by the
acceptance rate the engine exports (`stats()["speculative"]` and the
``kubedl_tpu_serving_spec_*`` metrics).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence


class DraftModel:
    """Protocol for draft proposers (duck-typed; subclassing optional)."""

    name = "draft"

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        """Return up to ``k`` proposed continuation tokens for
        ``context`` (prompt + generated so far). Shorter lists are
        allowed — the engine pads the verify window with repeats of the
        last proposal and simply accepts less."""
        raise NotImplementedError


class RepeatDraft(DraftModel):
    """Propose the last context token k times: the zero-knowledge
    baseline. Wins exactly on run-length repetition."""

    name = "repeat"

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if not context:
            return []
        return [int(context[-1])] * k


class NgramDraft(DraftModel):
    """Self-speculative prompt-lookup decoding: find the most recent
    earlier occurrence of the context's tail ``n``-gram (longest match
    first, down to 1) and propose the tokens that followed it. The
    context IS the draft model — no weights, no device time."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, window: int = 1024) -> None:
        self.max_ngram = max(1, int(max_ngram))
        self.window = max(8, int(window))

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ctx = [int(t) for t in context[-self.window:]]
        n_ctx = len(ctx)
        if n_ctx < 2:
            return []
        for n in range(min(self.max_ngram, n_ctx - 1), 0, -1):
            tail = ctx[n_ctx - n:]
            # scan for the most recent PRIOR occurrence of the tail
            for i in range(n_ctx - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    out = ctx[i + n:i + n + k]
                    if out:
                        return out
                    break
        # no lookup hit: fall back to run-length repetition
        return [ctx[-1]] * k


class ScriptedDraft(DraftModel):
    """Deterministic proposal stream for tests: pops pre-seeded
    proposals in order, then falls back to repeats."""

    name = "scripted"

    def __init__(self, proposals: Sequence[Sequence[int]]) -> None:
        self._q = deque([list(p) for p in proposals])

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if self._q:
            return [int(t) for t in self._q.popleft()][:k]
        return RepeatDraft().propose(context, k)


_DRAFTS = {
    "ngram": NgramDraft,
    "repeat": RepeatDraft,
}


def make_draft(name: str, **kwargs) -> DraftModel:
    """Draft factory for the engine's ``spec_draft`` knob."""
    try:
        return _DRAFTS[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown draft {name!r} (have: {sorted(_DRAFTS)})"
        ) from None


def accept_length(drafts: Sequence[int], greedy_ids: Sequence[int]) -> int:
    """Longest agreeing prefix: number of draft tokens ``a`` such that
    ``drafts[j] == greedy_ids[j]`` for all ``j < a`` (greedy_ids[j] is
    the target's argmax after consuming the j-th verify input). The
    engine emits ``greedy_ids[:a+1]`` — a accepted drafts plus the bonus
    token, every one of them the target's own greedy choice."""
    a = 0
    for d, g in zip(drafts, greedy_ids):
        if int(d) != int(g):
            break
        a += 1
    return a


class SpecStats:
    """Acceptance accounting shared by the engine, stats(), and
    /metrics. ``accepted``/``proposed`` count DRAFT tokens (the bonus
    token is not a draft — a 0-acceptance verify still emits one token);
    ``window`` keeps recent per-verify acceptance lengths for the
    distribution tests and the p50 the autoscaler reads."""

    def __init__(self, maxlen: int = 4096) -> None:
        self._lock = threading.Lock()
        self.proposed = 0
        self.accepted = 0
        self.verifies = 0
        self.emitted = 0
        self.window: "deque[int]" = deque(maxlen=maxlen)

    def record(self, proposed: int, accepted: int, emitted: int) -> None:
        with self._lock:
            self.proposed += int(proposed)
            self.accepted += int(accepted)
            self.verifies += 1
            self.emitted += int(emitted)
            self.window.append(int(accepted))

    def acceptance_rate(self) -> float:
        with self._lock:
            return self.accepted / self.proposed if self.proposed else 0.0

    def snapshot(self) -> Dict:
        with self._lock:
            win = list(self.window)
            out = {
                "proposed": self.proposed,
                "accepted": self.accepted,
                "verifies": self.verifies,
                "emitted": self.emitted,
            }
        out["acceptance_rate"] = round(
            out["accepted"] / out["proposed"], 4
        ) if out["proposed"] else 0.0
        out["tokens_per_verify"] = round(
            out["emitted"] / out["verifies"], 4
        ) if out["verifies"] else 0.0
        if win:
            srt = sorted(win)
            out["accept_len_p50"] = srt[len(srt) // 2]
            out["accept_len_mean"] = round(sum(win) / len(win), 4)
        return out


__all__ = [
    "DraftModel", "NgramDraft", "RepeatDraft", "ScriptedDraft",
    "make_draft", "accept_length", "SpecStats",
]

"""Device-resident prefix KV cache for shared-prompt serving traffic.

Real request fleets overwhelmingly share prompt prefixes — system
prompts, few-shot templates, chat history. Recomputing the shared
prefix's K/V on every admission is pure redundant prefill work: cached
prefix KV turns O(prompt) prefill into O(suffix), cutting TTFT and
freeing device time for decode throughput (docs/serving.md "Prefix
cache").

Two token-trie structures, both host-side and tiny; the PAYLOAD (per-
layer K/V arrays, `[L, P, KV, hd]` bucket-padded) lives wherever JAX put
it — HBM on a TPU host:

- The **entry trie** indexes stored prefixes for longest-prefix match:
  `match(prompt)` walks the prompt and returns the DEEPEST stored entry
  that still leaves at least one suffix token to prefill (the engine
  needs last-token logits to sample from).
- The **observation trie** watches traffic to decide what is WORTH
  storing: every admitted prompt is `observe()`d, and
  `insert_candidate()` returns the longest prefix of a prompt that at
  least ``min_seen`` distinct requests have shared — exactly the
  "system prompt" of a shared-prefix fleet, without any tagging. A
  request can also tag itself cacheable (`"cache_prefix": true` in the
  body), which makes its whole prompt a candidate on first sight.

Entries are kept under a configurable byte budget with LRU eviction.
Entries grafted into in-flight rows are PINNED by refcount: `match`
pins, the engine unpins at prefill harvest / finalize / slot vacation /
error recovery — a pinned entry is never evicted. Accounting (hits,
misses, tokens saved, insertions, evictions, bytes) feeds
`LlamaEngine.stats()["prefix_cache"]` and the `prefix_cache` Prometheus
family in `observability.metrics.ServingMetrics`.

Thread safety: one internal lock; callers are the scheduler thread plus
request threads releasing pins on timeout vacation.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


class PrefixEntry:
    """One cached prefix. Two payload shapes:

    - **array payload** (contiguous engine): device-resident per-layer
      K/V copies, ``bytes`` = their nbytes;
    - **block payload** (paged engine): ``blocks`` holds pool block ids
      the entry REFERENCES (refcounted by the engine's BlockAllocator —
      no copy exists), and the caller passes the bytes those references
      pin via ``nbytes``.
    """

    __slots__ = ("tokens", "length", "k", "v", "blocks", "bytes", "refs",
                 "last_use", "hits")

    def __init__(self, tokens: Tuple[int, ...], k, v, length: int,
                 blocks: Optional[Tuple[int, ...]] = None,
                 nbytes: Optional[int] = None) -> None:
        self.tokens = tokens
        self.length = length  # true prefix length (k/v are bucket-padded)
        self.k = k  # [L, P, KV, hd] (None for block-payload entries)
        self.v = v
        self.blocks = tuple(blocks) if blocks else None
        if nbytes is not None:
            self.bytes = int(nbytes)
        else:
            self.bytes = int(getattr(k, "nbytes", 0)) + int(
                getattr(v, "nbytes", 0)
            )
        self.refs = 0  # in-flight rows using this entry (pin count)
        self.last_use = 0  # LRU clock value at last match/insert
        self.hits = 0


class _Node:
    """Entry-trie node: child per token, optional terminal entry."""

    __slots__ = ("children", "entry")

    def __init__(self) -> None:
        self.children: Dict[int, "_Node"] = {}
        self.entry: Optional[PrefixEntry] = None


class PrefixCache:
    """Token-trie prefix KV store with byte budget + LRU + pinning."""

    def __init__(
        self,
        budget_bytes: int,
        min_len: int = 8,
        min_seen: int = 2,
        max_obs_nodes: int = 100_000,
        max_obs_depth: int = 4096,
        on_evict=None,
    ) -> None:
        #: callback(entry) fired whenever an entry leaves the cache via
        #: eviction/reclaim — the paged engine returns the entry's block
        #: references to its allocator here. Called under the cache lock;
        #: the callback must not call back into this cache.
        self.on_evict = on_evict
        #: HBM byte budget for entry payloads (k+v nbytes)
        self.budget_bytes = int(budget_bytes)
        #: prefixes shorter than this are not worth a graft dispatch
        self.min_len = max(1, int(min_len))
        #: observation threshold: insert once this many requests shared it
        self.min_seen = max(1, int(min_seen))
        self._lock = threading.Lock()
        self._root = _Node()
        self._entries: Dict[Tuple[int, ...], PrefixEntry] = {}
        self._bytes = 0
        self._clock = 0  # LRU tick, bumped per match/insert
        # observation trie: token -> [count, children]; bounded node count
        self._obs_root: list = [0, {}]
        self._obs_nodes = 0
        self._max_obs_nodes = int(max_obs_nodes)
        self._max_obs_depth = int(max_obs_depth)
        self._stats = {
            "hits": 0, "misses": 0, "inserts": 0, "evictions": 0,
            "tokens_saved": 0, "insert_rejects": 0,
        }

    # -- lookup / pinning --------------------------------------------------

    def match(self, prompt: Sequence[int]) -> Tuple[Optional[PrefixEntry], int]:
        """Longest stored prefix of ``prompt`` that leaves >= 1 suffix
        token. On a hit the entry is PINNED (refcount) and its LRU clock
        bumped; the caller owns one `unpin`. Returns (entry, length) or
        (None, 0)."""
        # deepest usable terminal: depth <= len(prompt) - 1
        limit = len(prompt) - 1
        with self._lock:
            node = self._root
            best: Optional[PrefixEntry] = None
            for d in range(limit):
                node = node.children.get(int(prompt[d]))
                if node is None:
                    break
                if node.entry is not None:
                    best = node.entry
            if best is None:
                self._stats["misses"] += 1
                return None, 0
            self._clock += 1
            best.last_use = self._clock
            best.refs += 1
            best.hits += 1
            self._stats["hits"] += 1
            return best, best.length

    def unpin(self, entry: PrefixEntry) -> None:
        """Release one in-flight pin taken by `match` (or `pin`)."""
        with self._lock:
            if entry.refs > 0:
                entry.refs -= 1

    def pin(self, entry: PrefixEntry) -> None:
        with self._lock:
            entry.refs += 1

    def add_tokens_saved(self, n: int) -> None:
        """Account prefill tokens actually skipped (the engine calls this
        at suffix-prefill dispatch, not at match: a graft dropped before
        prefill — overflow fixup, vacation — must not inflate it)."""
        if n > 0:
            with self._lock:
                self._stats["tokens_saved"] += int(n)

    # -- traffic observation ----------------------------------------------

    def observe(self, prompt: Sequence[int]) -> None:
        """Record one request's prompt in the observation trie (bounded
        nodes/depth). Counts on each node = how many requests shared the
        prefix ending there."""
        with self._lock:
            node = self._obs_root
            node[0] += 1
            for tok in list(prompt)[: self._max_obs_depth]:
                tok = int(tok)
                nxt = node[1].get(tok)
                if nxt is None:
                    if self._obs_nodes >= self._max_obs_nodes:
                        return  # full: keep counting along existing paths
                    nxt = [0, {}]
                    node[1][tok] = nxt
                    self._obs_nodes += 1
                nxt[0] += 1
                node = nxt

    def insert_candidate(
        self, prompt: Sequence[int], tagged: bool = False
    ) -> int:
        """Length of the prefix of ``prompt`` worth inserting now: the
        whole prompt when ``tagged`` (request body opted in), else the
        longest prefix >= ``min_seen`` requests have shared. 0 = nothing
        (too short, or not shared traffic)."""
        if tagged:
            return len(prompt) if len(prompt) >= self.min_len else 0
        with self._lock:
            node = self._obs_root
            depth = 0
            for tok in list(prompt)[: self._max_obs_depth]:
                nxt = node[1].get(int(tok))
                if nxt is None or nxt[0] < self.min_seen:
                    break
                depth += 1
                node = nxt
        return depth if depth >= self.min_len else 0

    # -- insertion / eviction ----------------------------------------------

    def insert(self, tokens: Sequence[int], k, v, length: int,
               blocks: Optional[Sequence[int]] = None,
               nbytes: Optional[int] = None) -> bool:
        """Store a prefix entry (payload bucket-padded by the caller;
        or, paged, ``blocks`` references with explicit ``nbytes``).
        Duplicate keys just refresh the existing entry's LRU clock.
        Evicts LRU unpinned entries until the new entry fits; rejects it
        (False) if it cannot fit — pinned bytes never get evicted and a
        single entry larger than the budget never enters."""
        key = tuple(int(t) for t in tokens)
        entry = PrefixEntry(
            key, k, v, int(length),
            blocks=tuple(blocks) if blocks else None, nbytes=nbytes,
        )
        with self._lock:
            self._clock += 1
            existing = self._entries.get(key)
            if existing is not None:
                existing.last_use = self._clock
                return False
            if entry.bytes > self.budget_bytes:
                self._stats["insert_rejects"] += 1
                return False
            while self._bytes + entry.bytes > self.budget_bytes:
                if not self._evict_lru_locked():
                    self._stats["insert_rejects"] += 1
                    return False
            node = self._root
            for tok in key:
                node = node.children.setdefault(tok, _Node())
            node.entry = entry
            entry.last_use = self._clock
            self._entries[key] = entry
            self._bytes += entry.bytes
            self._stats["inserts"] += 1
            return True

    def _evict_lru_locked(self) -> bool:
        victim = None
        for e in self._entries.values():
            if e.refs > 0:
                continue
            if victim is None or e.last_use < victim.last_use:
                victim = e
        if victim is None:
            return False
        self._remove_locked(victim)
        self._stats["evictions"] += 1
        return True

    def reclaim(self, nbytes: int) -> int:
        """Evict LRU UNPINNED entries until at least ``nbytes`` of budget
        came back (or nothing evictable remains); returns bytes freed.
        The paged engine's pressure valve: under block exhaustion, cached
        prefixes are the first thing to go — they are an optimization,
        resident rows are work. `on_evict` fires per entry, handing block
        references back to the allocator."""
        freed = 0
        with self._lock:
            while freed < int(nbytes):
                before = self._bytes
                if not self._evict_lru_locked():
                    break
                freed += before - self._bytes
        return freed

    def clear(self) -> None:
        """Drop EVERY entry — pinned or not — without firing `on_evict`.
        Error-recovery only: the engine rebuilt its device pool and
        allocator, so the block references entries hold are already
        dead and must not be double-freed into the new allocator."""
        with self._lock:
            for e in self._entries.values():
                e.k = e.v = None
            self._entries.clear()
            self._root = _Node()
            self._bytes = 0

    def _remove_locked(self, entry: PrefixEntry) -> None:
        del self._entries[entry.tokens]
        self._bytes -= entry.bytes
        # unlink from the trie, pruning now-empty branches
        path: List[Tuple[_Node, int]] = []
        node = self._root
        for tok in entry.tokens:
            path.append((node, tok))
            node = node.children[tok]
        node.entry = None
        for parent, tok in reversed(path):
            child = parent.children[tok]
            if child.entry is None and not child.children:
                del parent.children[tok]
            else:
                break
        if self.on_evict is not None:
            self.on_evict(entry)
        entry.k = entry.v = None  # drop device buffer refs eagerly

    # -- introspection -----------------------------------------------------

    def prefix_keys(self, limit: int = 256) -> List[Tuple[int, ...]]:
        """Snapshot of stored prefix token keys, most-recently-used first
        (bounded by ``limit``). Feeds the engine's advertised-prefix map:
        the router's block-aware affinity steers shared prompts onto
        replicas whose caches already hold their blocks."""
        with self._lock:
            keys = sorted(
                self._entries.values(), key=lambda e: -e.last_use
            )[: max(0, int(limit))]
            return [e.tokens for e in keys]

    def stats(self) -> Dict:
        with self._lock:
            s = dict(self._stats)
            s["entries"] = len(self._entries)
            s["bytes"] = self._bytes
            s["budget_bytes"] = self.budget_bytes
            s["pinned"] = sum(1 for e in self._entries.values() if e.refs)
        total = s["hits"] + s["misses"]
        s["hit_rate"] = round(s["hits"] / total, 4) if total else 0.0
        return s

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

"""Inference CRD types.

Reference: apis/serving/v1alpha1/inference_types.go:37-117 — Inference
{framework, predictors[]}; predictor = modelVersion + replicas +
trafficWeight + template + autoScale + batching stubs. TrafficPolicy is the
in-store analogue of the Istio VirtualService the reference programs
(inference_controller.go:206-274).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.core.objects import BaseObject, PodTemplateSpec


class Framework(str, enum.Enum):
    """Serving frameworks (reference: inference_types.go:106-111 — the
    reference implements TFServing and enumerates Triton; the TPU-native
    default here is the JAX server)."""

    JAX = "JAXServing"
    TF_SERVING = "TFServing"
    TRITON = "Triton"


@dataclass
class AutoScaleSpec:
    """Predictor autoscaling bounds (reference carries this as a stub on
    the predictor spec; the console surfaces it)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_qps: Optional[float] = None


@dataclass
class BatchingSpec:
    """Server-side request batching knobs (reference: batching stub)."""

    max_batch_size: int = 1
    timeout_ms: int = 0


@dataclass
class Predictor:
    """One model variant behind the endpoint (reference:
    inference_types.go:57-95)."""

    name: str = "default"
    #: ModelVersion to serve; empty = the model's latest version
    model_version: str = ""
    #: Model whose latest version to track when model_version is empty
    model_name: str = ""
    replicas: int = 1
    #: Canary weight 0-100; weights are normalized across ready predictors
    traffic_weight: int = 100
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    autoscale: Optional[AutoScaleSpec] = None
    batching: Optional[BatchingSpec] = None
    #: weight quantization for the JAX engine: "" (serve the checkpoint
    #: dtype) or "int8" (weight-only; measured +68% b1 decode on v5e —
    #: docs/serving.md). A canary predictor can A/B it against full
    #: precision behind the same endpoint.
    quantize: str = ""
    #: paged decode attention kernel: "" (engine default, gather oracle)
    #: or "blocked" (flash-style online softmax over the block table —
    #: docs/serving.md "Blocked paged attention"). Greedy outputs are
    #: bit-identical either way, so a canary can A/B kernels safely.
    attention_kernel: str = ""
    #: speculative decoding: draft tokens per verify forward (0 = off),
    #: draft kind ("ngram" host lookup or "model" early-exit slice of
    #: the target), and candidate continuations ranked per verify round
    #: (1 = single-candidate).
    spec_k: int = 0
    spec_draft: str = ""
    spec_candidates: int = 0
    #: disaggregated serving role: "" / "colocated" (prefill + decode on
    #: every replica), "prefill" (fills KV blocks, exports KVHandoffs),
    #: or "decode" (adopts handoffs into its own block pool). Advisory:
    #: every engine still serves the full API, so the router degrades a
    #: pool outage to the colocated path (docs/serving.md
    #: "Disaggregated serving").
    role: str = ""
    #: per-tenant QoS block forwarded to the router config: ``{"classes":
    #: {name: {"weight": int, "priority": int}}, "tenants": {tenant:
    #: class}, "default_class": str, "capacity": int, "max_queue": int}``
    qos: Optional[Dict] = None


@dataclass
class PredictorStatus:
    replicas: int = 0
    ready_replicas: int = 0
    image: str = ""  # model artifact ref being served
    message: str = ""
    #: RUNNING pods whose stats probe has failed consecutively past the
    #: controller's NotReady threshold — the replica is up but unreachable
    #: (previously these silently dropped out of the QPS math)
    not_ready: List[str] = field(default_factory=list)


@dataclass
class Inference(BaseObject):
    KIND = "Inference"
    framework: Framework = Framework.JAX
    predictors: List[Predictor] = field(default_factory=list)
    # -- status --
    predictor_statuses: Dict[str, PredictorStatus] = field(default_factory=dict)
    endpoint: str = ""  # entry service DNS


@dataclass
class TrafficRoute:
    predictor: str
    weight: int  # normalized percentage
    service: str  # backing per-predictor service name


@dataclass
class TrafficPolicy(BaseObject):
    """Weighted canary routing table (VirtualService analogue,
    inference_controller.go:206-274; gateway "kubedl-serving-gateway")."""

    KIND = "TrafficPolicy"
    host: str = ""  # entry service host
    routes: List[TrafficRoute] = field(default_factory=list)

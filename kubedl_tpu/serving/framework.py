"""Per-framework predictor environment setters.

Reference: controllers/serving/framework/ — a `Setter` registry keyed by
framework (types.go:26-33) whose TFServing impl injects MODEL_NAME /
MODEL_BASE_PATH (tfserving.go:29-54). Same shape here, plus the TPU-native
JAX setter that wires the bundled artifact into the in-repo server.
"""

from __future__ import annotations

import json
from typing import Callable, Dict

from kubedl_tpu.api import constants
from kubedl_tpu.core.objects import Pod
from kubedl_tpu.lineage.types import ModelVersion
from kubedl_tpu.serving.types import Framework, Inference, Predictor

Setter = Callable[[Inference, Predictor, Pod, ModelVersion, int], None]

SETTERS: Dict[Framework, Setter] = {}


def register_setter(framework: Framework, setter: Setter) -> None:
    SETTERS[framework] = setter


def apply_setter(
    inf: Inference, pred: Predictor, pod: Pod, mv: ModelVersion, port: int
) -> None:
    setter = SETTERS.get(inf.framework)
    if setter is None:
        raise KeyError(f"no setter registered for framework {inf.framework}")
    setter(inf, pred, pod, mv, port)


def _tfserving_setter(
    inf: Inference, pred: Predictor, pod: Pod, mv: ModelVersion, port: int
) -> None:
    """Reference: framework/tfserving.go:29-54."""
    main = pod.spec.main_container()
    main.set_env("MODEL_NAME", mv.model_name)
    main.set_env("MODEL_BASE_PATH", f"/models/{mv.model_name}")
    main.set_env("KUBEDL_ARTIFACT", mv.image)


def _jax_setter(
    inf: Inference, pred: Predictor, pod: Pod, mv: ModelVersion, port: int
) -> None:
    """TPU-native: point the in-repo JAX server at the artifact's
    checkpoint and give it the serve config; default the entrypoint so an
    empty predictor template serves out of the box."""
    main = pod.spec.main_container()
    if not main.command and not main.entrypoint:
        main.entrypoint = "kubedl_tpu.serving.server:serve_main"
    # resolve through the storage provider instead of injecting the raw
    # storage_root: a remote (http) root stays a URL for serve_main's
    # fetch-on-load path, anything mis-shaped fails HERE, at pod creation
    from kubedl_tpu.lineage.storage import get_storage_provider

    root = get_storage_provider(mv.storage_provider).serving_root(mv)
    main.set_env(constants.ENV_MODEL_PATH, root)
    serve_cfg = {
        "model_name": mv.model_name,
        "artifact": mv.image,
        "port": port,
        "quantize": pred.quantize,
        "batching": (
            {"max_batch_size": pred.batching.max_batch_size,
             "timeout_ms": pred.batching.timeout_ms}
            if pred.batching else None
        ),
    }
    # decode-path knobs ride along only when the predictor sets them, so
    # a default Predictor keeps the engine's own defaults (and template
    # JSON below can still override either way)
    if pred.attention_kernel:
        serve_cfg["kv_attention"] = pred.attention_kernel
    if pred.spec_k:
        serve_cfg["spec_k"] = pred.spec_k
    if pred.spec_draft:
        serve_cfg["spec_draft"] = pred.spec_draft
    if pred.spec_candidates:
        serve_cfg["spec_candidates"] = pred.spec_candidates
    if getattr(pred, "role", ""):
        serve_cfg["role"] = pred.role
    # template-provided keys win (e.g. a custom port or preset)
    existing = main.get_env("KUBEDL_SERVE_CONFIG")
    if existing:
        serve_cfg.update(json.loads(existing))
    main.set_env("KUBEDL_SERVE_CONFIG", json.dumps(serve_cfg))


def _triton_setter(
    inf: Inference, pred: Predictor, pod: Pod, mv: ModelVersion, port: int
) -> None:
    """Reference parity: Triton is enum-only there (inference_types.go:
    106-111) — we inject the standard repository layout env and leave the
    container image to the user."""
    main = pod.spec.main_container()
    main.set_env("TRITON_MODEL_REPOSITORY", mv.storage_root)


register_setter(Framework.TF_SERVING, _tfserving_setter)
register_setter(Framework.JAX, _jax_setter)
register_setter(Framework.TRITON, _triton_setter)

"""Host-side routing policy: the decisions, without the HTTP.

Everything the router (kubedl_tpu/serving/router.py) decides — eject or
trust a replica, retry or surface an error, hedge or wait, which replica
owns a prompt prefix — lives here as small deterministic state machines
so the policy layer is unit-testable with fake clocks and no sockets.
The mechanisms are the tail-at-scale toolkit (PAPERS.md): circuit
breakers for fast failure detection, retry *budgets* (not counts) so
retries cannot amplify an overload, p95-based hedging for tail latency,
and consistent hashing so the fleet keeps PR 4's prefix-cache hit rate.

docs/serving.md "Router" documents the knobs; docs/robustness.md has the
failure-modes table these policies implement.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# -- circuit breaker --------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica breaker: ``fail_threshold`` CONSECUTIVE failures open
    it (replica ejected from routing), after ``cooldown_s`` it half-opens
    and admits exactly one trial (the health probe); a success closes it,
    a failure re-opens with a fresh cooldown. Consecutive — not windowed —
    because a replica that answers at all is better kept in rotation and
    judged by the retry layer."""

    def __init__(self, fail_threshold: int = 3, cooldown_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.fail_threshold = max(1, int(fail_threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.ejections = 0          # CLOSED/HALF_OPEN -> OPEN transitions
        self.readmissions = 0       # HALF_OPEN -> CLOSED transitions
        self._opened_at = 0.0
        self._trial_out = False     # half-open: one probe in flight

    def record_success(self) -> None:
        with self._lock:
            if self.state != CLOSED:
                self.readmissions += 1
            self.state = CLOSED
            self.consecutive_failures = 0
            self._trial_out = False

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            self._trial_out = False
            if self.state == HALF_OPEN:
                # the trial failed: back to OPEN, restart the cooldown
                self.state = OPEN
                self._opened_at = self.clock()
            elif (self.state == CLOSED
                    and self.consecutive_failures >= self.fail_threshold):
                self.state = OPEN
                self.ejections += 1
                self._opened_at = self.clock()

    def allow(self) -> bool:
        """May a request (or probe) be sent to this replica right now?
        OPEN converts to HALF_OPEN once the cooldown elapses, and
        HALF_OPEN admits exactly ONE in-flight trial at a time."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self.clock() - self._opened_at < self.cooldown_s:
                    return False
                self.state = HALF_OPEN
                self._trial_out = True
                return True
            # HALF_OPEN: only the single outstanding trial
            if self._trial_out:
                return False
            self._trial_out = True
            return True

    @property
    def available(self) -> bool:
        """Cheap availability view for replica *selection* (no state
        transition): CLOSED, or OPEN past its cooldown."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                return self.clock() - self._opened_at >= self.cooldown_s
            return not self._trial_out


# -- retry budget -----------------------------------------------------------

class RetryBudget:
    """Retries as a FRACTION of traffic, not a per-request count: each
    accepted request deposits ``ratio`` tokens, each retry (or hedge)
    withdraws one. Under a fleet-wide overload the budget drains and
    retries stop — the classic retry-storm amplifier (N clients x M
    attempts) is capped at ``1 + ratio`` of offered load.
    ``min_tokens`` keeps a trickle so a cold router can still fail over."""

    def __init__(self, ratio: float = 0.2, min_tokens: float = 2.0,
                 max_tokens: float = 100.0) -> None:
        self.ratio = float(ratio)
        self.min_tokens = float(min_tokens)
        self.max_tokens = float(max_tokens)
        self._lock = threading.Lock()
        self._tokens = self.min_tokens
        self.spent = 0      # granted retries/hedges
        self.denied = 0     # withdrawals refused (budget exhausted)

    def on_request(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            # epsilon: N deposits of ratio=1/N must sum to a whole token
            if self._tokens >= 1.0 - 1e-9:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


# -- latency tracking (hedge delay) -----------------------------------------

class LatencyTracker:
    """Sliding window of request latencies; the hedge fires when the
    primary has been out longer than p95 — by definition ~5% of requests
    hedge, the tail-at-scale sweet spot. Until ``min_samples`` real
    latencies exist, hedging uses ``default_ms`` (conservatively high so
    a cold router does not double its own traffic)."""

    def __init__(self, window: int = 512, min_samples: int = 20,
                 default_ms: float = 1000.0) -> None:
        self._lock = threading.Lock()
        self._samples: "deque[float]" = deque(maxlen=window)
        self.min_samples = int(min_samples)
        self.default_ms = float(default_ms)

    def record(self, ms: float) -> None:
        with self._lock:
            self._samples.append(float(ms))

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            n = len(self._samples)
            if n < self.min_samples:
                return None
            srt = sorted(self._samples)
        return srt[min(n - 1, int(n * q))]

    def hedge_delay_ms(self, floor_ms: float = 0.0) -> float:
        p95 = self.quantile(0.95)
        if p95 is None:
            return max(self.default_ms, floor_ms)
        return max(p95, floor_ms)


# -- deadlines --------------------------------------------------------------

def deadline_at(budget_ms: float,
                clock: Callable[[], float] = time.monotonic) -> float:
    """Absolute (monotonic-clock) deadline for a client budget."""
    return clock() + max(0.0, float(budget_ms)) / 1000.0


def remaining_ms(deadline: float,
                 clock: Callable[[], float] = time.monotonic) -> float:
    """Remaining budget in ms; <= 0 means expired (never dispatch)."""
    return (deadline - clock()) * 1000.0


# -- prefix affinity (consistent hashing) -----------------------------------

def _stable_hash(data: bytes) -> int:
    # NOT the builtin hash(): PYTHONHASHSEED would shuffle the ring every
    # process restart and the affinity (and its cache hit rate) with it
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


def prefix_digest(prompt_ids: Sequence[int],
                  prefix_len: int) -> Optional[int]:
    """Stable digest of a prompt's first ``prefix_len`` tokens — the SAME
    value the ring hashes on, so an engine's advertised-prefix map (built
    from its prefix-cache keys) and the router's per-request lookup agree
    by construction. None for prompts shorter than the affinity length or
    disabled affinity."""
    if prefix_len <= 0:
        return None
    ids = list(prompt_ids)[:prefix_len]
    if len(ids) < prefix_len:
        return None
    return _stable_hash(b",".join(str(int(t)).encode() for t in ids))


class ConsistentHashRing:
    """Replica ring with virtual nodes: a prompt-prefix key maps to a
    deterministic PREFERENCE ORDER of replicas (walk clockwise), so when
    the owner is ejected/draining the key falls to the same second owner
    every time — its prefix KV warms exactly one fallback, not a random
    one. Adding/removing one replica remaps only ~1/N of key space, which
    is the whole point: a canary shift must not flush every engine's
    prefix cache (PR 4) fleet-wide."""

    def __init__(self, vnodes: int = 64) -> None:
        self.vnodes = max(1, int(vnodes))
        self._ring: List[Tuple[int, str]] = []  # (point, replica name)
        self._names: List[str] = []

    def rebuild(self, names: Sequence[str]) -> None:
        ring: List[Tuple[int, str]] = []
        for name in names:
            for v in range(self.vnodes):
                ring.append(
                    (_stable_hash(f"{name}#{v}".encode()), name)
                )
        ring.sort()
        self._ring = ring
        self._names = list(names)

    def key_for_prefix(self, prompt_ids: Sequence[int],
                       prefix_len: int) -> Optional[int]:
        """Hash point for a prompt's affinity prefix; None when the
        prompt is shorter than the affinity length (no shared prefix
        worth pinning — let least-loaded decide) or affinity is disabled
        (``prefix_len <= 0``)."""
        return prefix_digest(prompt_ids, prefix_len)

    def preference(self, point: int) -> List[str]:
        """Distinct replica names in ring order starting at ``point``."""
        if not self._ring:
            return []
        seen: List[str] = []
        start = bisect.bisect_left(self._ring, (point, ""))
        n = len(self._ring)
        for i in range(n):
            name = self._ring[(start + i) % n][1]
            if name not in seen:
                seen.append(name)
                if len(seen) == len(self._names):
                    break
        return seen


def pick_replicas(
    candidates: Dict[str, int],
    prompt_ids: Sequence[int],
    ring: ConsistentHashRing,
    prefix_len: int,
    advertised: Optional[Dict[str, set]] = None,
) -> List[str]:
    """Routing order for one request: block-aware affinity first (a
    replica that ADVERTISES the request's prefix digest has the prefix
    KV resident right now — stronger signal than ring ownership, which
    only says where it WOULD be), then the ring's affinity owner, then
    least-loaded (by in-flight count, then name for determinism).
    ``candidates`` maps available replica name -> current in-flight
    count; ``advertised`` maps replica name -> set of prefix digests it
    reported via ``stats()["prefix_cache"]["advertised"]``. Returns every
    candidate, best first — the caller takes [0] as primary, [1] as
    hedge/failover."""
    if not candidates:
        return []
    by_load = sorted(candidates, key=lambda n: (candidates[n], n))
    point = ring.key_for_prefix(prompt_ids, prefix_len)
    if point is None:
        return by_load
    holders: List[str] = []
    if advertised:
        holders = [n for n in by_load if point in advertised.get(n, ())]
    pref = [n for n in ring.preference(point) if n in candidates]
    # advertised holders first (least-loaded among them), then the ring
    # owner, then the rest by load: the hedge/failover target is the
    # least-loaded NON-owner, not the ring's second owner, so a hot
    # prefix cannot overload two replicas in lockstep
    head = holders + [n for n in ([pref[0]] if pref else [])
                      if n not in holders]
    rest = [n for n in by_load if n not in head]
    return head + rest

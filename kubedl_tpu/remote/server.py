"""The remote-store server: blobs + persist RPC over HTTP.

The network-boundary analogue of the reference's external stores (MySQL
over the wire for job/pod/event rows, mysql.go:413-440; object storage
for artifacts). One small HTTP server exposes:

- ``PUT/GET/DELETE /blobs/<key>`` and ``GET /blobs?prefix=`` — a flat
  object store for model artifacts (checkpoint shards, manifests).
- ``POST /persist/call {"method": ..., "kwargs": ...}`` — RPC onto a
  server-side persistence backend (the built-in SQLite one), so the full
  Query/filter semantics live server-side exactly like a SQL store, and
  the client is a thin typed stub (`kubedl_tpu.persist.http_backend`).

Blobs are files under ``root/``; keys are sanitized relative paths.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

log = logging.getLogger("kubedl_tpu.remote.server")

#: process umask, read once at import (single-threaded moment): os.umask
#: can only be read by writing it, which is unsafe per-request under
#: ThreadingHTTPServer
_UMASK = os.umask(0)
os.umask(_UMASK)

#: persist methods callable over RPC (both backend roles)
_PERSIST_METHODS = frozenset({
    "save_job", "get_job", "list_jobs", "mark_job_deleted",
    "remove_job_record", "save_pod", "list_pods", "mark_pod_deleted",
    "save_event", "list_events",
})


def _safe_key(key: str) -> str:
    key = urllib.parse.unquote(key).strip("/")
    parts = [p for p in key.split("/") if p not in ("", ".", "..")]
    if not parts:
        raise ValueError("empty blob key")
    if parts[-1].endswith(".tmp-upload"):
        raise ValueError("reserved blob name suffix .tmp-upload")
    return "/".join(parts)


class RemoteStoreServer:
    """Serve blobs from ``root`` and persist RPC from a SQLite backend."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 db_path: str = ":memory:") -> None:
        from kubedl_tpu.persist.sqlite_backend import SQLiteBackend

        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.backend = SQLiteBackend(db_path)
        self.backend.initialize()
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug(fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload) -> None:
                self._send(code, json.dumps(payload).encode())

            def do_PUT(self):
                parsed = urllib.parse.urlparse(self.path)
                if not parsed.path.startswith("/blobs/"):
                    self._json(404, {"error": "not found"})
                    return
                try:
                    key = _safe_key(parsed.path[len("/blobs/"):])
                    length = int(self.headers.get("Content-Length", "0"))
                    data = self.rfile.read(length)
                    dest = server.root / key
                    dest.parent.mkdir(parents=True, exist_ok=True)
                    # unique temp per request: concurrent PUTs to the same
                    # key must not interleave into one staging file
                    import tempfile as _tempfile

                    fd, tmp_name = _tempfile.mkstemp(
                        prefix=dest.name + ".", suffix=".tmp-upload",
                        dir=dest.parent,
                    )
                    try:
                        with os.fdopen(fd, "wb") as f:
                            f.write(data)
                            # mkstemp creates 0600; blobs may be read
                            # directly off a shared filesystem by other
                            # uids (workers mounting the storage root), so
                            # restore what a plain open() would have
                            # created: 0666 filtered by the process umask
                            # (a deployment running umask 027 keeps its
                            # tighter permissions). _UMASK is read once at
                            # import: os.umask() is process-global and
                            # this handler runs on ThreadingHTTPServer
                            # threads — a get/restore here would race.
                            os.fchmod(f.fileno(), 0o666 & ~_UMASK)
                        os.replace(tmp_name, dest)
                    except BaseException:
                        with contextlib.suppress(OSError):
                            os.unlink(tmp_name)
                        raise
                    self._json(200, {"key": key, "size": len(data)})
                except Exception as e:
                    self._json(400, {"error": str(e)})

            def do_DELETE(self):
                parsed = urllib.parse.urlparse(self.path)
                if not parsed.path.startswith("/blobs/"):
                    self._json(404, {"error": "not found"})
                    return
                try:
                    key = _safe_key(parsed.path[len("/blobs/"):])
                    target = server.root / key
                    if target.is_file():
                        target.unlink()
                        self._json(200, {"deleted": key})
                    else:
                        self._json(404, {"error": f"no blob {key}"})
                except Exception as e:
                    self._json(400, {"error": str(e)})

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/healthz":
                    self._json(200, {"status": "ok"})
                    return
                if parsed.path == "/blobs":
                    q = urllib.parse.parse_qs(parsed.query)
                    prefix = q.get("prefix", [""])[0].strip("/")
                    base = server.root

                    def match(key: str) -> bool:
                        # path-component boundary: "models/m1" must not
                        # also return "models/m10/..."; in-flight upload
                        # temp files never appear in listings
                        if key.endswith(".tmp-upload"):
                            return False
                        if not prefix:
                            return True
                        return key == prefix or key.startswith(prefix + "/")

                    keys = sorted(
                        str(p.relative_to(base))
                        for p in base.rglob("*")
                        if p.is_file() and match(str(p.relative_to(base)))
                    )
                    self._json(200, {"keys": keys})
                    return
                if parsed.path.startswith("/blobs/"):
                    try:
                        key = _safe_key(parsed.path[len("/blobs/"):])
                    except ValueError as e:
                        self._json(400, {"error": str(e)})
                        return
                    target = server.root / key
                    if not target.is_file():
                        self._json(404, {"error": f"no blob {key}"})
                        return
                    self._send(200, target.read_bytes(),
                               "application/octet-stream")
                    return
                self._json(404, {"error": "not found"})

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path != "/persist/call":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    method = req.get("method", "")
                    if method not in _PERSIST_METHODS:
                        self._json(400, {"error": f"unknown method {method!r}"})
                        return
                    result = server._call(method, req.get("kwargs") or {})
                    self._json(200, {"result": result})
                except Exception as e:
                    self._json(500, {"error": str(e)})

        self._http = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._http.server_address[:2]
        self.base_url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def _call(self, method: str, kwargs: dict):
        """Decode typed args, dispatch to the SQLite backend, re-encode."""
        from kubedl_tpu.api.codec import decode
        from kubedl_tpu.persist.backends import Query
        from kubedl_tpu.persist.dmo import EventInfo, JobInfo, ReplicaInfo, to_jsonable

        typed = {
            "job": JobInfo, "pod": ReplicaInfo, "ev": EventInfo,
            "query": Query,
        }
        call_kwargs = {}
        for k, v in kwargs.items():
            cls = typed.get(k)
            call_kwargs[k] = decode(cls, v) if cls and isinstance(v, dict) else v
        with self._lock:
            out = getattr(self.backend, method)(**call_kwargs)
        return to_jsonable(out)

    def start(self) -> "RemoteStoreServer":
        self._thread = threading.Thread(
            target=self._http.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="remote-store",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.backend.close()

    def __enter__(self) -> "RemoteStoreServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

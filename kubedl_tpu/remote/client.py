"""Blob client for the remote store (urllib only, no extra deps).

Remote model roots are self-describing URLs: ``http://host:port/blobs/
<prefix>`` — `is_remote_root` gates the fetch-on-load path in serving and
the upload path in the `http` storage provider.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path
from typing import List

from kubedl_tpu import chaos


class RemoteError(Exception):
    def __init__(self, msg: str, transient: bool = False) -> None:
        super().__init__(msg)
        #: True for 5xx / connection errors — safe to retry; 4xx is not
        self.transient = transient


def is_remote_root(root: str) -> bool:
    return root.startswith(("http://", "https://")) and "/blobs/" in root


def _split(root: str) -> tuple:
    """'http://h:p/blobs/a/b' -> ('http://h:p', 'a/b')."""
    base, _, prefix = root.partition("/blobs/")
    return base, prefix.strip("/")


def _request_once(url: str, data: bytes = None, method: str = "GET") -> bytes:
    chaos.check("remote.request")
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        raise RemoteError(
            f"{method} {url}: HTTP {e.code}: {e.read()[:200]}",
            transient=e.code >= 500,
        ) from e
    except urllib.error.URLError as e:
        raise RemoteError(f"{method} {url}: {e.reason}", transient=True) from e


def _request(url: str, data: bytes = None, method: str = "GET") -> bytes:
    """One blob-server round trip; transient failures (5xx, connection
    reset, injected chaos) retry under the shared policy, permanent 4xx
    surface immediately."""
    policy = chaos.RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=0.5)
    return policy.call(
        lambda: _request_once(url, data=data, method=method),
        retry_on=(RemoteError, chaos.FaultInjected),
        giveup=lambda e: isinstance(e, RemoteError) and not e.transient,
    )


def _quote_key(key: str) -> str:
    return urllib.parse.quote(key, safe="/")


def put_blob(base_url: str, key: str, data: bytes) -> None:
    _request(f"{base_url}/blobs/{_quote_key(key)}", data=data, method="PUT")


def get_blob(base_url: str, key: str) -> bytes:
    return _request(f"{base_url}/blobs/{_quote_key(key)}")


def delete_blob(base_url: str, key: str) -> None:
    _request(f"{base_url}/blobs/{_quote_key(key)}", method="DELETE")


def list_blobs(base_url: str, prefix: str = "") -> List[str]:
    q = urllib.parse.quote(prefix, safe="/")
    out = json.loads(_request(f"{base_url}/blobs?prefix={q}"))
    return out["keys"]


def upload_tree(local_dir: str, remote_root: str) -> int:
    """Upload every file under ``local_dir`` to the remote prefix.
    Returns the number of files uploaded."""
    base, prefix = _split(remote_root)
    root = Path(local_dir)
    n = 0
    for p in sorted(root.rglob("*")):
        if p.is_file():
            rel = p.relative_to(root).as_posix()
            put_blob(base, f"{prefix}/{rel}" if prefix else rel, p.read_bytes())
            n += 1
    return n


def download_tree(remote_root: str, local_dir: str) -> int:
    """Mirror the remote prefix into ``local_dir``; returns file count."""
    base, prefix = _split(remote_root)
    keys = list_blobs(base, prefix)
    n = 0
    for key in keys:
        rel = key[len(prefix):].lstrip("/") if prefix else key
        dest = Path(local_dir) / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_bytes(get_blob(base, key))
        n += 1
    return n

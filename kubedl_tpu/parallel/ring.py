"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context training support the reference lacks entirely (SURVEY.md §5
"Long-context / sequence parallelism: absent") but is first-class here: the
sequence dim is sharded over an ``sp`` mesh axis, each device holds one
query block, and K/V blocks rotate around the ring via `lax.ppermute` while
an online-softmax accumulator (the flash-attention recurrence) folds each
visiting block in. Peak memory per device is O(S/n * S/n) scores instead of
O(S^2), and the K/V transfer rides ICI neighbor links — the collective
pattern ring attention was designed around (PAPERS.md: Ring Attention with
Blockwise Transformers; blockwise parallel transformer recurrence).

Numerics: fp32 scores/accumulator, bf16 inputs — matches the dense oracle
`kubedl_tpu.models.llama.attention` to ~1e-2 in bf16, ~1e-5 in fp32.

Use inside `shard_map` (the trainer wires this via
`make_context_attention`); RoPE must already be applied with *global*
positions — under jit the caller's rope sees global S, so this holds for
free.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_scores(
    q: jax.Array,  # [B, Sq, H, hd] (already grouped-up for GQA)
    k: jax.Array,  # [B, Sk, H, hd]
    scale: float,
) -> jax.Array:
    return jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale


def ring_attention(
    q: jax.Array,  # [B, S_local, H, hd]
    k: jax.Array,  # [B, S_local, KV, hd]
    v: jax.Array,  # [B, S_local, KV, hd]
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Blockwise ring attention over ``axis_name`` (call under shard_map).

    GQA K/V are repeated up to H heads per block before the score matmul;
    the pallas flash kernel is the fused single-chip analogue
    (kubedl_tpu.ops), this is the cross-chip layer above it.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, hd = q.shape
    KV = k.shape[2]
    if H != KV:
        group = H // KV
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scale = 1.0 / math.sqrt(hd)
    rows = idx * Sl + jnp.arange(Sl)  # global query positions

    acc0 = jnp.zeros((B, H, Sl, hd), jnp.float32)
    m0 = jnp.full((B, H, Sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        acc, m, l, k_blk, v_blk = carry
        j = (idx - t) % n  # which global block this k/v shard is
        s = _block_scores(q, k_blk, scale)  # [B, H, Sl, Sl]
        if causal:
            cols = j * Sl + jnp.arange(Sl)
            mask = rows[:, None] >= cols[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # fully-masked-so-far rows keep m at NEG_INF; exp() stays finite
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhst,bthd->bhsd", p.astype(v_blk.dtype), v_blk)
        acc = acc * corr[..., None] + pv.astype(jnp.float32)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (acc, m_new, l, k_blk, v_blk), None

    (acc, _, l, _, _), _ = lax.scan(
        tick, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Sl, hd]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,  # [B, S_local, H, hd]
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: one `all_to_all`
    re-shards seq-sharded/head-replicated tensors into seq-replicated/
    head-sharded, dense attention runs locally per head group, and a second
    all_to_all restores sequence sharding. One collective round-trip instead
    of a ring of n-1 ppermutes — better when heads >= axis size and the
    sequence still fits per-device (PAPERS.md: Ulysses). Requires H and KV
    divisible by the axis size.
    """
    from kubedl_tpu.models.llama import attention

    a2a = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    q, k, v = a2a(q), a2a(k), a2a(v)  # [B, S, H/n, hd]
    out = attention(q, k, v, causal=causal)
    # restore: split S back out, concatenate heads
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def make_context_attention(
    mesh: Mesh,
    sp_axis: str = "sp",
    batch_axes: Tuple[str, ...] = ("replica", "data", "fsdp"),
    head_axis: str = "tensor",
    impl: str = "ring",
    causal: bool = True,
):
    """Wrap ring/ulysses attention in shard_map for use inside a jitted
    forward (the trainer passes the result as ``attn_fn`` to llama_forward).

    Returns None if the mesh has no ``sp_axis`` (caller falls back to dense
    attention — XLA shards that fine without sequence parallelism).
    """
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown context-parallel impl {impl!r}; "
                         "expected 'ring' or 'ulysses'")
    if sp_axis not in mesh.axis_names or mesh.shape[sp_axis] <= 1:
        return None
    from kubedl_tpu.utils.shardmap import shard_map

    bt = tuple(a for a in batch_axes if a in mesh.axis_names)
    ht = head_axis if head_axis in mesh.axis_names else None
    spec = P(bt if bt else None, sp_axis, ht, None)
    fn = ring_attention if impl == "ring" else ulysses_attention
    inner = shard_map(
        partial(fn, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)

    make_causal = causal

    def attn_fn(q, k, v, causal=None, mask=None):  # llama.attention signature
        # the ring recurrence is specialized at build time — reject silent
        # divergence from the requested semantics (None = build-time value)
        if mask is not None:
            raise ValueError(
                "ring/ulysses attention does not support arbitrary masks; "
                "use the dense oracle or flash_attention for masked paths"
            )
        if causal is not None and causal != make_causal:
            raise ValueError(
                f"context attention was built with causal={make_causal}; "
                f"got causal={causal} at call time"
            )
        q = lax.with_sharding_constraint(q, sharding)
        k = lax.with_sharding_constraint(k, sharding)
        v = lax.with_sharding_constraint(v, sharding)
        return inner(q, k, v)

    return attn_fn

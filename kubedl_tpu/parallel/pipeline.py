"""Pipeline parallelism: GPipe-style microbatched stage execution.

The layer stack is split into ``n`` stages along a ``pipe`` mesh axis; each
device owns one stage's weights (sharded on the stacked leading axis) and
activations flow stage-to-stage with `lax.ppermute` — a neighbor transfer
that rides ICI, never DCN. Scheduling is the classic GPipe fill/drain: with
M microbatches the loop runs M + n - 1 ticks, every device executing the
same compiled tick body (SPMD — no per-stage programs to compile).

Differentiable end-to-end: the tick loop is a `lax.scan`, so reverse-mode
AD through the whole pipeline works and the backward pass is itself a
pipeline (reversed ring) — no hand-written backward schedule needed.

Bubble fraction is (n-1)/(M+n-1); callers pick M >= 4n to keep it small.
The reference has no in-process parallelism at all (SURVEY.md §2.5: TP/PP
absent) — this is net-new TPU capability.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_mb: jax.Array,  # [M, mb, ...] microbatched input (replicated)
    axis_name: str,
) -> jax.Array:
    """Run microbatches through the stage ring (call under shard_map).

    ``stage_fn(stage_params, x)`` applies THIS device's stage (its slice of
    the layer stack). Returns the last stage's outputs, replicated across
    the pipe axis, shape [M, mb, ...].
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]

    state0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        state, out = carry
        # stage 0 ingests microbatch t (clamped during drain); others take
        # the activation handed over from the previous stage last tick
        feed = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        x = jnp.where(idx == 0, feed, state)
        y = stage_fn(stage_params, x)
        # the last stage completes microbatch t-(n-1) at tick t
        mb_done = t - (n - 1)
        write = (idx == n - 1) & (mb_done >= 0)
        slot = jnp.clip(mb_done, 0, M - 1)
        cur = lax.dynamic_index_in_dim(out, slot, axis=0, keepdims=False)
        upd = jnp.where(write, y, cur)
        out = lax.dynamic_update_index_in_dim(out, upd, slot, axis=0)
        state = lax.ppermute(y, axis_name, [(i, (i + 1) % n) for i in range(n)])
        return (state, out), None

    (_, out), _ = lax.scan(tick, (state0, out0), jnp.arange(M + n - 1))
    # replicate the last stage's outputs to every stage (cheap at our M*mb;
    # keeps out_specs simple and check_rep happy being explicit)
    return lax.psum(jnp.where(idx == n - 1, out, jnp.zeros_like(out)), axis_name)


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable,
    pipe_axis: str = "pipe",
    params_leading_axis_sharded: bool = True,
    data_axes: tuple = (),
):
    """Wrap pipeline_apply in shard_map over ``pipe_axis`` (and, for the
    activations' microbatch dim, over ``data_axes`` — GPipe composes with
    data parallelism for free: each dp shard runs its own pipeline over the
    same stage weights).

    Returns ``run(stacked_params, x_mb)`` where ``stacked_params`` leaves
    have a leading [n_stages, ...] axis (sharded across the pipe axis) and
    ``x_mb`` is [M, mb, ...] with mb sharded over ``data_axes``.
    ``stage_fn(params_slice, x)`` sees its own stage's slice with the
    leading axis collapsed to this stage's share.
    """
    from jax import shard_map

    pspec = P(pipe_axis) if params_leading_axis_sharded else P()
    dt = tuple(a for a in data_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    xspec = P(None, dt if dt else None)  # [M, mb, ...rest replicated]

    def local(stage_params, x_mb):
        return pipeline_apply(stage_fn, stage_params, x_mb, pipe_axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_vma=False,
    )

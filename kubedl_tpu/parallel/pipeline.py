"""Pipeline parallelism: GPipe-style microbatched stage execution.

The layer stack is split into ``n`` stages along a ``pipe`` mesh axis; each
device owns one stage's weights (sharded on the stacked leading axis) and
activations flow stage-to-stage with `lax.ppermute` — a neighbor transfer
that rides ICI, never DCN. Scheduling is the classic GPipe fill/drain: with
M microbatches the loop runs M + n - 1 ticks, every device executing the
same compiled tick body (SPMD — no per-stage programs to compile).

Differentiable end-to-end: the tick loop is a `lax.scan`, so reverse-mode
AD through the whole pipeline works and the backward pass is itself a
pipeline (reversed ring) — no hand-written backward schedule needed.

Bubble fraction is (n-1)/(M+n-1); callers pick M >= 4n to keep it small.
The reference has no in-process parallelism at all (SURVEY.md §2.5: TP/PP
absent) — this is net-new TPU capability.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class PipelineHooks:
    """What a model family provides to run through the GPipe pipeline
    (llama.pipeline_hooks / moe.pipeline_hooks): pure functions so the
    trainer stays family-agnostic (VERDICT r2 #5: the round-2 pipeline
    loss hardcoded Llama)."""

    #: embed(params, tokens [B,S]) -> activations [B, S, D]
    embed: Callable
    #: rope(S) -> (cos, sin) position tables
    rope: Callable
    #: make_stage(attn_fn, cos, sin, tp_axis=, ep_axis=) ->
    #:   stage_fn(layer_params_slice, x) -> (y, aux_scalar)
    make_stage: Callable
    #: head_loss(params, h [B,S,D], tokens, aux_mean) -> scalar loss
    head_loss: Callable
    n_layers: int


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_mb: jax.Array,  # [M, mb, ...] microbatched input (replicated)
    axis_name: str,
):
    """Run microbatches through the stage ring (call under shard_map).

    ``stage_fn(stage_params, x) -> (y, aux)`` applies THIS device's stage
    (its slice of the layer stack); ``aux`` is a scalar auxiliary-loss
    contribution (e.g. MoE load balancing), summed over VALID ticks only
    (fill/drain ticks process clamped garbage microbatches and must not
    pollute it). Returns ``(out, aux_sum)``: the last stage's outputs
    replicated across the pipe axis [M, mb, ...], and the aux sum over
    every (layer, microbatch) this pipeline processed (psum over pipe).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]

    state0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        state, out, aux_sum = carry
        # stage 0 ingests microbatch t (clamped during drain); others take
        # the activation handed over from the previous stage last tick
        feed = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        x = jnp.where(idx == 0, feed, state)
        y, aux = stage_fn(stage_params, x)
        # stage idx processes microbatch t - idx at tick t; only ticks
        # carrying a real microbatch contribute aux
        valid = (t >= idx) & (t - idx < M)
        aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        # the last stage completes microbatch t-(n-1) at tick t
        mb_done = t - (n - 1)
        write = (idx == n - 1) & (mb_done >= 0)
        slot = jnp.clip(mb_done, 0, M - 1)
        cur = lax.dynamic_index_in_dim(out, slot, axis=0, keepdims=False)
        upd = jnp.where(write, y, cur)
        out = lax.dynamic_update_index_in_dim(out, upd, slot, axis=0)
        state = lax.ppermute(y, axis_name, [(i, (i + 1) % n) for i in range(n)])
        return (state, out, aux_sum), None

    (_, out, aux_sum), _ = lax.scan(
        tick, (state0, out0, jnp.zeros((), jnp.float32)), jnp.arange(M + n - 1)
    )
    # replicate the last stage's outputs to every stage (cheap at our M*mb;
    # keeps out_specs simple and check_rep happy being explicit)
    out = lax.psum(jnp.where(idx == n - 1, out, jnp.zeros_like(out)), axis_name)
    return out, lax.psum(aux_sum, axis_name)


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable,
    pipe_axis: str = "pipe",
    param_specs=None,
    data_axes: tuple = (),
):
    """Wrap pipeline_apply in shard_map over ``pipe_axis`` (and, for the
    activations' microbatch dim, over ``data_axes`` — GPipe composes with
    data parallelism for free: each dp shard runs its own pipeline over the
    same stage weights).

    ``param_specs`` is the PartitionSpec tree for the stacked stage params
    (leading axis on ``pipe_axis``; inner dims may additionally name
    "tensor"/"expert" axes, whose collectives the stage body issues
    itself). Defaults to P(pipe_axis) broadcast over every leaf.

    Returns ``run(stacked_params, x_mb) -> (out, aux_sum)`` where
    ``stacked_params`` leaves have a leading [n_stages, ...] axis and
    ``x_mb`` is [M, mb, ...] with mb sharded over ``data_axes``. The aux
    sum is additionally psum'd over the data axes, so it is a replicated
    scalar: the caller divides by (n_layers * M * dp) for a mean.
    """
    from kubedl_tpu.utils.shardmap import LEGACY, shard_map

    pspec = param_specs if param_specs is not None else P(pipe_axis)
    dt = tuple(a for a in data_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    xspec = P(None, dt if dt else None)  # [M, mb, ...rest replicated]

    def local(stage_params, x_mb):
        out, aux = pipeline_apply(stage_fn, stage_params, x_mb, pipe_axis)
        for a in dt:  # replicate the aux scalar across data shards too
            aux = lax.psum(aux, a)
        return out, aux

    if LEGACY:
        # jax < 0.6 shard_map cannot emit rank-0 residual outputs from
        # partial-eval ("add at least one (singleton) axis" _SpecError on
        # grad); remat the body so the backward recomputes from the
        # pipeline inputs and no scalar residuals cross the boundary
        local = jax.checkpoint(local)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=(xspec, P()),
        check_vma=False,
    )

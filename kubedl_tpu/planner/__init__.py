"""Auto-parallelism planner: model description + slice topology -> MeshSpec.

AMP-style (PAPERS.md: arXiv 2210.07297): enumerate (data, fsdp, sequence,
tensor) layouts over the slice's chips, price each against an analytical
cost model of the ICI/DCN fabric, prune memory-infeasible candidates, and
rank by modeled step time. The winning layout rides the existing
``KUBEDL_MESH_AXES`` env contract into the workers; the engine stamps a
``Planned`` condition/event and re-plans on elastic resize (docs/planning.md).

Pure control-plane Python: no jax import, safe inside the operator.
"""

from kubedl_tpu.planner.costmodel import (  # noqa: F401
    CostBreakdown,
    ModelDesc,
    MODEL_ZOO,
    estimate,
)
from kubedl_tpu.planner.planner import Plan, PlanError, dp_baseline, plan  # noqa: F401
from kubedl_tpu.planner.search import enumerate_layouts, search  # noqa: F401

"""plan(): the one-call entry the TPUJob controller uses.

Wraps the search with timing, the naive pure-data-parallel baseline
comparison (the contract: the chosen layout is never modeled slower than
naive DP, and strictly beats it whenever DP is memory-infeasible), and an
annotation-friendly serialization the engine stamps on the job so a plan
is computed once per (topology, world size) — an elastic resize changes
the world size and naturally invalidates the cached verdict.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Optional

from kubedl_tpu.api.topology import MeshSpec, SliceTopology
from kubedl_tpu.planner.costmodel import CostBreakdown, ModelDesc, estimate
from kubedl_tpu.planner.search import SearchResult, search


class PlanError(Exception):
    """No memory-feasible layout exists for this model on this slice
    shape; the engine fails the job with reason PlanInfeasible."""


@dataclass
class Plan:
    """The planner's verdict for one (model, topology, world size)."""

    mesh: MeshSpec
    topology: str
    num_slices: int
    step_time_ms: float
    compute_ms: float
    comm_ms: float
    #: comm still on the critical path after the sharded-update overlap
    #: hides part of the gradient collective (== comm_ms when overlap is
    #: not priced); step_time_ms = compute_ms + exposed_comm_ms
    exposed_comm_ms: float
    hbm_gib: float
    #: modeled step time of the naive pure-data-parallel layout; None when
    #: DP is infeasible (memory or batch divisibility) on this shape
    baseline_dp_ms: Optional[float]
    candidates_evaluated: int
    plan_ms: float  # host wall time plan() spent

    def to_annotation(self) -> str:
        """Compact JSON for the planned-mesh annotation (the re-plan cache
        key is (topology, slices))."""
        return json.dumps({
            "axes": self.mesh.to_env(),
            "topology": self.topology,
            "slices": self.num_slices,
            "step_ms": round(self.step_time_ms, 3),
            "hbm_gib": round(self.hbm_gib, 3),
        }, sort_keys=True)

    def summary(self) -> str:
        base = (
            f"dp baseline {self.baseline_dp_ms:.1f} ms"
            if self.baseline_dp_ms is not None
            else "dp baseline infeasible"
        )
        return (
            f"mesh [{self.mesh.to_env()}] on {self.num_slices}x"
            f"{self.topology}: predicted step {self.step_time_ms:.1f} ms "
            f"({self.compute_ms:.1f} compute + {self.exposed_comm_ms:.1f} "
            f"exposed of {self.comm_ms:.1f} comm), "
            f"{self.hbm_gib:.1f} GiB/chip HBM; {base}; "
            f"{self.candidates_evaluated} candidates in {self.plan_ms:.1f} ms"
        )


def dp_baseline(
    model: ModelDesc,
    topo: SliceTopology,
    num_slices: int = 1,
    efficiency: Optional[float] = None,
) -> CostBreakdown:
    """Price the naive layout planning replaces: pure data parallel over
    every chip (replica across slices) — exactly what
    ``MeshSpec.for_slice`` defaults to."""
    mesh = MeshSpec.for_slice(topo, num_slices=num_slices)
    cost = estimate(model, topo, mesh, num_slices, efficiency=efficiency)
    if cost.feasible and model.global_batch % (topo.chips * num_slices):
        # structurally illegal (each gradient replica needs >= 1 sequence):
        # the search would never emit it, so the baseline must not claim it
        cost.feasible = False
        cost.reason = (
            f"global_batch {model.global_batch} not divisible by "
            f"{topo.chips * num_slices} data-parallel ranks"
        )
    return cost


def plan(
    model: ModelDesc,
    topo: SliceTopology,
    num_slices: int = 1,
    efficiency: Optional[float] = None,
) -> Plan:
    """Search the layout space and return the best feasible plan.

    ``efficiency`` overrides the cost model's flops-efficiency constant —
    the controller passes ``calibrated_flops_efficiency()[0]`` so
    admission-time estimates track measured bench MFU. Raises
    :class:`PlanError` when nothing fits — the model cannot train on this
    slice shape under any supported sharding.
    """
    t0 = time.perf_counter()
    errs = model.validate()
    if errs:
        raise PlanError("; ".join(errs))
    res: SearchResult = search(
        model, topo, max(num_slices, 1), efficiency=efficiency
    )
    plan_ms = (time.perf_counter() - t0) * 1e3
    if not res.ranked:
        worst = min(
            (c.hbm_gib for c in res.infeasible), default=0.0
        )
        raise PlanError(
            f"no memory-feasible layout for {model.num_params():,} params "
            f"on {max(num_slices, 1)}x{topo.name} "
            f"({topo.hbm_gib_per_chip} GiB/chip; best candidate still "
            f"needs {worst:.1f} GiB/chip)"
        )
    best = res.best
    base = dp_baseline(model, topo, max(num_slices, 1), efficiency=efficiency)
    return Plan(
        mesh=best.mesh,
        topology=topo.name,
        num_slices=max(num_slices, 1),
        step_time_ms=best.step_ms,
        compute_ms=best.compute_ms,
        comm_ms=best.comm_ms,
        exposed_comm_ms=best.exposed_comm_ms,
        hbm_gib=best.hbm_gib,
        baseline_dp_ms=base.step_ms if base.feasible else None,
        candidates_evaluated=res.evaluated,
        plan_ms=plan_ms,
    )

"""Analytical per-step cost model for one candidate mesh layout.

Three ingredients, all closed-form (AMP, arXiv 2210.07297, §4 — an
alpha-beta cost model is enough to rank layouts; exactness only matters
within a candidate set priced by the SAME model):

- **Compute**: dense-transformer training flops (6 * params per token,
  matching ``LlamaConfig.flops_per_token``) spread over every chip, at a
  fixed fraction of ``SliceTopology.peak_bf16_tflops``. Constant across
  candidates, so it anchors predictions without changing the ranking.
- **Communication**: per-axis collective volume — gradient all-reduce on
  the data/replica axes, param all-gather + gradient reduce-scatter on the
  fsdp axis (ZeRO-3), per-layer activation all-reduces on the tensor axis
  (megatron), ring K/V exchange on the sp axis — priced against
  ``ici_gbps`` for intra-slice axes and ``dcn_gbps`` for the slice-crossing
  replica axis. No overlap is assumed: modeled step time is compute + comm,
  a pessimistic-but-monotone upper bound.
- **Memory**: params + gradients + Adam moments sharded over (fsdp x
  tensor) and replicated over the batch axes, plus remat-resident
  activations and the loss-chunk logits buffer, against
  ``hbm_gib_per_chip`` with a runtime reserve.

Assumptions are spelled out in docs/planning.md; the constants below are
single-sourced so the unit tests pin the formulas, not magic numbers.
"""

from __future__ import annotations

import glob
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.topology import MeshSpec, SliceTopology

#: bytes per element for the dtypes the trainer supports
DTYPE_BYTES = {
    "bfloat16": 2, "bf16": 2,
    "float16": 2, "fp16": 2,
    "float32": 4, "fp32": 4,
}

#: Adam keeps two fp32 moments per parameter (training/trainer.py default
#: opt_moment_dtype="float32").
OPT_BYTES_PER_PARAM = 8

#: Fraction of peak flops an honest dense step achieves. The FALLBACK
#: when no committed bench artifact carries a measured MFU —
#: :func:`calibrated_flops_efficiency` reads the real number from
#: BENCH_*.json history and ``workloads/tpujob.py`` feeds it to
#: :func:`plan` at admission; this constant keeps ``estimate()``
#: deterministic for the formula-pinning unit tests.
MODEL_FLOPS_EFFICIENCY = 0.4

#: Price the trainer's ZeRO-style cross-replica sharded weight update
#: (arXiv 2004.13336; TrainConfig.shard_update, on by default): gradient
#: reduce-scatter + param all-gather on the data axis move the same bytes
#: as the all-reduce they replace, but optimizer state and the update
#: compute drop to 1/data per chip.
UPDATE_SHARDING = True

#: Fraction of the data/replica-axis gradient collective hidden under
#: backward compute by the overlapped microbatch loop
#: (TrainConfig.overlap_comm; arXiv 2011.03641 measures TPU collectives
#: hiding 70-90% under compute once scheduled concurrently — 0.7 is the
#: conservative end). Only the non-hidden remainder counts toward step
#: time; hiding is capped by the compute it hides under.
OVERLAP_FRACTION = 0.7

#: Fraction of HBM the planner may budget; the rest covers the XLA
#: runtime, collective scratch, and fragmentation.
HBM_USABLE_FRACTION = 0.9

#: Residual-stream-sized tensors the remat policy keeps live per layer
#: (models/llama.py: scan + checkpoint saves a handful of per-layer
#: activations; everything else is recomputed in backward).
ACT_SAVED_PER_LAYER = 4.0

#: Live microbatch the memory model assumes: gradient accumulation caps
#: resident activations at one sequence per chip regardless of the
#: per-replica batch (comm volume still counts every sequence — all
#: microbatches cross the wire each step).
ACT_MICROBATCH_SEQS = 1

#: Positions the chunked LM loss materializes at fp32 logits at once
#: (models/llama.py loss_chunk rationale).
LOSS_CHUNK_POSITIONS = 512


@dataclass
class ModelDesc:
    """What the planner needs to know about a training workload.

    Riding ``TPUJob.model_desc``: either give ``params`` directly or the
    transformer dims (``layers``/``hidden``/...) and let the planner derive
    the count. ``global_batch`` is sequences per optimizer step — it fixes
    both the tokens each step must push through the chips and how far the
    batch axes can be stretched.
    """

    params: int = 0  # total parameter count; 0 = derive from the dims
    layers: int = 0
    hidden: int = 0
    ffn: int = 0  # 0 -> 4 * hidden
    vocab: int = 32000
    seq_len: int = 2048
    global_batch: int = 8
    dtype: str = "bfloat16"

    def num_params(self) -> int:
        """``params`` when given, else the standard dense-decoder count:
        4h^2 attention + 3h*ffn gated MLP per layer, plus embeddings."""
        if self.params > 0:
            return self.params
        ffn = self.ffn or 4 * self.hidden
        per_layer = 4 * self.hidden * self.hidden + 3 * self.hidden * ffn
        return self.layers * per_layer + self.vocab * self.hidden

    def flops_per_token(self) -> float:
        """Training flops per token (fwd+bwd), 6*N — the same accounting
        ``LlamaConfig.flops_per_token`` uses for MFU."""
        return 6.0 * self.num_params()

    def bytes_per_param(self) -> int:
        return DTYPE_BYTES[self.dtype]

    def validate(self, prefix: str = "modelDesc") -> List[str]:
        errs: List[str] = []
        if self.params <= 0 and (self.layers <= 0 or self.hidden <= 0):
            errs.append(
                f"{prefix} must give params, or layers+hidden to derive them"
            )
        for name in ("params", "layers", "hidden", "ffn"):
            if getattr(self, name) < 0:
                errs.append(f"{prefix}.{name} must be >= 0")
        if self.vocab < 1:
            errs.append(f"{prefix}.vocab must be >= 1")
        if self.seq_len < 1:
            errs.append(f"{prefix}.seqLen must be >= 1")
        if self.global_batch < 1:
            errs.append(f"{prefix}.globalBatch must be >= 1")
        if self.dtype not in DTYPE_BYTES:
            errs.append(
                f"{prefix}.dtype {self.dtype!r} unknown; one of "
                + ", ".join(sorted(DTYPE_BYTES))
            )
        return errs


#: Small model zoo shared by the golden-plan tests, the planner microbench
#: and the bench section. Batch sizes are chosen so the pure-data-parallel
#: candidate stays *structurally* legal up to 256 chips (512 % 256 == 0) —
#: when DP loses it must lose on memory or comm, not on divisibility.
MODEL_ZOO: Dict[str, ModelDesc] = {
    # matches models/llama.py TINY (the CPU-testable config)
    "tiny": ModelDesc(layers=2, hidden=64, ffn=256, vocab=256,
                      seq_len=128, global_batch=8),
    "gpt-350m": ModelDesc(layers=24, hidden=1024, ffn=4096, vocab=32000,
                          seq_len=2048, global_batch=512),
    "llama-1b": ModelDesc(layers=16, hidden=2048, ffn=8192, vocab=128256,
                          seq_len=2048, global_batch=512),
    "llama-4b": ModelDesc(layers=24, hidden=3072, ffn=12288, vocab=32000,
                          seq_len=2048, global_batch=512),
}


# ---- collective volume primitives (bytes ONE chip sends) -----------------
# Ring-algorithm costs for an n-way collective over a buffer of ``nbytes``
# (the full, unsharded-on-this-axis buffer): these are the standard
# 2(n-1)/n and (n-1)/n factors every topology-aware cost model uses.


def allreduce_bytes(n: int, nbytes: float) -> float:
    return 0.0 if n <= 1 else 2.0 * (n - 1) / n * nbytes


def allgather_bytes(n: int, nbytes: float) -> float:
    return 0.0 if n <= 1 else (n - 1) / n * nbytes


def reduce_scatter_bytes(n: int, nbytes: float) -> float:
    return 0.0 if n <= 1 else (n - 1) / n * nbytes


@dataclass
class CostBreakdown:
    """One candidate layout, fully priced."""

    mesh: MeshSpec
    step_ms: float = math.inf
    compute_ms: float = 0.0
    comm_ms: float = 0.0
    #: per-axis comm cost, e.g. {"data": 1.2, "fsdp": 3.4} (ms)
    comm_ms_by_axis: Dict[str, float] = field(default_factory=dict)
    #: comm left on the critical path after overlap hides part of the
    #: data/replica gradient collective under backward compute;
    #: == comm_ms when the update is not sharded/overlapped
    exposed_comm_ms: float = 0.0
    hbm_gib: float = 0.0
    feasible: bool = False
    reason: str = ""  # why infeasible, when it is


def _axis_sizes(mesh: MeshSpec) -> Dict[str, int]:
    get = mesh.axes.get
    return {
        "replica": get("replica", 1), "data": get("data", 1),
        "fsdp": get("fsdp", 1), "sp": get("sp", 1),
        "tensor": get("tensor", 1),
    }


def hbm_per_chip_gib(
    model: ModelDesc,
    mesh: MeshSpec,
    update_sharding: bool = UPDATE_SHARDING,
) -> float:
    """Per-chip HBM under the candidate sharding: model state sharded over
    (fsdp x tensor), activations over (batch axes x sp), logits over
    tensor. With ``update_sharding`` the gradient accumulator and Adam
    moments additionally shard over the data axis (the trainer's
    cross-replica update; params stay gathered between steps)."""
    ax = _axis_sizes(mesh)
    p = model.num_params()
    state_shard = p / (ax["fsdp"] * ax["tensor"])
    upd = ax["data"] if update_sharding else 1
    state = state_shard * (
        model.bytes_per_param()  # params (gathered between steps)
        + model.bytes_per_param() / upd  # grads (scattered accumulator)
        + OPT_BYTES_PER_PARAM / upd  # Adam moments track the update shard
    )
    seq_local = model.seq_len / ax["sp"]
    act_bytes = DTYPE_BYTES[model.dtype]
    acts = (
        ACT_SAVED_PER_LAYER * model.layers
        * ACT_MICROBATCH_SEQS * seq_local * model.hidden * act_bytes
    ) if model.hidden else 0.0
    logits = (
        ACT_MICROBATCH_SEQS
        * min(LOSS_CHUNK_POSITIONS, seq_local)
        * model.vocab * 4 / ax["tensor"]
    )
    return (state + acts + logits) / 2**30


def estimate(
    model: ModelDesc,
    topo: SliceTopology,
    mesh: MeshSpec,
    num_slices: int = 1,
    update_sharding: bool = UPDATE_SHARDING,
    overlap_fraction: float = OVERLAP_FRACTION,
    efficiency: Optional[float] = None,
) -> CostBreakdown:
    """Price one candidate layout: modeled step time + per-chip HBM.

    The replica axis is the only one allowed to cross slices (search
    guarantees replica == num_slices when num_slices > 1), so it is priced
    at DCN bandwidth; every other axis rides ICI.

    ``update_sharding``/``overlap_fraction`` mirror the trainer's sharded
    weight update and comm/compute overlap: the data/replica gradient
    collective (reduce-scatter + all-gather, same ring bytes as the
    all-reduce it replaces) is partially hidden under backward compute, so
    ``step_ms = compute_ms + exposed_comm_ms``. ``efficiency`` overrides
    MODEL_FLOPS_EFFICIENCY (pass
    ``calibrated_flops_efficiency()[0]`` to price with measured MFU).
    """
    ax = _axis_sizes(mesh)
    out = CostBreakdown(mesh=mesh)

    # ---- memory feasibility ------------------------------------------
    out.hbm_gib = hbm_per_chip_gib(model, mesh, update_sharding)
    budget = topo.hbm_gib_per_chip * HBM_USABLE_FRACTION
    if out.hbm_gib > budget:
        out.reason = (
            f"needs {out.hbm_gib:.1f} GiB/chip, budget {budget:.1f} "
            f"(={HBM_USABLE_FRACTION:.0%} of {topo.hbm_gib_per_chip})"
        )
        return out

    # ---- compute ------------------------------------------------------
    chips = topo.chips * num_slices
    tokens = model.global_batch * model.seq_len
    flops_per_chip = model.flops_per_token() * tokens / chips
    eff = MODEL_FLOPS_EFFICIENCY if efficiency is None else efficiency
    out.compute_ms = flops_per_chip / (
        topo.peak_bf16_tflops * 1e12 * eff
    ) * 1e3

    # ---- communication ------------------------------------------------
    ici = topo.ici_gbps * 1e9
    dcn = topo.dcn_gbps * 1e9
    p_bytes = model.num_params() * model.bytes_per_param()
    # gradient shard each chip owns after fsdp/tensor sharding
    grad_shard = p_bytes / (ax["fsdp"] * ax["tensor"])
    by_axis: Dict[str, float] = {}
    # data axis: grad all-reduce over ICI
    by_axis["data"] = allreduce_bytes(ax["data"], grad_shard) / ici
    # replica axis: the same all-reduce, but over DCN when multislice
    by_axis["replica"] = allreduce_bytes(ax["replica"], grad_shard) / (
        dcn if num_slices > 1 else ici
    )
    # fsdp axis (ZeRO-3): all-gather params fwd + bwd, reduce-scatter grads
    fsdp_buf = p_bytes / ax["tensor"]
    by_axis["fsdp"] = (
        2 * allgather_bytes(ax["fsdp"], fsdp_buf)
        + reduce_scatter_bytes(ax["fsdp"], fsdp_buf)
    ) / ici
    # tensor axis (megatron): 2 activation all-reduces per layer, fwd+bwd.
    # Every sequence crosses the wire each step (grad accum does not shave
    # comm), so the buffer uses the full per-replica batch.
    batch_local = model.global_batch / (ax["replica"] * ax["data"] * ax["fsdp"])
    act_buf = (
        batch_local * (model.seq_len / ax["sp"]) * model.hidden
        * DTYPE_BYTES[model.dtype]
    )
    by_axis["tensor"] = (
        4 * model.layers * allreduce_bytes(ax["tensor"], act_buf) / ici
    )
    # sp axis (ring attention): K and V circulate the ring, fwd + bwd
    by_axis["sp"] = 0.0
    if ax["sp"] > 1:
        kv_buf = act_buf * 2  # K and V, same shape class as the act buffer
        by_axis["sp"] = (
            2 * model.layers * (ax["sp"] - 1) / ax["sp"] * kv_buf / ici
        )
    out.comm_ms_by_axis = {k: v * 1e3 for k, v in by_axis.items() if v > 0}
    out.comm_ms = sum(out.comm_ms_by_axis.values())
    # ---- overlap ------------------------------------------------------
    # The sharded update turns the data/replica grad all-reduce into
    # reduce-scatter + all-gather (same ring bytes); the overlapped
    # microbatch loop hides overlap_fraction of it under backward compute,
    # capped by the compute actually available to hide under. fsdp/tensor/
    # sp collectives stay on the critical path (they gate the very next
    # matmul).
    hidden_ms = 0.0
    if update_sharding and overlap_fraction > 0.0:
        grad_coll_ms = out.comm_ms_by_axis.get("data", 0.0) + (
            out.comm_ms_by_axis.get("replica", 0.0)
        )
        hidden_ms = min(overlap_fraction * grad_coll_ms, out.compute_ms)
    out.exposed_comm_ms = out.comm_ms - hidden_ms
    out.step_ms = out.compute_ms + out.exposed_comm_ms
    out.feasible = True
    return out


# ---- efficiency calibration from bench history ---------------------------


def _walk_mfu(node) -> List[float]:
    """Every dense-MFU number in an artifact, any vintage of layout:
    ``summary.mfu.median``, ``summary.mfu`` (plain float),
    ``parsed.detail.mfu``, ``runs[i].detail.mfu`` — the key is always
    literally "mfu"; long_context_mfu is NOT calibration input (the
    efficiency constant anchors the dense regime)."""
    found: List[float] = []
    if isinstance(node, dict):
        for k, v in node.items():
            if k == "mfu":
                if isinstance(v, (int, float)):
                    found.append(float(v))
                elif isinstance(v, dict) and isinstance(
                    v.get("median"), (int, float)
                ):
                    found.append(float(v["median"]))
            else:
                found.extend(_walk_mfu(v))
    elif isinstance(node, list):
        for v in node:
            found.extend(_walk_mfu(v))
    return found


def calibrated_flops_efficiency(repo_root: Optional[str] = None):
    """(efficiency, source): dense MFU measured by the NEWEST committed
    BENCH_*.json that carries a plausible one, else
    (MODEL_FLOPS_EFFICIENCY, "default").

    Plausible means 0.05 < mfu <= 1.0 — CPU-CI artifacts report mfu ~0
    and must not drag admission-time step estimates to garbage. Medians
    win over single runs (``_walk_mfu``); multiple values in one artifact
    reduce by median. Reads are cheap (a handful of small json files) but
    the result is cached per repo_root for the admission hot path.
    """
    import json
    import statistics

    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    key = os.path.abspath(root)
    if key in _EFFICIENCY_CACHE:
        return _EFFICIENCY_CACHE[key]
    result = (MODEL_FLOPS_EFFICIENCY, "default")
    try:
        arts = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    except OSError:
        arts = []
    for path in reversed(arts):  # newest naming first (BENCH_rNN sorts)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        vals = [v for v in _walk_mfu(doc) if 0.05 < v <= 1.0]
        if vals:
            result = (statistics.median(vals), os.path.basename(path))
            break
    _EFFICIENCY_CACHE[key] = result
    return result


_EFFICIENCY_CACHE: Dict[str, tuple] = {}

"""Layout search: enumerate mesh factorizations, prune, rank.

The candidate space is every factorization of the slice's chips into the
``MeshSpec.AXIS_ORDER`` batch/model axes the trainer supports today —
(data, fsdp, sp, tensor) — with multislice handled by pinning the
``replica`` axis to ``num_slices``: DCN-crossing axes may only be
outermost, and the slice boundary IS the outermost stride of the device
grid, so exactly one axis (replica, first in AXIS_ORDER) may span it.

Pruning is structural (divisibility the trainer would reject anyway) then
physical (per-chip HBM); survivors are ranked by modeled step time with a
deterministic tie-break that prefers simpler, more data-parallel layouts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from kubedl_tpu.api.topology import MeshSpec, SliceTopology
from kubedl_tpu.planner.costmodel import CostBreakdown, ModelDesc, estimate


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _structurally_valid(
    model: ModelDesc, data: int, fsdp: int, sp: int, tensor: int,
    num_slices: int,
) -> bool:
    dp_total = num_slices * data * fsdp
    # every gradient replica needs at least one whole sequence per step
    if model.global_batch % dp_total:
        return False
    # megatron splits attention heads / ffn columns across tensor ranks
    if model.hidden and tensor > 1 and model.hidden % tensor:
        return False
    # ring attention splits the sequence
    if sp > 1 and model.seq_len % sp:
        return False
    # fsdp shards the parameter pytree leaf-wise; one chip per shard floor
    if model.hidden and fsdp > 1 and model.hidden % fsdp:
        return False
    return True


def enumerate_layouts(
    model: ModelDesc, topo: SliceTopology, num_slices: int = 1
) -> List[MeshSpec]:
    """All structurally-valid factorizations of ``num_slices x chips``.

    The replica axis is exactly ``num_slices`` (DCN only ever carries the
    outermost axis); the per-slice chips factor into data/fsdp/sp/tensor.
    """
    chips = topo.chips
    out: List[MeshSpec] = []
    for data in _divisors(chips):
        rem_d = chips // data
        for fsdp in _divisors(rem_d):
            rem_f = rem_d // fsdp
            for sp in _divisors(rem_f):
                tensor = rem_f // sp
                if not _structurally_valid(
                    model, data, fsdp, sp, tensor, num_slices
                ):
                    continue
                axes = {}
                if num_slices > 1:
                    axes["replica"] = num_slices
                axes["data"] = data
                if fsdp > 1:
                    axes["fsdp"] = fsdp
                if sp > 1:
                    axes["sp"] = sp
                if tensor > 1:
                    axes["tensor"] = tensor
                out.append(MeshSpec(axes=axes))
    return out


@dataclass
class SearchResult:
    #: feasible candidates, best (lowest modeled step time) first
    ranked: List[CostBreakdown] = field(default_factory=list)
    #: every candidate priced, including memory-infeasible ones
    evaluated: int = 0
    #: infeasible candidates kept for diagnostics (reason populated)
    infeasible: List[CostBreakdown] = field(default_factory=list)

    @property
    def best(self) -> CostBreakdown:
        return self.ranked[0]


#: A layout must beat the simplest alternative by MORE than this to win:
#: within max(1% of best, 0.5 ms) every candidate is "as fast as the
#: best" and the tie-break below picks the simplest — the cost model's
#: µs-scale noise must never talk a job out of plain data parallelism.
SLACK_RELATIVE = 0.01
SLACK_ABS_MS = 0.5


def _simplicity_key(c: CostBreakdown):
    ax = c.mesh.axes
    model_axes = sum(
        1 for a in ("fsdp", "sp", "tensor") if ax.get(a, 1) > 1
    )
    # fewer model-parallel axes, then more data parallelism, then the
    # smaller tensor degree — deterministic regardless of enumeration order
    return (
        model_axes, -ax.get("data", 1),
        ax.get("tensor", 1), ax.get("fsdp", 1), ax.get("sp", 1),
        round(c.step_ms, 6),
    )


def _rank_key(c: CostBreakdown):
    return (round(c.step_ms, 6),) + _simplicity_key(c)


def search(
    model: ModelDesc,
    topo: SliceTopology,
    num_slices: int = 1,
    efficiency: Optional[float] = None,
) -> SearchResult:
    """Enumerate, price, prune, rank. ``efficiency`` overrides the cost
    model's MODEL_FLOPS_EFFICIENCY (bench-calibrated MFU at admission)."""
    res = SearchResult()
    for mesh in enumerate_layouts(model, topo, num_slices):
        cost = estimate(model, topo, mesh, num_slices, efficiency=efficiency)
        res.evaluated += 1
        (res.ranked if cost.feasible else res.infeasible).append(cost)
    res.ranked.sort(key=_rank_key)
    if res.ranked:
        # simplest-within-slack wins the top spot (see SLACK_* above)
        best_ms = res.ranked[0].step_ms
        cut = max(best_ms * (1 + SLACK_RELATIVE), best_ms + SLACK_ABS_MS)
        near = [c for c in res.ranked if c.step_ms <= cut]
        near.sort(key=_simplicity_key)
        rest = [c for c in res.ranked if c not in near]
        res.ranked = near + rest
    return res

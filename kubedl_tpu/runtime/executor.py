"""Kubelet: watches pods, runs containers, reports phases back to the store."""

from __future__ import annotations

import importlib
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from kubedl_tpu.core.manager import ControllerManager
from kubedl_tpu.core.objects import BaseObject, ContainerStatus, Pod, PodPhase
from kubedl_tpu.core.store import NotFound, ObjectStore

log = logging.getLogger("kubedl_tpu.runtime")

#: OS pid of the pod's main process, stamped at launch — the handle a
#: RESTARTED kubelet needs to re-attach to (adopt) a still-running pod
#: instead of orphaning or re-creating it (docs/robustness.md)
PID_ANNOTATION = "kubedl-tpu.io/runtime-pid"


class ProcHandle:
    """One running container; wait() returns the exit code."""

    def wait(self) -> int:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def pid(self) -> Optional[int]:
        """OS pid when the container is a real process (adoptable across
        operator restarts); None for thread/placeholder handles."""
        return None


class ContainerRuntime:
    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        raise NotImplementedError


class _SubprocHandle(ProcHandle):
    def __init__(self, proc: subprocess.Popen) -> None:
        self.proc = proc

    def wait(self) -> int:
        return self.proc.wait()

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def pid(self) -> Optional[int]:
        return self.proc.pid


class _AttachedHandle(ProcHandle):
    """A process launched by a PREVIOUS operator incarnation, re-attached
    by pid after a restart. When the pid is still this process's child
    (in-process crash simulation) ``waitpid`` yields the real exit status;
    an orphan reparented to init can only be liveness-polled, so its exit
    reads as 0 — a non-child cannot be reaped, which is the documented
    adoption limit (real kubelets read containerd state instead)."""

    def __init__(self, pid: int) -> None:
        self._pid = pid

    def pid(self) -> Optional[int]:
        return self._pid

    def _poll(self) -> Optional[int]:
        """None while alive; exit code once gone."""
        try:
            done, status = os.waitpid(self._pid, os.WNOHANG)
            if done == self._pid:
                if os.WIFEXITED(status):
                    return os.WEXITSTATUS(status)
                if os.WIFSIGNALED(status):
                    return -os.WTERMSIG(status)
                return 1
            return None
        except ChildProcessError:
            pass  # not our child (true orphan) or already reaped elsewhere
        try:
            os.kill(self._pid, 0)
            return None
        except ProcessLookupError:
            return 0  # gone; exit code unknowable for a non-child
        except PermissionError:
            return None  # alive, different user

    def wait(self) -> int:
        while True:
            code = self._poll()
            if code is not None:
                return code
            time.sleep(0.05)

    def kill(self) -> None:
        try:
            os.kill(self._pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + 3.0
        while time.time() < deadline:
            if self._poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.kill(self._pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class SubprocessRuntime(ContainerRuntime):
    """Run the main container's argv as a real OS process. `python` in the
    argv resolves to the current interpreter so env (JAX flags) carries."""

    def __init__(self, log_dir: str = "") -> None:
        self.log_dir = log_dir

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        main = pod.spec.main_container()
        argv = list(main.command)
        if not argv:
            raise ValueError(f"pod {pod.metadata.name}: empty command")
        if argv[0] == "python":
            argv[0] = sys.executable
        full_env = {**os.environ, **env}
        # spawn timestamp: entrypoints attribute pod-spawn -> process-start
        # latency in their startup breakdown (launch-delay parity with the
        # reference's job_metrics.go:139-194, but per-phase)
        full_env.setdefault("KUBEDL_SPAWN_TS", repr(time.time()))
        stdout = None
        if self.log_dir:
            # namespaced: same-named pods in different namespaces must not
            # share (or leak) a log file
            ns_dir = os.path.join(self.log_dir, pod.metadata.namespace)
            os.makedirs(ns_dir, exist_ok=True)
            stdout = open(  # noqa: SIM115 - handle outlives this scope
                os.path.join(ns_dir, f"{pod.metadata.name}.log"), "ab"
            )
        proc = subprocess.Popen(
            argv,
            env=full_env,
            cwd=main.working_dir or None,
            stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None,
        )
        return _SubprocHandle(proc)


#: env key under which ThreadRuntime passes the cancellation Event object
#: (entrypoints poll `env.get(CANCEL_EVENT_KEY)` between steps; cooperative
#: — threads can't be killed)
CANCEL_EVENT_KEY = "_KUBEDL_CANCEL"


class _ThreadHandle(ProcHandle):
    def __init__(self, fn: Callable[[Dict[str, str]], object], env: Dict[str, str]) -> None:
        self._exit = 0
        self._done = threading.Event()
        self._cancel = threading.Event()
        env = dict(env)
        env[CANCEL_EVENT_KEY] = self._cancel  # type: ignore[assignment]

        def run() -> None:
            try:
                rc = fn(env)
                self._exit = int(rc) if isinstance(rc, int) else 0
            except SystemExit as e:
                # sys.exit(None)=0, sys.exit(int)=int, sys.exit(str)=failure
                if e.code is None:
                    self._exit = 0
                elif isinstance(e.code, int):
                    self._exit = e.code
                else:
                    log.error("entrypoint exited with message: %s", e.code)
                    self._exit = 1
            except Exception:
                log.error("entrypoint raised:\n%s", traceback.format_exc())
                self._exit = 1
            finally:
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> int:
        self._done.wait()
        return self._exit

    def kill(self) -> None:
        # threads are not killable; entrypoints poll env[CANCEL_EVENT_KEY]
        self._cancel.set()


class ThreadRuntime(ContainerRuntime):
    """Resolve `container.entrypoint` ("pkg.mod:fn") and call fn(env) in a
    thread. fn returns an int exit code (or None == 0)."""

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        main = pod.spec.main_container()
        if not main.entrypoint:
            raise ValueError(f"pod {pod.metadata.name}: no entrypoint for ThreadRuntime")
        mod_name, _, fn_name = main.entrypoint.partition(":")
        fn = getattr(importlib.import_module(mod_name), fn_name)
        return ThreadRuntime.spawn(fn, env)

    @staticmethod
    def spawn(fn: Callable, env: Dict[str, str]) -> ProcHandle:
        return _ThreadHandle(fn, env)


class FakeRuntime(ContainerRuntime):
    """Containers never actually run; tests drive phases via Kubelet-free
    store updates (see tests/helpers.py)."""

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:  # pragma: no cover
        raise RuntimeError("FakeRuntime pods are driven manually by tests")


class Kubelet:
    """Realizes Pending pods and reports their lifecycle.

    One Kubelet instance typically serves ALL simulated nodes on this
    machine (locally it plays every TPU host); pass `nodes` to restrict it
    to a subset for multi-agent setups.
    """

    NAME = "kubelet"

    def __init__(
        self,
        store: ObjectStore,
        runtime: ContainerRuntime,
        nodes: Optional[set] = None,
        pod_ip: str = "127.0.0.1",
        metrics=None,
    ) -> None:
        self.store = store
        self.runtime = runtime
        self.nodes = nodes
        self.pod_ip = pod_ip
        self.metrics = metrics  # JobMetrics or None (adopted_pods counter)
        #: processes started by THIS incarnation — the restart e2e asserts
        #: zero duplicate creates via this count
        self.launch_count = 0
        self.adopted_count = 0
        #: (ns/name -> uid) of RUNNING pods captured at begin_recovery():
        #: exactly the pods whose processes may have outlived the previous
        #: operator. Adoption applies ONLY to these — in steady state a
        #: RUNNING pod missing from _running is a reap-in-progress race,
        #: not an orphan, and must not be failed or re-attached.
        self._recovery: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._running: Dict[str, ProcHandle] = {}
        #: pod uid each running handle belongs to — a same-name replacement
        #: pod (elastic resize deletes RUNNING pods and recreates them)
        #: must not be mistaken for the pod whose process is still alive
        self._running_uid: Dict[str, str] = {}
        #: (ns, pod, volume) -> (pod uid, ConfigMap resource version) last
        #: materialized; cleared when the pod is deleted
        self._materialized: Dict[tuple, tuple] = {}
        #: ns/name -> progress-beacon file path (KUBEDL_BEACON_FILE env),
        #: recorded at launch so pod deletion can remove the file — a
        #: stale beacon from a dead pod must never be re-published
        self._beacon_files: Dict[str, str] = {}

    def setup(self, manager: ControllerManager) -> None:
        def mapper(event: str, obj: BaseObject, old):
            if obj.kind == "ConfigMap":
                # re-sync mounted ConfigMap volumes of running pods (real
                # kubelet semantics; e.g. MPI hostfile refresh on scale)
                keys = []
                for pod in self.store.list("Pod", obj.metadata.namespace):
                    if any(
                        v.config_map == obj.metadata.name
                        for v in pod.spec.volumes  # type: ignore[union-attr]
                    ):
                        keys.append((pod.metadata.namespace, pod.metadata.name))
                return keys
            return [(obj.metadata.namespace, obj.metadata.name)]

        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Pod", "ConfigMap"],
            mapper=mapper,
            workers=4,
            # list-then-watch: pods that already exist when the manager
            # starts (rehydrated store) get their launch/adoption pass
            # without waiting for a mutation
            resync_on_start=True,
        )

    # ------------------------------------------------------------------

    def _served(self, pod: Pod) -> bool:
        return self.nodes is None or pod.spec.node_name in self.nodes

    @staticmethod
    def _pod_env(pod: Pod) -> Dict[str, str]:
        env: Dict[str, str] = {}
        for c in pod.spec.init_containers + pod.spec.containers:
            for e in c.env:
                env[e.name] = e.value
        env["KUBEDL_POD_NAME"] = pod.metadata.name
        env["KUBEDL_POD_NAMESPACE"] = pod.metadata.namespace
        env["KUBEDL_NODE_NAME"] = pod.spec.node_name
        return env

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        key = f"{namespace}/{name}"
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            # deleted: kill the container but KEEP the _running slot — the
            # reap thread frees it (and relaunches any same-name
            # replacement) only after handle.wait() returns, i.e. after
            # the old container fully tore down. Freeing the slot here let
            # a replacement launch while the cancelled entrypoint was
            # still unwinding — two trainers sharing one device runtime,
            # one of them mid-teardown (real kubelets likewise never start
            # a same-name container before the old one is gone).
            with self._lock:
                handle = self._running.get(key)
                beacon = self._beacon_files.pop(key, None)
                for sk in [k for k in self._materialized
                           if (k[0], k[1]) == (namespace, name)]:
                    del self._materialized[sk]
            if handle is not None:
                handle.kill()
            if beacon:
                try:
                    os.unlink(beacon)
                except OSError:
                    pass
            return None
        assert isinstance(pod, Pod)
        if not self._served(pod):
            return None
        if pod.is_terminal():
            # a pod marked terminal EXTERNALLY (node-lifecycle eviction)
            # may still have a live local process: kill it, or its
            # same-name replacement can never launch (the reap thread
            # frees the slot and relaunches). In the normal flow the
            # handle is popped before the terminal phase is stamped, so a
            # live handle here always means external termination.
            with self._lock:
                handle = self._running.get(key)
                self._recovery.pop(key, None)
            if handle is not None and not isinstance(handle, _PlaceholderHandle):
                handle.kill()
            return None
        if self._recovery:
            with self._lock:
                rec_uid = self._recovery.pop(key, None)
            if (
                rec_uid is not None
                and rec_uid == pod.metadata.uid
                and pod.status.phase == PodPhase.RUNNING
            ):
                return self._adopt(pod, key)
        with self._lock:
            recorded_uid = self._running_uid.get(key)
            stale = (
                key in self._running
                and recorded_uid is not None
                and recorded_uid != pod.metadata.uid
            )
            handle = self._running.get(key)
            already_running = key in self._running and not stale
            if not already_running and not stale:
                if pod.status.phase != PodPhase.PENDING:
                    return None
                # reserve the slot before leaving the lock
                self._running[key] = _PlaceholderHandle()
                self._running_uid[key] = pod.metadata.uid
        if stale:
            # the live process belongs to a same-name pod that was deleted
            # and already replaced before its DELETED event was processed
            # (workqueue coalescing collapses DELETED+ADDED into one key).
            # Cancel it; the reap thread frees the slot and relaunches the
            # replacement.
            if handle is not None:
                handle.kill()
            return None
        if already_running:
            # keep mounted ConfigMap volumes fresh (outside self._lock —
            # materialization takes it internally)
            try:
                self._materialize_config_volumes(pod)
            except RuntimeError:
                pass  # ConfigMap deleted mid-run; keep last snapshot
            return None
        try:
            self._launch(pod, key)
        except Exception as e:
            log.error("launch %s failed: %s", key, e)
            with self._lock:
                self._running.pop(key, None)
                self._running_uid.pop(key, None)
            self._set_phase(pod, PodPhase.FAILED, reason=f"LaunchError: {e}", exit_code=1)
        return None

    def _launch(self, pod: Pod, key: str) -> None:
        env = self._pod_env(pod)
        beacon = env.get("KUBEDL_BEACON_FILE")
        if beacon:
            with self._lock:
                self._beacon_files[key] = beacon
        self._materialize_config_volumes(pod)
        # init containers run to completion first (code-sync etc.)
        for init in pod.spec.init_containers:
            if init.command:
                rc = subprocess.call(init.command, env={**os.environ, **env})
                if rc != 0:
                    raise RuntimeError(f"init container {init.name} exited {rc}")
        handle = self.runtime.start(pod, env)
        self.launch_count += 1
        with self._lock:
            self._running[key] = handle
        pid = handle.pid()
        if pid is not None:
            self._stamp_pid(pod, pid)
        self._set_phase(pod, PodPhase.RUNNING)
        # an eviction landing DURING launch (init containers etc.) found
        # only the placeholder handle and could kill nothing; now that the
        # real handle exists, honor any terminal phase stamped meanwhile
        fresh = self.store.try_get("Pod", pod.metadata.name, pod.metadata.namespace)
        if (
            fresh is None
            or fresh.metadata.uid != pod.metadata.uid
            or fresh.is_terminal()
        ):
            handle.kill()

        self._start_reaper(pod, key, handle)

    def _start_reaper(self, pod: Pod, key: str, handle: ProcHandle) -> None:
        def reap() -> None:
            code = handle.wait()
            with self._lock:
                self._running.pop(key, None)
                self._running_uid.pop(key, None)
            phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            self._set_phase(pod, phase, exit_code=code)
            # a same-name replacement pod may have been created while this
            # process was dying (gang restart) — give it a launch pass now
            # that the _running slot is free
            self.reconcile(pod.metadata.namespace, pod.metadata.name)

        threading.Thread(target=reap, daemon=True, name=f"reap-{key}").start()

    # ---- crash recovery: pod adoption --------------------------------

    def begin_recovery(self) -> int:
        """Arm the adoption pass. Called after store rehydration, BEFORE
        controllers start: records every RUNNING pod of the dead
        incarnation so the first reconcile of each re-attaches its live
        process (by pid annotation) instead of ignoring it forever — or
        fails it retryably when the process did not survive. Returns the
        number of candidates."""
        with self._lock:
            for pod in self.store.list("Pod", namespace=None):
                if not isinstance(pod, Pod) or not self._served(pod):
                    continue
                key = f"{pod.metadata.namespace}/{pod.metadata.name}"
                if (
                    pod.status.phase == PodPhase.RUNNING
                    and not pod.is_terminal()
                    and key not in self._running
                ):
                    self._recovery[key] = pod.metadata.uid
            return len(self._recovery)

    def _adopt(self, pod: Pod, key: str) -> None:
        """First post-restart reconcile of a RUNNING pod: re-attach by
        (name, uid, pid) or fail it retryably (exit 137 -> gang restart)."""
        handle = self._attach(pod)
        if handle is None:
            log.warning(
                "pod %s (uid %s) was Running before the restart but its "
                "process is gone — failing retryably",
                key, pod.metadata.uid,
            )
            self._set_phase(
                pod, PodPhase.FAILED, reason="LostOnRestart", exit_code=137
            )
            return None
        with self._lock:
            self._running[key] = handle
            self._running_uid[key] = pod.metadata.uid
        self.adopted_count += 1
        if self.metrics is not None:
            self.metrics.adopted_pods.inc()
        log.info("adopted pod %s (uid %s, pid %s)", key, pod.metadata.uid,
                 handle.pid())
        self._start_reaper(pod, key, handle)
        return None

    def _attach(self, pod: Pod) -> Optional[ProcHandle]:
        pid_s = pod.metadata.annotations.get(PID_ANNOTATION, "")
        if not pid_s:
            return None  # thread/fake runtime pods die with the process
        try:
            pid = int(pid_s)
        except ValueError:
            return None
        try:
            os.kill(pid, 0)  # liveness (zombie children still count:
            # _AttachedHandle reaps them for the real exit code)
        except ProcessLookupError:
            return None
        except PermissionError:
            pass
        return _AttachedHandle(pid)

    def _stamp_pid(self, pod: Pod, pid: int) -> None:
        """Durably record the pod's OS pid so a restarted kubelet can
        adopt the live process (the containerd-state analogue)."""

        def mutate(obj: Pod) -> None:  # type: ignore[type-arg]
            if obj.metadata.uid != pod.metadata.uid or obj.is_terminal():
                raise Kubelet._StalePod()
            obj.metadata.annotations[PID_ANNOTATION] = str(pid)

        try:
            self.store.update_with_retry(
                "Pod", pod.metadata.name, pod.metadata.namespace, mutate
            )
        except (NotFound, Kubelet._StalePod):
            pass

    def _materialize_config_volumes(self, pod: Pod) -> None:
        """Write ConfigMap-backed volumes to their mount path (the kubelet
        side of the reference's ConfigMap volume mounts). Files are swapped
        in atomically (write-then-rename, the real kubelet's symlink-swap
        equivalent) so a running process never reads a torn hostfile, and
        unchanged ConfigMap versions are skipped."""
        from kubedl_tpu.core.objects import ConfigMap, config_mount_path

        for vol in pod.spec.volumes:
            if not vol.config_map:
                continue
            cm = self.store.try_get(
                "ConfigMap", vol.config_map, pod.metadata.namespace
            )
            if not isinstance(cm, ConfigMap):
                raise RuntimeError(f"ConfigMap {vol.config_map} not found")
            sync_key = (pod.metadata.namespace, pod.metadata.name, vol.name)
            stamp = (pod.metadata.uid, cm.metadata.resource_version)
            with self._lock:
                if self._materialized.get(sync_key) == stamp:
                    continue
            root = vol.mount_path or config_mount_path(
                pod.metadata.namespace, pod.metadata.name, vol.name
            )
            os.makedirs(root, exist_ok=True)
            for fname, content in cm.data.items():
                path = os.path.join(root, fname)
                # per-thread tmp name: concurrent materializers must never
                # interleave writes into the same tmp file
                tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
                with open(tmp, "w") as f:
                    f.write(content)
                if content.startswith("#!"):
                    os.chmod(tmp, 0o755)
                os.replace(tmp, path)
            with self._lock:
                self._materialized[sync_key] = stamp

    class _StalePod(Exception):
        pass

    def _set_phase(
        self,
        pod: Pod,
        phase: PodPhase,
        reason: str = "",
        exit_code: Optional[int] = None,
    ) -> None:
        def mutate(obj: Pod) -> None:  # type: ignore[type-arg]
            if obj.metadata.uid != pod.metadata.uid:
                # same-name pod recreated after a gang restart: the old
                # process's lifecycle must not stamp the fresh pod
                raise Kubelet._StalePod()
            if obj.is_terminal():
                # terminal is final: a pod already failed EXTERNALLY
                # (node-lifecycle eviction, exit 137 retryable) must not
                # be overwritten by the reaped kill signal (-15, which
                # would read as a permanent code-bug failure) or
                # resurrected to Running by an in-flight launch
                raise Kubelet._StalePod()
            obj.status.phase = phase
            obj.status.pod_ip = self.pod_ip
            obj.status.host_ip = self.pod_ip
            if reason:
                obj.status.reason = reason
            if phase == PodPhase.RUNNING and obj.status.start_time is None:
                obj.status.start_time = time.time()
            if phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                obj.status.finish_time = time.time()
                obj.status.container_statuses = [
                    ContainerStatus(exit_code=exit_code if exit_code is not None else 0)
                ]

        try:
            self.store.update_with_retry(
                "Pod", pod.metadata.name, pod.metadata.namespace, mutate
            )
        except (NotFound, Kubelet._StalePod):
            pass

    def shutdown(self) -> None:
        with self._lock:
            handles = list(self._running.values())
            self._running.clear()
            self._running_uid.clear()
        for h in handles:
            h.kill()


class _PlaceholderHandle(ProcHandle):
    def wait(self) -> int:  # pragma: no cover
        return 0

    def kill(self) -> None:
        pass

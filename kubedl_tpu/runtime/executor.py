"""Kubelet: watches pods, runs containers, reports phases back to the store."""

from __future__ import annotations

import importlib
import logging
import os
import subprocess
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from kubedl_tpu.core.manager import ControllerManager
from kubedl_tpu.core.objects import BaseObject, ContainerStatus, Pod, PodPhase
from kubedl_tpu.core.store import NotFound, ObjectStore

log = logging.getLogger("kubedl_tpu.runtime")


class ProcHandle:
    """One running container; wait() returns the exit code."""

    def wait(self) -> int:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError


class ContainerRuntime:
    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        raise NotImplementedError


class _SubprocHandle(ProcHandle):
    def __init__(self, proc: subprocess.Popen) -> None:
        self.proc = proc

    def wait(self) -> int:
        return self.proc.wait()

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class SubprocessRuntime(ContainerRuntime):
    """Run the main container's argv as a real OS process. `python` in the
    argv resolves to the current interpreter so env (JAX flags) carries."""

    def __init__(self, log_dir: str = "") -> None:
        self.log_dir = log_dir

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        main = pod.spec.main_container()
        argv = list(main.command)
        if not argv:
            raise ValueError(f"pod {pod.metadata.name}: empty command")
        if argv[0] == "python":
            argv[0] = sys.executable
        full_env = {**os.environ, **env}
        # spawn timestamp: entrypoints attribute pod-spawn -> process-start
        # latency in their startup breakdown (launch-delay parity with the
        # reference's job_metrics.go:139-194, but per-phase)
        full_env.setdefault("KUBEDL_SPAWN_TS", repr(time.time()))
        stdout = None
        if self.log_dir:
            # namespaced: same-named pods in different namespaces must not
            # share (or leak) a log file
            ns_dir = os.path.join(self.log_dir, pod.metadata.namespace)
            os.makedirs(ns_dir, exist_ok=True)
            stdout = open(  # noqa: SIM115 - handle outlives this scope
                os.path.join(ns_dir, f"{pod.metadata.name}.log"), "ab"
            )
        proc = subprocess.Popen(
            argv,
            env=full_env,
            cwd=main.working_dir or None,
            stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None,
        )
        return _SubprocHandle(proc)


#: env key under which ThreadRuntime passes the cancellation Event object
#: (entrypoints poll `env.get(CANCEL_EVENT_KEY)` between steps; cooperative
#: — threads can't be killed)
CANCEL_EVENT_KEY = "_KUBEDL_CANCEL"


class _ThreadHandle(ProcHandle):
    def __init__(self, fn: Callable[[Dict[str, str]], object], env: Dict[str, str]) -> None:
        self._exit = 0
        self._done = threading.Event()
        self._cancel = threading.Event()
        env = dict(env)
        env[CANCEL_EVENT_KEY] = self._cancel  # type: ignore[assignment]

        def run() -> None:
            try:
                rc = fn(env)
                self._exit = int(rc) if isinstance(rc, int) else 0
            except SystemExit as e:
                # sys.exit(None)=0, sys.exit(int)=int, sys.exit(str)=failure
                if e.code is None:
                    self._exit = 0
                elif isinstance(e.code, int):
                    self._exit = e.code
                else:
                    log.error("entrypoint exited with message: %s", e.code)
                    self._exit = 1
            except Exception:
                log.error("entrypoint raised:\n%s", traceback.format_exc())
                self._exit = 1
            finally:
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> int:
        self._done.wait()
        return self._exit

    def kill(self) -> None:
        # threads are not killable; entrypoints poll env[CANCEL_EVENT_KEY]
        self._cancel.set()


class ThreadRuntime(ContainerRuntime):
    """Resolve `container.entrypoint` ("pkg.mod:fn") and call fn(env) in a
    thread. fn returns an int exit code (or None == 0)."""

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:
        main = pod.spec.main_container()
        if not main.entrypoint:
            raise ValueError(f"pod {pod.metadata.name}: no entrypoint for ThreadRuntime")
        mod_name, _, fn_name = main.entrypoint.partition(":")
        fn = getattr(importlib.import_module(mod_name), fn_name)
        return ThreadRuntime.spawn(fn, env)

    @staticmethod
    def spawn(fn: Callable, env: Dict[str, str]) -> ProcHandle:
        return _ThreadHandle(fn, env)


class FakeRuntime(ContainerRuntime):
    """Containers never actually run; tests drive phases via Kubelet-free
    store updates (see tests/helpers.py)."""

    def start(self, pod: Pod, env: Dict[str, str]) -> ProcHandle:  # pragma: no cover
        raise RuntimeError("FakeRuntime pods are driven manually by tests")


class Kubelet:
    """Realizes Pending pods and reports their lifecycle.

    One Kubelet instance typically serves ALL simulated nodes on this
    machine (locally it plays every TPU host); pass `nodes` to restrict it
    to a subset for multi-agent setups.
    """

    NAME = "kubelet"

    def __init__(
        self,
        store: ObjectStore,
        runtime: ContainerRuntime,
        nodes: Optional[set] = None,
        pod_ip: str = "127.0.0.1",
    ) -> None:
        self.store = store
        self.runtime = runtime
        self.nodes = nodes
        self.pod_ip = pod_ip
        self._lock = threading.Lock()
        self._running: Dict[str, ProcHandle] = {}
        #: pod uid each running handle belongs to — a same-name replacement
        #: pod (elastic resize deletes RUNNING pods and recreates them)
        #: must not be mistaken for the pod whose process is still alive
        self._running_uid: Dict[str, str] = {}
        #: (ns, pod, volume) -> (pod uid, ConfigMap resource version) last
        #: materialized; cleared when the pod is deleted
        self._materialized: Dict[tuple, tuple] = {}

    def setup(self, manager: ControllerManager) -> None:
        def mapper(event: str, obj: BaseObject, old):
            if obj.kind == "ConfigMap":
                # re-sync mounted ConfigMap volumes of running pods (real
                # kubelet semantics; e.g. MPI hostfile refresh on scale)
                keys = []
                for pod in self.store.list("Pod", obj.metadata.namespace):
                    if any(
                        v.config_map == obj.metadata.name
                        for v in pod.spec.volumes  # type: ignore[union-attr]
                    ):
                        keys.append((pod.metadata.namespace, pod.metadata.name))
                return keys
            return [(obj.metadata.namespace, obj.metadata.name)]

        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Pod", "ConfigMap"],
            mapper=mapper,
            workers=4,
        )

    # ------------------------------------------------------------------

    def _served(self, pod: Pod) -> bool:
        return self.nodes is None or pod.spec.node_name in self.nodes

    @staticmethod
    def _pod_env(pod: Pod) -> Dict[str, str]:
        env: Dict[str, str] = {}
        for c in pod.spec.init_containers + pod.spec.containers:
            for e in c.env:
                env[e.name] = e.value
        env["KUBEDL_POD_NAME"] = pod.metadata.name
        env["KUBEDL_POD_NAMESPACE"] = pod.metadata.namespace
        env["KUBEDL_NODE_NAME"] = pod.spec.node_name
        return env

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        key = f"{namespace}/{name}"
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            with self._lock:
                handle = self._running.pop(key, None)
                self._running_uid.pop(key, None)
                for sk in [k for k in self._materialized
                           if (k[0], k[1]) == (namespace, name)]:
                    del self._materialized[sk]
            if handle is not None:
                handle.kill()
            return None
        assert isinstance(pod, Pod)
        if not self._served(pod):
            return None
        if pod.is_terminal():
            # a pod marked terminal EXTERNALLY (node-lifecycle eviction)
            # may still have a live local process: kill it, or its
            # same-name replacement can never launch (the reap thread
            # frees the slot and relaunches). In the normal flow the
            # handle is popped before the terminal phase is stamped, so a
            # live handle here always means external termination.
            with self._lock:
                handle = self._running.get(key)
            if handle is not None and not isinstance(handle, _PlaceholderHandle):
                handle.kill()
            return None
        with self._lock:
            recorded_uid = self._running_uid.get(key)
            stale = (
                key in self._running
                and recorded_uid is not None
                and recorded_uid != pod.metadata.uid
            )
            handle = self._running.get(key)
            already_running = key in self._running and not stale
            if not already_running and not stale:
                if pod.status.phase != PodPhase.PENDING:
                    return None
                # reserve the slot before leaving the lock
                self._running[key] = _PlaceholderHandle()
                self._running_uid[key] = pod.metadata.uid
        if stale:
            # the live process belongs to a same-name pod that was deleted
            # and already replaced before its DELETED event was processed
            # (workqueue coalescing collapses DELETED+ADDED into one key).
            # Cancel it; the reap thread frees the slot and relaunches the
            # replacement.
            if handle is not None:
                handle.kill()
            return None
        if already_running:
            # keep mounted ConfigMap volumes fresh (outside self._lock —
            # materialization takes it internally)
            try:
                self._materialize_config_volumes(pod)
            except RuntimeError:
                pass  # ConfigMap deleted mid-run; keep last snapshot
            return None
        try:
            self._launch(pod, key)
        except Exception as e:
            log.error("launch %s failed: %s", key, e)
            with self._lock:
                self._running.pop(key, None)
                self._running_uid.pop(key, None)
            self._set_phase(pod, PodPhase.FAILED, reason=f"LaunchError: {e}", exit_code=1)
        return None

    def _launch(self, pod: Pod, key: str) -> None:
        env = self._pod_env(pod)
        self._materialize_config_volumes(pod)
        # init containers run to completion first (code-sync etc.)
        for init in pod.spec.init_containers:
            if init.command:
                rc = subprocess.call(init.command, env={**os.environ, **env})
                if rc != 0:
                    raise RuntimeError(f"init container {init.name} exited {rc}")
        handle = self.runtime.start(pod, env)
        with self._lock:
            self._running[key] = handle
        self._set_phase(pod, PodPhase.RUNNING)
        # an eviction landing DURING launch (init containers etc.) found
        # only the placeholder handle and could kill nothing; now that the
        # real handle exists, honor any terminal phase stamped meanwhile
        fresh = self.store.try_get("Pod", pod.metadata.name, pod.metadata.namespace)
        if (
            fresh is None
            or fresh.metadata.uid != pod.metadata.uid
            or fresh.is_terminal()
        ):
            handle.kill()

        def reap() -> None:
            code = handle.wait()
            with self._lock:
                self._running.pop(key, None)
                self._running_uid.pop(key, None)
            phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            self._set_phase(pod, phase, exit_code=code)
            # a same-name replacement pod may have been created while this
            # process was dying (gang restart) — give it a launch pass now
            # that the _running slot is free
            self.reconcile(pod.metadata.namespace, pod.metadata.name)

        threading.Thread(target=reap, daemon=True, name=f"reap-{key}").start()

    def _materialize_config_volumes(self, pod: Pod) -> None:
        """Write ConfigMap-backed volumes to their mount path (the kubelet
        side of the reference's ConfigMap volume mounts). Files are swapped
        in atomically (write-then-rename, the real kubelet's symlink-swap
        equivalent) so a running process never reads a torn hostfile, and
        unchanged ConfigMap versions are skipped."""
        from kubedl_tpu.core.objects import ConfigMap, config_mount_path

        for vol in pod.spec.volumes:
            if not vol.config_map:
                continue
            cm = self.store.try_get(
                "ConfigMap", vol.config_map, pod.metadata.namespace
            )
            if not isinstance(cm, ConfigMap):
                raise RuntimeError(f"ConfigMap {vol.config_map} not found")
            sync_key = (pod.metadata.namespace, pod.metadata.name, vol.name)
            stamp = (pod.metadata.uid, cm.metadata.resource_version)
            with self._lock:
                if self._materialized.get(sync_key) == stamp:
                    continue
            root = vol.mount_path or config_mount_path(
                pod.metadata.namespace, pod.metadata.name, vol.name
            )
            os.makedirs(root, exist_ok=True)
            for fname, content in cm.data.items():
                path = os.path.join(root, fname)
                # per-thread tmp name: concurrent materializers must never
                # interleave writes into the same tmp file
                tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
                with open(tmp, "w") as f:
                    f.write(content)
                if content.startswith("#!"):
                    os.chmod(tmp, 0o755)
                os.replace(tmp, path)
            with self._lock:
                self._materialized[sync_key] = stamp

    class _StalePod(Exception):
        pass

    def _set_phase(
        self,
        pod: Pod,
        phase: PodPhase,
        reason: str = "",
        exit_code: Optional[int] = None,
    ) -> None:
        def mutate(obj: Pod) -> None:  # type: ignore[type-arg]
            if obj.metadata.uid != pod.metadata.uid:
                # same-name pod recreated after a gang restart: the old
                # process's lifecycle must not stamp the fresh pod
                raise Kubelet._StalePod()
            if obj.is_terminal():
                # terminal is final: a pod already failed EXTERNALLY
                # (node-lifecycle eviction, exit 137 retryable) must not
                # be overwritten by the reaped kill signal (-15, which
                # would read as a permanent code-bug failure) or
                # resurrected to Running by an in-flight launch
                raise Kubelet._StalePod()
            obj.status.phase = phase
            obj.status.pod_ip = self.pod_ip
            obj.status.host_ip = self.pod_ip
            if reason:
                obj.status.reason = reason
            if phase == PodPhase.RUNNING and obj.status.start_time is None:
                obj.status.start_time = time.time()
            if phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                obj.status.finish_time = time.time()
                obj.status.container_statuses = [
                    ContainerStatus(exit_code=exit_code if exit_code is not None else 0)
                ]

        try:
            self.store.update_with_retry(
                "Pod", pod.metadata.name, pod.metadata.namespace, mutate
            )
        except (NotFound, Kubelet._StalePod):
            pass

    def shutdown(self) -> None:
        with self._lock:
            handles = list(self._running.values())
            self._running.clear()
            self._running_uid.clear()
        for h in handles:
            h.kill()


class _PlaceholderHandle(ProcHandle):
    def wait(self) -> int:  # pragma: no cover
        return 0

    def kill(self) -> None:
        pass

"""Model / ModelVersion objects.

Reference: apis/model/v1alpha1/{model,modelversion}_types.go — Model is the
logical lineage head (Status.LatestVersion, model_types.go:27-38);
ModelVersion is one artifact: a storage ref plus a target image repo, built
into an image tagged `repo:v<uid5>` (modelversion_controller.go:137-220).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from kubedl_tpu.core.objects import BaseObject


class ModelVersionPhase(str, enum.Enum):
    PENDING = "Pending"
    IMAGE_BUILDING = "ImageBuilding"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class Model(BaseObject):
    KIND = "Model"
    description: str = ""
    latest_version: str = ""  # Status.LatestVersion analogue
    versions: list = field(default_factory=list)


@dataclass
class ModelVersion(BaseObject):
    KIND = "ModelVersion"
    model_name: str = ""
    image_repo: str = ""
    #: Filesystem root holding the trained artifact (checkpoint dir). The
    #: reference's Storage union (NFS/LocalStorage/AWSEfs,
    #: modelversion_types.go:72-115) maps to a storage provider name + root.
    storage_root: str = ""
    storage_provider: str = "shared"
    #: Node that produced the artifact (LocalStorage nodeName pinning,
    #: job.go:341-382).
    node_name: str = ""
    created_by: str = ""  # "<Kind>/<job-name>"
    # -- lineage (recorded at registration, immutable afterwards) --
    #: Name of the Model's latest version at registration time — the
    #: version this one was trained from / supersedes ("" for the first).
    parent_version: str = ""
    #: Content fingerprint of the checkpoint artifact at registration
    #: (training.checkpoint.checkpoint_fingerprint over the latest step:
    #: manifest + shard digests). Serving and rollout tooling compare it
    #: against what they actually loaded, so a swapped or truncated
    #: artifact is detectable after the fact.
    checkpoint_fingerprint: str = ""
    # -- status --
    phase: ModelVersionPhase = ModelVersionPhase.PENDING
    image: str = ""  # final image ref "repo:v<uid5>"
    message: str = ""

    def image_tag(self) -> str:
        return f"v{self.metadata.uid[-5:]}"

"""Model-output storage providers: the union behind ModelVersion storage.

Reference: `controllers/model/storage/storage_provider.go:1-35` dispatches
NFS / LocalStorage / AWSEfs providers (modelversion_types.go:72-115), each
knowing how to (a) provision the PV/PVC for a ModelVersion and (b) mount
the output dir into training pods (AddModelVolumeToPodSpec,
pkg/job_controller/job.go:312-339).

TPU-native equivalents over the self-hosted substrate:

- **shared** (NFS/EFS-style): one root every node sees — the only layout
  that works for multi-host slice jobs, where every host writes its own
  checkpoint shards (`kubedl_tpu.training.checkpoint`) into the same tree.
  "nfs" and "efs" are registered aliases so specs written against the
  reference's union port over directly.
- **local**: node-pinned output (LocalStorage path+nodeName). The artifact
  only exists on the node that trained; the MV records `node_name`
  (pinned to the master/worker-0 node via GetNodeForModelOutput) and the
  builder validates it runs co-located before reading the path.

Providers are a registry (reference: GetStorageProvider) so a cloud bucket
provider can be plugged in without touching the engine or the builder.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional

from kubedl_tpu.core.objects import Volume


class StorageError(Exception):
    pass


class StorageProvider:
    """One storage flavor: how jobs write and builders read an artifact."""

    NAME = ""
    #: whether the artifact is visible from any node (shared filesystem)
    SHARED = True

    def provision(self, root: str) -> str:
        """Make the output root exist (the PV/PVC-provisioning analogue,
        modelversion_controller.go:239-325). Returns the resolved root."""
        Path(root).mkdir(parents=True, exist_ok=True)
        return root

    def add_model_volume(self, pod, root: str) -> None:
        """Mount the output dir into a training pod
        (AddModelVolumeToPodSpec, job.go:312-339)."""
        pod.spec.volumes.append(
            Volume(name="kubedl-model", host_path=root, mount_path=root)
        )

    def artifact_dir(self, mv, local_node: str = "") -> str:
        """Where the builder reads this ModelVersion's artifact. Raises
        StorageError when the artifact isn't reachable from here."""
        return mv.storage_root


class SharedDirProvider(StorageProvider):
    NAME = "shared"
    SHARED = True


class NodeLocalProvider(StorageProvider):
    NAME = "local"
    SHARED = False

    def artifact_dir(self, mv, local_node: str = "") -> str:
        if mv.node_name and local_node and mv.node_name != local_node:
            raise StorageError(
                f"node-local artifact lives on {mv.node_name!r}, "
                f"builder is on {local_node!r} — use a 'shared' storage "
                "provider for multi-host jobs"
            )
        return mv.storage_root


_PROVIDERS: Dict[str, StorageProvider] = {}


def register_storage_provider(provider: StorageProvider, *aliases: str) -> None:
    for name in (provider.NAME, *aliases):
        _PROVIDERS[name] = provider


def get_storage_provider(name: str) -> StorageProvider:
    """Reference: GetStorageProvider (storage_provider.go:1-35)."""
    try:
        return _PROVIDERS[name or "shared"]
    except KeyError:
        raise StorageError(
            f"unknown storage provider {name!r}; known: {sorted(_PROVIDERS)}"
        ) from None


register_storage_provider(SharedDirProvider(), "nfs", "efs")
register_storage_provider(NodeLocalProvider())

"""Model-output storage providers: the union behind ModelVersion storage.

Reference: `controllers/model/storage/storage_provider.go:1-35` dispatches
NFS / LocalStorage / AWSEfs providers (modelversion_types.go:72-115), each
knowing how to (a) provision the PV/PVC for a ModelVersion and (b) mount
the output dir into training pods (AddModelVolumeToPodSpec,
pkg/job_controller/job.go:312-339).

TPU-native equivalents over the self-hosted substrate:

- **shared** (NFS/EFS-style): one root every node sees — the only layout
  that works for multi-host slice jobs, where every host writes its own
  checkpoint shards (`kubedl_tpu.training.checkpoint`) into the same tree.
  "nfs" and "efs" are registered aliases so specs written against the
  reference's union port over directly.
- **local**: node-pinned output (LocalStorage path+nodeName). The artifact
  only exists on the node that trained; the MV records `node_name`
  (pinned to the master/worker-0 node via GetNodeForModelOutput) and the
  builder validates it runs co-located before reading the path.

Providers are a registry (reference: GetStorageProvider) so a cloud bucket
provider can be plugged in without touching the engine or the builder.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional

from kubedl_tpu.core.objects import Volume


class StorageError(Exception):
    pass


class StorageProvider:
    """One storage flavor: how jobs write and builders read an artifact."""

    NAME = ""
    #: whether the artifact is visible from any node (shared filesystem)
    SHARED = True

    def provision(self, root: str) -> str:
        """Make the output root exist (the PV/PVC-provisioning analogue,
        modelversion_controller.go:239-325). Returns the resolved root."""
        Path(root).mkdir(parents=True, exist_ok=True)
        return root

    def add_model_volume(self, pod, root: str) -> None:
        """Mount the output dir into a training pod
        (AddModelVolumeToPodSpec, job.go:312-339)."""
        pod.spec.volumes.append(
            Volume(name="kubedl-model", host_path=root, mount_path=root)
        )

    def artifact_dir(self, mv, local_node: str = "") -> str:
        """Where the builder reads this ModelVersion's artifact. Raises
        StorageError when the artifact isn't reachable from here."""
        return mv.storage_root

    def serving_root(self, mv) -> str:
        """What a predictor pod receives as KUBEDL_MODEL_PATH. Resolved
        through the provider (not raw `mv.storage_root`) so a mis-shaped
        root fails at pod creation instead of crash-looping the predictor.
        Base contract: the root is a directory readable in place."""
        return mv.storage_root


class SharedDirProvider(StorageProvider):
    NAME = "shared"
    SHARED = True


class NodeLocalProvider(StorageProvider):
    NAME = "local"
    SHARED = False

    def artifact_dir(self, mv, local_node: str = "") -> str:
        if mv.node_name and local_node and mv.node_name != local_node:
            raise StorageError(
                f"node-local artifact lives on {mv.node_name!r}, "
                f"builder is on {local_node!r} — use a 'shared' storage "
                "provider for multi-host jobs"
            )
        return mv.storage_root


class RemoteBlobProvider(StorageProvider):
    """Network-remote artifact storage over the blob server
    (`kubedl_tpu.remote`) — the AWS-EFS/object-store analogue
    (aws_efs_provider.go), and the first provider whose artifacts cross
    a real network boundary.

    ``storage_root`` is a SELF-DESCRIBING URL: ``http://host:port/blobs/
    <prefix>``. Training pods write into a local staging dir (returned by
    :meth:`provision` — the engine mounts and exports THAT as
    KUBEDL_MODEL_PATH); the builder's :meth:`artifact_dir` uploads fresh
    local staging to the remote prefix and otherwise downloads the prefix
    into a local cache — so the blob server is the source of truth and
    build/serve work from any host."""

    NAME = "http"
    SHARED = True

    def __init__(self, staging_root: str = "") -> None:
        import os
        import tempfile

        self.staging_root = staging_root or os.path.join(
            tempfile.gettempdir(), f"kubedl-remote-staging-{os.getuid()}"
        )

    def _staging_dir(self, remote_root: str) -> Path:
        import hashlib

        digest = hashlib.sha256(remote_root.encode()).hexdigest()[:16]
        return Path(self.staging_root) / digest

    def provision(self, root: str) -> str:
        from kubedl_tpu.remote.client import is_remote_root

        if not is_remote_root(root):
            raise StorageError(
                f"http storage_root must be http(s)://…/blobs/<prefix>, got {root!r}"
            )
        d = self._staging_dir(root)
        d.mkdir(parents=True, exist_ok=True)
        return str(d)

    def add_model_volume(self, pod, root: str) -> None:
        # root here is the resolved local staging dir
        super().add_model_volume(pod, root)

    def artifact_dir(self, mv, local_node: str = "") -> str:
        from kubedl_tpu.remote.client import download_tree, upload_tree

        remote_root = mv.storage_root
        staging = self._staging_dir(remote_root)
        if staging.is_dir() and any(staging.rglob("*")):
            # fresh local training output: publish it, then build from it
            upload_tree(str(staging), remote_root)
            return str(staging)
        cache = Path(self.staging_root) / "fetch" / staging.name
        cache.mkdir(parents=True, exist_ok=True)
        n = download_tree(remote_root, str(cache))
        if n == 0:
            raise StorageError(f"no artifact blobs under {remote_root}")
        return str(cache)

    def serving_root(self, mv) -> str:
        from kubedl_tpu.remote.client import is_remote_root

        if not is_remote_root(mv.storage_root):
            raise StorageError(
                f"http ModelVersion {mv.metadata.name!r} has a non-remote "
                f"storage_root {mv.storage_root!r} — predictors would treat "
                "the URL as a local directory"
            )
        # the URL stays a URL: serve_main mirrors the blob prefix into a
        # local cache on startup (predictors may run on any host)
        return mv.storage_root


_PROVIDERS: Dict[str, StorageProvider] = {}


def register_storage_provider(provider: StorageProvider, *aliases: str) -> None:
    for name in (provider.NAME, *aliases):
        _PROVIDERS[name] = provider


def list_storage_providers() -> Dict[str, StorageProvider]:
    """Registered providers incl. aliases (console storage/list route)."""
    return dict(_PROVIDERS)


def get_storage_provider(name: str) -> StorageProvider:
    """Reference: GetStorageProvider (storage_provider.go:1-35)."""
    try:
        return _PROVIDERS[name or "shared"]
    except KeyError:
        raise StorageError(
            f"unknown storage provider {name!r}; known: {sorted(_PROVIDERS)}"
        ) from None


register_storage_provider(SharedDirProvider(), "nfs", "efs")
register_storage_provider(NodeLocalProvider())
register_storage_provider(RemoteBlobProvider())

"""Llama-3-family decoder, TPU-first.

Design choices (and why they're TPU-idiomatic, not a torch translation):

- **Functional**: params are a plain pytree; the forward is a pure function
  under `jit` — no modules, no state.
- **Scanned layers**: per-layer weights are stacked on a leading axis and the
  decoder runs as one `lax.scan` over layers. XLA compiles ONE layer body
  (compile time O(1) in depth) and the weight layout is uniform, which is
  what makes fsdp/tp shardings trivially specifiable for all layers at once.
- **Remat**: the scan body is `jax.checkpoint`ed so activations are
  recomputed in backward — HBM is the bottleneck, MXU flops are cheap.
- **bf16 params/activations, fp32 softmax + loss** — MXU-native precision.
- **GQA** (n_kv_heads < n_heads) exactly as Llama-3 uses it.
- **Sharding by rules**: :func:`param_pspecs` returns a PartitionSpec tree
  (megatron tensor split + fsdp) consumed by `pjit`/NamedSharding; XLA
  inserts the collectives.

North-star config (BASELINE.md #4): Llama-3-8B on a gang-scheduled v5e-32.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from kubedl_tpu.models import paged_attention as blocked_attention


def remat_policy_for(name: str):
    """Map a config string to a jax.checkpoint policy (None = save
    nothing, i.e. full recompute)."""
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "nothing":
        return jax.checkpoint_policies.nothing_saveable
    if name == "attn":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if name == "dots_attn":
        # matmul outputs AND the attention output: backward recomputes
        # neither the dots nor the flash forward kernel
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
    if name == "flash":
        # ONLY the flash kernel's residuals: backward re-runs the
        # projection/ffn dots (cheap, MXU-bound) but never the attention
        # kernel; saves ~8GB of stacked dot outputs vs "dots" at b8 —
        # for memory-capacity-bound shapes
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        )
    if name == "flash_rope":
        # flash residuals + the kernel's INPUTS (post-rope q/k and v):
        # backward then reconstructs nothing on the attention path —
        # no norm/projection/rope re-run to feed the bwd kernel. The
        # round-4 full-step winner at bench shapes (582ms vs 601 flash,
        # 605 dots, 643 r3-shipped) for ~4GB of saved activations.
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "rope_out", "attn_v"
        )
    if name == "attn_flash":
        # attention output + kernel residuals, dots recomputed
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "flash_out", "flash_lse"
        )
    if name == "dots_flash":
        # dots PLUS the flash kernel's own residuals (out + lse, tagged in
        # ops/flash_attention._flash_fwd). "dots_attn" was not enough: it
        # saves the post-transpose attention output but the custom-vjp
        # backward also needs lse, which no policy could name — so the
        # forward kernel still re-ran under remat (~43ms/step profiled on
        # the bench model). Costs lse (f32 [B,H,S]) + out per layer.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"
            ),
        )
    raise ValueError(f"unknown remat_policy {name!r}")

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    #: remat the scan body (trade flops for HBM)
    remat: bool = True
    #: what the remat saves: "dots_flash" (matmul outputs AND the flash
    #: kernel's out/lse residuals — the default, because without the
    #: residuals the backward must re-run the forward attention kernel
    #: every layer), "flash_rope" (kernel residuals + its post-rope
    #: q/k + v inputs: backward reconstructs nothing on the attention
    #: path — the measured bench winner), "flash" (only the kernel
    #: residuals: re-run the cheap dots, ~8GB less saved at bench
    #: shapes), "dots", "dots_attn", "nothing", "attn", "attn_flash"
    remat_policy: str = "dots_flash"
    #: compute the LM loss over sequence chunks of this many positions
    #: (0 = whole sequence at once). The full [B, S, V] fp32 logits are
    #: the single biggest activation (b8 x s2048 x v32k = 2.1 GB before
    #: softmax temporaries); chunking + remat caps loss memory at
    #: [B, chunk, V] and recomputes each chunk's logits in backward.
    loss_chunk: int = 0
    #: tie lm_head to the embedding table (smaller models do)
    tie_embeddings: bool = False
    #: fuse the QKV (and gate/up) projections into single matmuls at use
    #: (concat-at-use: param tree and checkpoints unchanged). Wrong for
    #: tensor-parallel meshes (the trainer force-disables it there); off
    #: for quantized weights automatically.
    fuse_projections: bool = False
    # -- Gemma-family knobs (same decoder skeleton, different details) -----
    #: MLP activation: "silu" (Llama SwiGLU) or "gelu" (Gemma GeGLU)
    act: str = "silu"
    #: RMSNorm uses (1 + weight) (Gemma)
    norm_plus_one: bool = False
    #: scale embeddings by sqrt(dim) at input (Gemma)
    embed_scale: bool = False
    #: fixed head dim decoupled from dim/n_heads (Gemma: 256); 0 = dim/heads
    head_dim_fixed: int = 0
    #: zero-init the residual OUTPUT projections (wo, w_down) of layers
    #: with index >= this value (0 = off). ReZero/GPT-2-style depth init:
    #: the deep layers start as exact identity residuals, so an early-exit
    #: draft sliced at this depth (serving.speculative.ModelDraft
    #: .from_target) agrees with the full target at init — the tiny-deep
    #: draft/target pairing the speculative bench measures honestly.
    zero_init_deep_from: int = 0

    @property
    def head_dim(self) -> int:
        return self.head_dim_fixed or self.dim // self.n_heads

    def num_params(self) -> int:
        hd = self.head_dim
        per_layer = (
            self.dim * (self.n_heads * hd)  # wq
            + 2 * self.dim * (self.n_kv_heads * hd)  # wk, wv
            + (self.n_heads * hd) * self.dim  # wo
            + 3 * self.dim * self.ffn_dim  # gate, up, down
            + 2 * self.dim  # norms
        )
        embed = self.vocab_size * self.dim
        head = 0 if self.tie_embeddings else self.dim * self.vocab_size
        return embed + self.n_layers * per_layer + head + self.dim

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ~= 6*N)."""
        return 6.0 * self.num_params()


# ---- presets ---------------------------------------------------------------

LLAMA3_8B = LlamaConfig()
LLAMA3_1B = LlamaConfig(
    vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
    ffn_dim=8192, tie_embeddings=True,
)
#: bench-scale model that fits one v5e chip (16 GiB) with room for a real
#: batch. loss_chunk keeps the fp32 logits out of HBM (2.1 GB at b8 s2048
#: — measured equal-speed and strictly more headroom, docs/performance.md)
BENCH_350M = LlamaConfig(
    vocab_size=32768, dim=1024, n_layers=24, n_heads=16, n_kv_heads=8,
    ffn_dim=4096, max_seq=2048, loss_chunk=0,
    # "flash_rope" saves the kernel residuals AND its inputs (post-rope
    # q/k, v): backward reconstructs nothing on the attention path while
    # the ~8GB of stacked dot outputs "dots" would have saved stay free —
    # which is also what lets loss_chunk=0 (unchunked logits) win.
    # Full-step sweep on v5e b8 s2048: flash_rope 582ms vs flash 597-601
    # vs dots 605-614 vs dots_flash 639-647 vs 643 shipped in r3.
    remat_policy="flash_rope",
)
TINY = LlamaConfig(
    vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    max_seq=128, dtype=jnp.float32, remat=False,
)
#: Gemma-2B (BASELINE.md target 5: inference on v5e): MQA, head_dim 256,
#: GeGLU, (1+w) norms, sqrt(dim)-scaled tied embeddings.
GEMMA_2B = LlamaConfig(
    vocab_size=256000, dim=2048, n_layers=18, n_heads=8, n_kv_heads=1,
    ffn_dim=16384, max_seq=8192, rope_theta=10000.0, tie_embeddings=True,
    act="gelu", norm_plus_one=True, embed_scale=True, head_dim_fixed=256,
)
#: tiny's 4-layer sibling for the draft/target MODEL_ZOO pairing: layers
#: >= 2 start as identity residuals (zero_init_deep_from), so the 2-layer
#: early-exit draft carved out of its own weights proposes what the full
#: target would emit — a CPU-scale proxy for a trained draft/target pair.
TINY_DEEP = dataclasses.replace(TINY, n_layers=4, zero_init_deep_from=2)
TINY_GEMMA = LlamaConfig(
    vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=1, ffn_dim=128,
    max_seq=128, dtype=jnp.float32, remat=False, tie_embeddings=True,
    act="gelu", norm_plus_one=True, embed_scale=True, head_dim_fixed=32,
)


def preset(name: str) -> LlamaConfig:
    table = {
        "llama3-8b": LLAMA3_8B,
        "llama3-1b": LLAMA3_1B,
        "bench-350m": BENCH_350M,
        "gemma-2b": GEMMA_2B,
        "tiny-gemma": TINY_GEMMA,
        "tiny": TINY,
        "tiny-deep": TINY_DEEP,
    }
    return table[name]


# ---- init ------------------------------------------------------------------

def llama_init(key: jax.Array, cfg: LlamaConfig) -> Params:
    hd = cfg.head_dim
    k = iter(jax.random.split(key, 12))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(
            cfg.dtype
        )

    L, D, F, V = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones
    params: Params = {
        "embed": dense(next(k), (V, D), D),
        "layers": {
            "attn_norm": norm_init((L, D), cfg.dtype),
            "wq": dense(next(k), (L, D, cfg.n_heads * hd), D),
            "wk": dense(next(k), (L, D, cfg.n_kv_heads * hd), D),
            "wv": dense(next(k), (L, D, cfg.n_kv_heads * hd), D),
            "wo": dense(next(k), (L, cfg.n_heads * hd, D), cfg.n_heads * hd),
            "mlp_norm": norm_init((L, D), cfg.dtype),
            "w_gate": dense(next(k), (L, D, F), D),
            "w_up": dense(next(k), (L, D, F), D),
            "w_down": dense(next(k), (L, F, D), F),
        },
        "final_norm": norm_init((D,), cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(k), (D, V), D)
    if cfg.zero_init_deep_from:
        deep = jnp.arange(L) >= cfg.zero_init_deep_from
        lyr = params["layers"]
        for name in ("wo", "w_down"):
            lyr[name] = jnp.where(
                deep[:, None, None], 0.0, lyr[name]
            ).astype(cfg.dtype)
    return params


def param_pspecs(cfg: LlamaConfig) -> Params:
    """Megatron tensor split + fsdp, stacked-layer aware.

    Column-parallel (output dim on "tensor"): wq/wk/wv, w_gate/w_up.
    Row-parallel (input dim on "tensor"): wo, w_down. fsdp shards the other
    matmul dim. Embedding: vocab on tensor, dim on fsdp.
    """
    specs: Params = {
        "embed": P("tensor", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tensor"),
            "wk": P(None, "fsdp", "tensor"),
            "wv": P(None, "fsdp", "tensor"),
            "wo": P(None, "tensor", "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tensor"),
            "w_up": P(None, "fsdp", "tensor"),
            "w_down": P(None, "tensor", "fsdp"),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tensor")
    return specs


# ---- weight-only int8 (serving) --------------------------------------------

#: weights quantized for serving (norms stay float: tiny and sensitive)
_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quant_leaf(w: jax.Array, axis: int = -2) -> Dict[str, jax.Array]:
    """Symmetric int8 with the scale reduced over ``axis``. For matmul
    weights that is the CONTRACTION axis (-2): `deq(w)` folds into the
    consuming matmul as a per-output-column scale and XLA fuses
    convert+scale into the dot — HBM reads the int8 bytes, half the bf16
    traffic. The embedding table instead scales PER ROW (axis=-1): one
    outlier token's norm must not inflate the int8 step for every token,
    and its consumers (a row gather; a dim-contraction when tied as the
    lm_head) factor a per-row scale just as well."""
    a = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(a), axis=axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(a / s), -127, 127).astype(jnp.int8)
    return {"q8": q, "s8": s.astype(jnp.bfloat16)}


def quantize_params(params: Params, cfg: LlamaConfig) -> Params:
    """Weight-only int8 for the decode/prefill paths (serving: decode is
    HBM-bandwidth-bound, and weights dominate the bytes — int8 halves
    them). Embedding/lm_head and all layer matmuls quantize; norms stay
    in their float dtype. Training never sees quantized params."""
    out: Params = dict(params)
    out["embed"] = _quant_leaf(params["embed"], axis=-1)  # per-token rows
    if "lm_head" in params:
        out["lm_head"] = _quant_leaf(params["lm_head"])
    layers = dict(params["layers"])
    for key in _QUANT_KEYS:
        layers[key] = _quant_leaf(layers[key])
    out["layers"] = layers
    return out


def deq(w) -> jax.Array:
    """Dequantize an int8 weight leaf ({"q8","s8"} -> bf16); identity for
    raw arrays, so every consumer works with either representation."""
    if isinstance(w, dict) and "q8" in w:
        return w["q8"].astype(w["s8"].dtype) * w["s8"]
    return w


def _wdim(w, axis: int) -> int:
    return (w["q8"] if isinstance(w, dict) and "q8" in w else w).shape[axis]


# ---- building blocks -------------------------------------------------------

def rmsnorm(
    x: jax.Array, weight: jax.Array, eps: float, plus_one: bool = False
) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if plus_one:  # Gemma convention: weight is a residual around 1
        w = w + 1.0
    return (x * w).astype(dtype)


def _act(cfg: LlamaConfig):
    return jax.nn.silu if cfg.act == "silu" else partial(
        jax.nn.gelu, approximate=True
    )


def rope_table(
    head_dim: int, theta: float, seq_len: int, offset: int = 0
) -> Tuple[jax.Array, jax.Array]:
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    ang = jnp.outer(t, inv)  # [S, hd/2]
    return jnp.cos(ang), jnp.sin(ang)


def rope_freqs(cfg: LlamaConfig, seq_len: int, offset: int = 0) -> Tuple[jax.Array, jax.Array]:
    return rope_table(cfg.head_dim, cfg.rope_theta, seq_len, offset)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, hd]; rotate pairs (even, odd)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    # interleaved convention folded to split-halves (equivalent under a
    # fixed permutation of head dims; consistent between q and k)
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def attention(
    q: jax.Array,  # [B, S, H, hd]
    k: jax.Array,  # [B, S, KV, hd]
    v: jax.Array,  # [B, S, KV, hd]
    causal: bool = True,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference attention: fp32 softmax, GQA via head grouping. The pallas
    flash kernel (kubedl_tpu.ops.flash_attention) is the fused drop-in; this
    is the numerics oracle and CPU fallback."""
    B, S, H, hd = q.shape
    KV = k.shape[2]
    group = H // KV
    q = q.reshape(B, S, KV, group, hd)
    scores = jnp.einsum("bskgh,btkh->bkgst", q, k).astype(jnp.float32)
    scores = scores / math.sqrt(hd)
    if causal:
        idx = jnp.arange(S)
        cmask = idx[:, None] >= idx[None, :]  # [S, T]
        scores = jnp.where(cmask[None, None, None], scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, v)
    return out.reshape(B, S, H, hd)


def _block(
    x: jax.Array, lp: Params, cfg: LlamaConfig, cos, sin, attn_fn=None,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """One decoder block. Head/ffn counts are inferred from the WEIGHT
    shapes, not the config, so the same body runs tensor-parallel inside a
    shard_map (megatron split: wq/wk/wv/w_gate/w_up column-parallel, wo/
    w_down row-parallel with a psum over ``tp_axis``) — this is what lets
    pipe x tensor compose in the GPipe stage body."""
    B, S, D = x.shape
    hd = cfg.head_dim
    po = cfg.norm_plus_one
    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, po)
    n_heads = _wdim(lp["wq"], -1) // hd  # local (tensor-split) head count
    n_kv = _wdim(lp["wk"], -1) // hd
    # fuse_projections: one [D, (H+2KV)*hd] matmul instead of three.
    # Concat-at-use keeps the param tree (and checkpoints) unchanged;
    # autodiff slices the fused grad back apart. Only for unsharded/
    # data-parallel meshes (the trainer force-disables it under tensor
    # parallelism: concat along the column-split dim would make GSPMD
    # all-gather the shards) and unquantized weights. Measured on v5e
    # bench shapes: -19ms/step in an isolated forward but +16ms on the
    # FULL remat'd train step (the concats rematerialize in backward and
    # the extra weight-bytes traffic beats the MXU gain) — hence default
    # OFF; the knob exists for inference-style forward-heavy workloads.
    fuse = cfg.fuse_projections and not isinstance(lp["wq"], dict)
    if fuse:
        qkv = h @ jnp.concatenate(
            [lp["wq"], lp["wk"], lp["wv"]], axis=1
        )
        dq_w, dkv_w = n_heads * hd, n_kv * hd
        q = qkv[..., :dq_w].reshape(B, S, n_heads, hd)
        k = qkv[..., dq_w:dq_w + dkv_w].reshape(B, S, n_kv, hd)
        v = qkv[..., dq_w + dkv_w:].reshape(B, S, n_kv, hd)
    else:
        q = (h @ deq(lp["wq"])).reshape(B, S, n_heads, hd)
        k = (h @ deq(lp["wk"])).reshape(B, S, n_kv, hd)
        v = (h @ deq(lp["wv"])).reshape(B, S, n_kv, hd)
    # named so "flash_rope" can SAVE the attention kernel's exact inputs:
    # without these, the backward scan re-runs norm + the q/k/v
    # projections + rope just to reconstruct the custom-vjp residuals
    # (the kernel's q/k/v) — measured 601 -> 582 ms/step on the bench
    # model for ~3.2GB of saved activations
    if getattr(attn_fn, "fused_rope", False):
        # rotary fused into the pallas kernel (rotation on VMEM tiles;
        # backward emits pre-rope grads): q/k go in UN-rotated, and the
        # saved kernel inputs are the raw projection outputs — the
        # XLA-side rope (rotate + concat + relayouts over [B,S,H,hd],
        # again in backward) profiled at ~37ms/step on the bench model
        q = checkpoint_name(q, "rope_out")
        k = checkpoint_name(k, "rope_out")
        v = checkpoint_name(v, "attn_v")
        attn = attn_fn(q, k, v, rope_cos=cos, rope_sin=sin)
    else:
        q = checkpoint_name(apply_rope(q, cos, sin), "rope_out")
        k = checkpoint_name(apply_rope(k, cos, sin), "rope_out")
        v = checkpoint_name(v, "attn_v")
        attn = (attn_fn or attention)(q, k, v)
    attn = attn.reshape(B, S, n_heads * hd)
    # named for remat_policy="attn": save the attention output so backward
    # never re-runs the (flash) attention kernel, recompute everything else
    attn = checkpoint_name(attn, "attn_out")
    attn_out = attn @ deq(lp["wo"])  # row-parallel: partial sums under tp
    if tp_axis:
        attn_out = lax.psum(attn_out, tp_axis)
    x = x + attn_out
    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, po)
    if fuse:
        F = _wdim(lp["w_gate"], -1)
        g_u = h @ jnp.concatenate([lp["w_gate"], lp["w_up"]], axis=1)
        gate = _act(cfg)(g_u[..., :F].astype(jnp.float32)).astype(h.dtype)
        mlp = (gate * g_u[..., F:]) @ deq(lp["w_down"])
    else:
        gate = _act(cfg)(
            (h @ deq(lp["w_gate"])).astype(jnp.float32)
        ).astype(h.dtype)
        mlp = (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
    if tp_axis:
        mlp = lax.psum(mlp, tp_axis)
    return x + mlp


def llama_hidden(
    params: Params, tokens: jax.Array, cfg: LlamaConfig, attn_fn=None
) -> jax.Array:
    """tokens [B, S] int32 -> final-norm hidden states [B, S, D]."""
    B, S = tokens.shape
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)
    if cfg.embed_scale:  # Gemma scales inputs by sqrt(dim)
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, S)

    def body(carry, lp):
        return _block(carry, lp, cfg, cos, sin, attn_fn), None

    if cfg.remat:
        body = jax.checkpoint(body, policy=remat_policy_for(cfg.remat_policy))
    x, _ = lax.scan(body, x, params["layers"])
    return rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)


def gather_embed(embed, tokens: jax.Array) -> jax.Array:
    """Token embedding lookup; int8 embeds gather q8 rows + their per-row
    scales (embed quantizes per row — see `_quant_leaf`)."""
    if isinstance(embed, dict) and "q8" in embed:
        return embed["q8"][tokens].astype(embed["s8"].dtype) * embed["s8"][tokens]
    return embed[tokens]


def lm_head_of(params: Params, cfg: LlamaConfig) -> jax.Array:
    return deq(params["embed"]).T if cfg.tie_embeddings else deq(params["lm_head"])


def llama_forward(
    params: Params, tokens: jax.Array, cfg: LlamaConfig, attn_fn=None
) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V] (fp32).

    ``attn_fn`` swaps the attention implementation: dense oracle (default),
    pallas flash kernel, or sequence-parallel ring/ulysses attention built
    by `kubedl_tpu.parallel.ring.make_context_attention` — RoPE is applied
    here with global positions, so sequence-sharded attention composes
    without position bookkeeping.
    """
    x = llama_hidden(params, tokens, cfg, attn_fn)
    return (x @ lm_head_of(params, cfg)).astype(jnp.float32)


def llama_loss(
    params: Params, tokens: jax.Array, cfg: LlamaConfig, attn_fn=None
) -> jax.Array:
    """Next-token cross entropy over tokens[:, 1:].

    The forward runs on the FULL sequence (last position's logits unused)
    so the seq dim keeps its length — slicing to S-1 before the forward
    would break even sequence sharding under context parallelism.

    With ``cfg.loss_chunk`` set, the head matmul + softmax run chunk by
    chunk so the [B, S, V] fp32 logits never materialize.
    """
    if cfg.loss_chunk:
        x = llama_hidden(params, tokens, cfg, attn_fn)
        return chunked_next_token_nll(
            x, lm_head_of(params, cfg), tokens, cfg.loss_chunk
        )
    logits = llama_forward(params, tokens, cfg, attn_fn)
    return next_token_nll(logits, tokens)


def next_token_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token NLL: logits [B, S, V] (full sequence) scored against
    tokens shifted by one. Shared by every LM family."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def chunked_next_token_nll(
    x: jax.Array,  # [B, S, D] final hidden states
    head: jax.Array,  # [D, V]
    tokens: jax.Array,  # [B, S]
    chunk: int,
) -> jax.Array:
    """Same mean NLL as :func:`next_token_nll`, computed over sequence
    chunks so the fp32 [B, S, V] logits (+ softmax temporaries) never
    exist at once — peak loss memory is [B, chunk, V], and the chunk body
    is rematerialized so backward recomputes each chunk's logits instead
    of saving softmax residuals for every chunk (which would be the full
    array again)."""
    B, S = tokens.shape
    n_pos = S - 1  # scored positions
    n_chunks = -(-n_pos // chunk)
    pad = n_chunks * chunk - n_pos
    xs = jnp.pad(x[:, :-1], ((0, 0), (0, pad), (0, 0)))
    targets = jnp.pad(tokens[:, 1:], ((0, 0), (0, pad)))
    xs = xs.reshape(B, n_chunks, chunk, -1).transpose(1, 0, 2, 3)
    targets = targets.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
    valid = (jnp.arange(n_chunks * chunk) < n_pos).reshape(n_chunks, chunk)

    def body(total, inp):
        xc, tc, vc = inp  # [B, chunk, D], [B, chunk], [chunk]
        logits = (xc @ head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        return total + (nll * vc[None, :]).sum(), None

    body = jax.checkpoint(
        body, policy=jax.checkpoint_policies.nothing_saveable
    )
    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, targets, valid))
    return total / (B * n_pos)


# ---- sharded serving -------------------------------------------------------

def serving_shardings(params: Params, cfg: LlamaConfig, mesh) -> Params:
    """NamedSharding tree for (possibly int8-quantized) params on a
    serving mesh — BASELINE target 5 runs Gemma-2B on a v5e-4, so the
    decode/prefill weights shard over a "tensor" axis (megatron split,
    `param_pspecs`) and XLA inserts the collectives. Quantized leaves
    shard q8 like the weight; scale dims of size 1 (the reduced axis)
    stay unsharded."""
    from jax.sharding import NamedSharding

    pspecs = param_pspecs(cfg)  # omits lm_head for tied configs already
    names = set(mesh.axis_names)

    def prune(spec: P) -> P:
        return P(*(a if a in names else None for a in spec))

    def leaf_sharding(leaf, spec: P):
        spec = prune(spec)
        if isinstance(leaf, dict) and "q8" in leaf:
            s_spec = P(*(
                a if leaf["s8"].shape[i] != 1 else None
                for i, a in enumerate(spec)
            ))
            return {
                "q8": NamedSharding(mesh, spec),
                "s8": NamedSharding(mesh, s_spec),
            }
        return NamedSharding(mesh, spec)

    out: Params = {
        "embed": leaf_sharding(params["embed"], pspecs["embed"]),
        "final_norm": NamedSharding(mesh, prune(pspecs["final_norm"])),
        "layers": {
            k: leaf_sharding(params["layers"][k], pspecs["layers"][k])
            for k in params["layers"]
        },
    }
    if "lm_head" in params:
        out["lm_head"] = leaf_sharding(params["lm_head"], pspecs["lm_head"])
    return out


def shard_serving_params(params: Params, cfg: LlamaConfig, mesh) -> Params:
    """device_put the params onto their serving shardings (one transfer at
    engine start; decode then runs fully sharded). The shardings tree
    mirrors the params structure, so a single pytree device_put covers
    raw and quantized leaves alike."""
    return jax.device_put(params, serving_shardings(params, cfg, mesh))


# ---- pipeline hooks --------------------------------------------------------

def pipeline_hooks(cfg: LlamaConfig):
    """Family adapter for the GPipe pipeline (trainer._make_pipeline_loss):
    embed / rope / stage body / head+loss, with optional tensor parallelism
    INSIDE the stage (tp_axis psums in `_block`)."""
    from kubedl_tpu.parallel.pipeline import PipelineHooks

    def embed(params, tokens):
        x = params["embed"][tokens].astype(cfg.dtype)
        if cfg.embed_scale:
            x = x * math.sqrt(cfg.dim)
        return x

    def make_stage(attn_fn, cos, sin, tp_axis=None, ep_axis=None):
        def stage_fn(layer_params, x):
            def body(carry, lp):
                return _block(carry, lp, cfg, cos, sin, attn_fn, tp_axis), None

            if cfg.remat:
                body = jax.checkpoint(
                    body, policy=remat_policy_for(cfg.remat_policy)
                )
            x, _ = lax.scan(body, x, layer_params)
            return x, jnp.zeros((), jnp.float32)

        return stage_fn

    def head_loss(params, h, tokens, aux_mean):
        h = rmsnorm(h, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
        logits = (h @ lm_head_of(params, cfg)).astype(jnp.float32)
        return next_token_nll(logits, tokens)

    return PipelineHooks(
        embed=embed,
        rope=lambda S: rope_freqs(cfg, S),
        make_stage=make_stage,
        head_loss=head_loss,
        n_layers=cfg.n_layers,
    )


# ---- KV-cache decode (serving path) ---------------------------------------

def init_cache(cfg: LlamaConfig, batch: int, max_seq: int) -> Params:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def init_batched_cache(cfg: LlamaConfig, batch: int, max_seq: int) -> Params:
    """Continuous-batching cache: PER-SLOT positions so every batch row can
    be a different sequence at a different decode depth (the serving
    engine's slot model). Shapes are static — one compile serves any mix
    of in-flight requests."""
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
    }


def _row_update(cache_layer: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Write ``new`` [B, S, KV, hd] into ``cache_layer`` [B, T, KV, hd] at
    per-row offset ``pos`` [B] via vmapped `dynamic_update_slice` — O(S)
    HBM traffic per row instead of the one-hot full-cache rewrite the
    round-2 decode paid (O(T) per generated token, VERDICT.md weak #2)."""
    return jax.vmap(
        lambda c, n, p: lax.dynamic_update_slice_in_dim(c, n, p, axis=0)
    )(cache_layer, new, pos)


def decode_step_batched(
    params: Params, cache: Params, tokens: jax.Array, cfg: LlamaConfig
) -> Tuple[jax.Array, Params]:
    """One decode step with per-row positions: tokens [B, 1] ->
    (logits [B, V], updated cache). Each row attends to its own prefix
    (per-row causal mask) and writes its KV at its own position with a
    per-row `dynamic_update_slice` (in-place under donation). The layer
    stack runs as one `lax.scan` so XLA compiles ONE layer body — compile
    time O(1) in depth, matching the training forward. Static shapes: the
    step compiles once and serves any interleaving of requests
    (continuous batching)."""
    B = tokens.shape[0]
    hd = cfg.head_dim
    pos = cache["pos"]  # [B]
    max_s = cache["k"].shape[2]
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)  # [B, 1, D]
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, max_s)
    cos_t = cos[pos][:, None, None, :]  # [B,1,1,hd/2] per-row rotation
    sin_t = sin[pos][:, None, None, :]
    # per-row validity: row b sees positions 0..pos[b]
    valid = (jnp.arange(max_s)[None, :] <= pos[:, None])  # [B, T]
    mask = valid[:, None, None, None, :]  # broadcast over (KV, G, S=1)

    def rot(t):  # apply_rope with per-row tables
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_t - t2 * sin_t, t1 * sin_t + t2 * cos_t], axis=-1
        ).astype(t.dtype)

    def body(x, inp):
        lp, ck, cv = inp  # ck/cv: [B, T, KV, hd] this layer's cache
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = rot((h @ deq(lp["wq"])).reshape(B, 1, cfg.n_heads, hd))
        k = rot((h @ deq(lp["wk"])).reshape(B, 1, cfg.n_kv_heads, hd))
        v = (h @ deq(lp["wv"])).reshape(B, 1, cfg.n_kv_heads, hd)
        ck = _row_update(ck, k, pos)
        cv = _row_update(cv, v, pos)
        attn = attention(q, ck, cv, causal=False, mask=mask)
        x = x + attn.reshape(B, 1, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    logits = (x[:, 0] @ lm_head_of(params, cfg)).astype(jnp.float32)
    cache = {
        "k": new_k,
        "v": new_v,
        "pos": jnp.minimum(pos + 1, max_s - 1),
    }
    return logits, cache


def decode_segment(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, 1] first input token per row
    temps: jax.Array,  # [B] sampling temperature; <= 0 = greedy
    key: jax.Array,  # PRNG key for the whole segment
    cfg: LlamaConfig,
    n_steps: int,
    greedy: bool = False,  # static: all rows argmax — skips the gumbel
) -> Tuple[jax.Array, jax.Array, jax.Array, Params]:
    """``n_steps`` decode steps with ON-DEVICE sampling, one dispatch.

    The serving engine's per-token tick paid a full-logits device_get
    ([B, V] — 8MB for Gemma-2B at B=8) plus a host round trip EVERY
    token; over the tunnel that dwarfed the compute. Here the
    sample->feed chain runs inside one jitted `lax.scan` (gumbel-max ==
    categorical; temperature <= 0 degrades to pure argmax) and only the
    sampled ids ([B, n_steps] int32) cross to the host, once per
    segment. Completion in the engine is token-COUNT based, so the
    scheduler can size segments to the earliest completion without
    seeing any token value. One compile per distinct n_steps (the engine
    buckets to powers of two).

    Returns ``(toks [B, n_steps], last [B, 1], next_key, cache)``:
    ``last`` and ``next_key`` stay on device, so the engine chains
    straight into the next segment with zero host->device transfers and
    no extra split dispatch while the slot set is unchanged. ``toks`` is
    shaped for DEFERRED harvest: the engine dispatches segment N+1
    against ``last`` before calling `device_get` on segment N's ``toks``,
    so the copy-out (and all host bookkeeping behind it) overlaps the
    next segment's device compute instead of idling the chip."""
    keys = jax.random.split(key, n_steps + 1)
    next_key, gumbel_keys = keys[0], keys[1:]

    def body(carry, step_key):
        cache, toks = carry
        logits, cache = decode_step_batched(params, cache, toks, cfg)
        if greedy:
            z = logits  # all-argmax batch: the [B, V] gumbel would cost
            # ~1.3ms/step at Gemma-2B's vocab for nothing
        else:
            g = jax.random.gumbel(step_key, logits.shape, dtype=logits.dtype)
            z = jnp.where(
                temps[:, None] > 0.0,
                logits / jnp.maximum(temps[:, None], 1e-4) + g,
                logits,
            )
        nxt = jnp.argmax(z, axis=-1).astype(jnp.int32)[:, None]  # [B, 1]
        return (cache, nxt), nxt[:, 0]

    (cache, last), toks = lax.scan(body, (cache, tokens), gumbel_keys)
    return toks.T, last, next_key, cache  # [B, n_steps], [B, 1]


def merge_chain_tokens(
    last: jax.Array,  # [B, 1] device token chain (prior segment's output)
    ids: jax.Array,  # [B] freshly sampled first tokens (prefill output)
    mask: jax.Array,  # [B] bool: True where a row was just prefilled
) -> jax.Array:
    """Graft prefill-sampled first tokens into the device token chain.

    An interleaved prefill used to invalidate the WHOLE chain, forcing
    the next segment's feed back through the host for every row. The
    prefill's first tokens are already on device (`_sample_logits` keeps
    the [B, V] logits there and returns [B] int32 ids), so scattering
    them into ``last`` keeps the chain device-resident across admissions:
    rows untouched by the prefill keep their in-flight segment's output,
    prefilled rows pick up their sampled id — zero host->device traffic
    either way."""
    return jnp.where(mask[:, None], ids[:, None], last)


def prefill_batched(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, S] right-padded prompts
    lengths: jax.Array,  # [B] prompt lengths; 0 = row untouched
    cfg: LlamaConfig,
) -> Tuple[jax.Array, Params]:
    """Consume whole prompts in ONE forward: fills rows' KV cache at
    positions [0, S), sets each active row's pos to its prompt length, and
    returns the logits at each row's LAST prompt token (the first sampled
    token comes from here) — so TTFT is one batched matmul-heavy forward
    instead of `prompt_len` sequential decode steps (round-2 measured
    633ms for a 64-token prompt; the reference only models batching,
    inference_types.go:96-104).

    Rows with ``lengths[b] == 0`` keep their cache and pos untouched, so
    new requests prefill while other rows are mid-decode (continuous
    batching). Padded query positions >= lengths[b] compute garbage that
    is never read: causal attention keeps them out of valid queries, later
    decode steps overwrite their cache slots before pos reaches them.
    """
    B, S = tokens.shape
    hd = cfg.head_dim
    max_s = cache["k"].shape[2]
    active = lengths > 0
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)  # [B, S, D]
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, S)
    sel = active[:, None, None, None]

    def body(x, inp):
        lp, ck, cv = inp
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = apply_rope((h @ deq(lp["wq"])).reshape(B, S, cfg.n_heads, hd), cos, sin)
        k = apply_rope((h @ deq(lp["wk"])).reshape(B, S, cfg.n_kv_heads, hd), cos, sin)
        v = (h @ deq(lp["wv"])).reshape(B, S, cfg.n_kv_heads, hd)
        attn = attention(q, k, v, causal=True)
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        # prompts start at position 0 (rows are reset on admission)
        ck = jnp.where(sel, lax.dynamic_update_slice_in_dim(ck, k, 0, axis=1), ck)
        cv = jnp.where(sel, lax.dynamic_update_slice_in_dim(cv, v, 0, axis=1), cv)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    # head matmul only at each row's last valid position (V is large)
    idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    logits = (x_last @ lm_head_of(params, cfg)).astype(jnp.float32)
    pos = jnp.where(active, jnp.minimum(lengths, max_s - 1), cache["pos"])
    return logits, {"k": new_k, "v": new_v, "pos": pos.astype(jnp.int32)}


# ---- prefix KV reuse (serving path) ----------------------------------------

def copy_prefix_into_row(
    cache: Params,
    k: jax.Array,  # [L, P, KV, hd] cached prefix keys (P = padded bucket)
    v: jax.Array,  # [L, P, KV, hd] cached prefix values
    row,  # scalar int: batch row to graft into
    length,  # scalar int: true prefix length (<= P)
) -> Params:
    """Graft a cached prefix's K/V into one batch row at offset 0.

    The serving prefix cache stores device-resident per-layer K/V for
    shared prompt prefixes; on a trie hit the engine copies them into the
    freshly admitted row instead of recomputing them, and prefill then
    consumes only the uncached SUFFIX. A per-row `dynamic_update_slice`
    keeps this O(prefix) HBM traffic (the same idiom as `_row_update`);
    under donation it is an in-place write. The entry is bucket-padded
    (P >= length): the pad tail lands at positions >= pos and is masked
    by the per-row validity until decode overwrites it — the exact
    garbage-beyond-pos contract batched prefill already relies on.
    ``pos`` is set to ``length`` so a decode step between graft and
    suffix prefill cannot write inside the protected prefix span."""
    ck = lax.dynamic_update_slice(cache["k"], k[:, None], (0, row, 0, 0, 0))
    cv = lax.dynamic_update_slice(cache["v"], v[:, None], (0, row, 0, 0, 0))
    length = jnp.asarray(length, jnp.int32)
    pos = lax.dynamic_update_slice(cache["pos"], length[None], (row,))
    return {"k": ck, "v": cv, "pos": pos}


def extract_prefix_from_row(
    cache: Params, row, p_len: int
) -> Tuple[jax.Array, jax.Array]:
    """Read the first ``p_len`` cached K/V positions of one batch row
    (a new prefix-cache entry, taken after that row's prefill filled
    them). ``p_len`` is STATIC (the engine buckets entry lengths to
    powers of two, so compiles stay bounded); ``row`` is traced. NOT
    donated — the live batched cache must survive the copy."""
    L, _, _, KV, hd = cache["k"].shape
    k = lax.dynamic_slice(
        cache["k"], (0, row, 0, 0, 0), (L, 1, p_len, KV, hd)
    )[:, 0]
    v = lax.dynamic_slice(
        cache["v"], (0, row, 0, 0, 0), (L, 1, p_len, KV, hd)
    )[:, 0]
    return k, v


def prefill_batched_from(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, S] right-padded SUFFIX tokens
    lengths: jax.Array,  # [B] suffix lengths; 0 = row untouched
    starts: jax.Array,  # [B] per-row start offset (cached prefix length)
    cfg: LlamaConfig,
) -> Tuple[jax.Array, Params]:
    """Suffix-only prefill: like :func:`prefill_batched`, but each row's
    prompt tokens occupy GLOBAL positions [starts[b], starts[b]+lengths[b])
    and attend over the K/V already resident in the cache below
    ``starts[b]`` (a prefix grafted by :func:`copy_prefix_into_row`).
    With ``starts == 0`` this is exactly whole-prompt prefill; with a
    cached prefix the prompt cost drops from O(prompt) to O(suffix) —
    the prefix-reuse win for shared-system-prompt serving traffic.

    Differences from the root-prefill path, all per-row:
    - rope runs at global positions ``starts[b] + s`` (gathered tables);
    - K/V write via vmapped `dynamic_update_slice` at ``starts[b]``
      (the `_row_update` idiom decode uses);
    - attention queries the FULL cache row with an offset causal mask
      ``t <= starts[b] + s``, so suffix queries see the grafted prefix.

    Callers must keep ``starts[b] + S <= T`` for active rows (the engine
    drops a graft rather than let the padded write clamp out of place).
    Inactive rows (``lengths[b] == 0``) keep cache and pos untouched.
    """
    B, S = tokens.shape
    hd = cfg.head_dim
    max_s = cache["k"].shape[2]
    active = lengths > 0
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)  # [B, S, D]
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos_full, sin_full = rope_freqs(cfg, max_s)  # [T, hd/2]
    # global query positions, clamped so padded rows stay in-table
    posq = jnp.minimum(
        starts[:, None] + jnp.arange(S)[None, :], max_s - 1
    )  # [B, S]
    cos_t = cos_full[posq][:, :, None, :]  # [B, S, 1, hd/2]
    sin_t = sin_full[posq][:, :, None, :]
    # offset causal mask: suffix query s sees cache positions t <= start+s
    mask = (
        jnp.arange(max_s)[None, None, :] <= posq[:, :, None]
    )[:, None, None]  # [B, 1, 1, S, T]
    sel = active[:, None, None, None]

    def rot(t):  # apply_rope with per-row-position tables
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_t - t2 * sin_t, t1 * sin_t + t2 * cos_t], axis=-1
        ).astype(t.dtype)

    def body(x, inp):
        lp, ck, cv = inp  # ck/cv: [B, T, KV, hd] this layer's cache
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = rot((h @ deq(lp["wq"])).reshape(B, S, cfg.n_heads, hd))
        k = rot((h @ deq(lp["wk"])).reshape(B, S, cfg.n_kv_heads, hd))
        v = (h @ deq(lp["wv"])).reshape(B, S, cfg.n_kv_heads, hd)
        # write the suffix K/V at each row's start (inactive rows keep
        # their cache bit-identical: mid-decode neighbours are sacred)
        ck = jnp.where(sel, _row_update(ck, k, starts), ck)
        cv = jnp.where(sel, _row_update(cv, v, starts), cv)
        attn = attention(q, ck, cv, causal=False, mask=mask)
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    # head matmul only at each row's LAST suffix token (V is large)
    idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, D]
    logits = (x_last @ lm_head_of(params, cfg)).astype(jnp.float32)
    pos = jnp.where(
        active, jnp.minimum(starts + lengths, max_s - 1), cache["pos"]
    )
    return logits, {"k": new_k, "v": new_v, "pos": pos.astype(jnp.int32)}


# ---- paged KV (block-table serving path) -----------------------------------
#
# The contiguous batched cache stores row b's position t at cache[l, b, t].
# The PAGED cache stores it at pool[l, bt[b, t // BS], t % BS]: the cache is
# a pool of NB fixed-size blocks of BS tokens and each row owns an ordered
# block list (the [B, MB] block table, MB = max_seq // BS). Rows grow block
# by block, so resident HBM tracks tokens actually cached instead of
# max_seq * batch; blocks are refcounted host-side
# (kubedl_tpu.serving.kv_blocks) so prefix-cache entries share blocks by
# reference instead of copying whole prefixes into rows.
#
# Exactness contract (the tier-1 gate): every paged function below computes
# the SAME attention math as its contiguous twin over a gathered
# [B, T, KV, hd] view of the pool, where view position t is logical
# position t. Valid positions (t < pos) hold bit-identical K/V by
# induction; masked positions hold garbage that contributes an exact 0.0
# through the -1e30 mask — the same garbage-beyond-pos contract the
# contiguous path already relies on. Block-table entries a row does not own
# point at block 0 (the trash block): writes from vacant rows, padded
# prefill positions, and budget overshoot land there and are never read.


def init_paged_cache(
    cfg: LlamaConfig, batch: int, max_seq: int, num_blocks: int,
    block_size: int,
) -> Params:
    """Paged serving cache: K/V pools ``[L, NB, BS, KV, hd]`` + per-row
    positions + the ``[B, MB]`` block table (all entries start at the
    trash block 0). ``max_seq`` must be a multiple of ``block_size`` so
    the gathered view is exactly [B, max_seq, KV, hd]."""
    if max_seq % block_size != 0:
        raise ValueError(
            f"max_seq {max_seq} not a multiple of block_size {block_size}"
        )
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
        "bt": jnp.zeros((batch, max_seq // block_size), jnp.int32),
    }


def _paged_view(pool: jax.Array, bt: jax.Array) -> jax.Array:
    """Gather one layer's pool [NB, BS, KV, hd] through the block table
    [B, MB] into the logical [B, MB*BS, KV, hd] view the contiguous
    attention math runs over unchanged."""
    B, MB = bt.shape
    BS = pool.shape[1]
    return pool[bt].reshape(B, MB * BS, pool.shape[2], pool.shape[3])


def _check_kv_attention(kv_attention: str) -> None:
    if kv_attention not in ("gather", "blocked"):
        raise ValueError(
            f"kv_attention must be 'gather' or 'blocked', got "
            f"{kv_attention!r}"
        )


def paged_decode_step_batched(
    params: Params, cache: Params, tokens: jax.Array, cfg: LlamaConfig,
    kv_attention: str = "gather",
) -> Tuple[jax.Array, Params]:
    """Block-table twin of :func:`decode_step_batched`: scatter the new
    K/V into each row's current block at ``(bt[b, pos//BS], pos%BS)``,
    then attend over the gathered view with the identical per-row
    validity mask. Rows whose table entry is unmapped write to the trash
    block (vacant rows keep advancing pos exactly like the contiguous
    path — their writes just land in garbage).

    ``kv_attention`` picks the attention implementation: ``"gather"``
    (the default bit-exactness oracle — materialize the logical view,
    dense masked attention) or ``"blocked"`` (the
    :mod:`kubedl_tpu.models.paged_attention` online-softmax kernel that
    walks the block table; fp-close, greedy-token-identical). The
    blocked path hands the step's K/V to the kernel (``new_k``/``new_v``)
    which writes them into the pool block in the same invocation — one
    dispatch per layer instead of scatter + attend."""
    _check_kv_attention(kv_attention)
    B = tokens.shape[0]
    hd = cfg.head_dim
    pos = cache["pos"]  # [B]
    bt = cache["bt"]  # [B, MB]
    BS = cache["k"].shape[2]
    max_s = bt.shape[1] * BS
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)  # [B, 1, D]
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, max_s)
    cos_t = cos[pos][:, None, None, :]
    sin_t = sin[pos][:, None, None, :]
    valid = (jnp.arange(max_s)[None, :] <= pos[:, None])  # [B, T]
    mask = valid[:, None, None, None, :]
    blk = bt[jnp.arange(B), pos // BS]  # [B] current block per row
    off = pos % BS

    def rot(t):
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_t - t2 * sin_t, t1 * sin_t + t2 * cos_t], axis=-1
        ).astype(t.dtype)

    def body(x, inp):
        lp, ckp, cvp = inp  # ckp/cvp: [NB, BS, KV, hd] this layer's pool
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = rot((h @ deq(lp["wq"])).reshape(B, 1, cfg.n_heads, hd))
        k = rot((h @ deq(lp["wk"])).reshape(B, 1, cfg.n_kv_heads, hd))
        v = (h @ deq(lp["wv"])).reshape(B, 1, cfg.n_kv_heads, hd)
        if kv_attention == "blocked":
            # fused KV write: the kernel lands this step's K/V into the
            # row's current block itself, retiring the separate scatter
            # dispatch the gather path still performs
            attn, ckp, cvp = blocked_attention.paged_attention(
                q, ckp, cvp, bt, pos, new_k=k[:, 0], new_v=v[:, 0]
            )
        else:
            ckp = ckp.at[blk, off].set(k[:, 0])
            cvp = cvp.at[blk, off].set(v[:, 0])
            attn = attention(
                q, _paged_view(ckp, bt), _paged_view(cvp, bt),
                causal=False, mask=mask,
            )
        x = x + attn.reshape(B, 1, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        return x, (ckp, cvp)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    logits = (x[:, 0] @ lm_head_of(params, cfg)).astype(jnp.float32)
    return logits, {
        "k": new_k,
        "v": new_v,
        "pos": jnp.minimum(pos + 1, max_s - 1),
        "bt": bt,
    }


def paged_decode_segment(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, 1] first input token per row
    temps: jax.Array,  # [B] sampling temperature; <= 0 = greedy
    key: jax.Array,
    cfg: LlamaConfig,
    n_steps: int,
    greedy: bool = False,
    kv_attention: str = "gather",
) -> Tuple[jax.Array, jax.Array, jax.Array, Params]:
    """Block-table twin of :func:`decode_segment` — same on-device
    sample->feed chain and return contract, over the paged step. The
    engine reserves blocks covering ``pos + n_steps`` for every decoding
    row BEFORE dispatch, so in-segment writes never need a host trip.

    The gumbel sample chain is keyed off ``key`` alone — per step, one
    split shared by every row — so for a fixed seed the sampled path is
    deterministic and IDENTICAL across ``kv_attention`` kernels (the
    regression gate for the blocked kernel: kernel choice may only
    perturb logits at fp tolerance, never the randomness)."""
    keys = jax.random.split(key, n_steps + 1)
    next_key, gumbel_keys = keys[0], keys[1:]

    def body(carry, step_key):
        cache, toks = carry
        logits, cache = paged_decode_step_batched(
            params, cache, toks, cfg, kv_attention=kv_attention
        )
        if greedy:
            z = logits
        else:
            g = jax.random.gumbel(step_key, logits.shape, dtype=logits.dtype)
            z = jnp.where(
                temps[:, None] > 0.0,
                logits / jnp.maximum(temps[:, None], 1e-4) + g,
                logits,
            )
        nxt = jnp.argmax(z, axis=-1).astype(jnp.int32)[:, None]
        return (cache, nxt), nxt[:, 0]

    (cache, last), toks = lax.scan(body, (cache, tokens), gumbel_keys)
    return toks.T, last, next_key, cache


def _paged_suffix_forward(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, S] right-padded suffix tokens
    lengths: jax.Array,  # [B] suffix lengths; 0 = row untouched
    starts: jax.Array,  # [B] per-row global start offset
    cfg: LlamaConfig,
    kv_attention: str = "gather",
    self_contained: bool = False,
    positions: Optional[jax.Array] = None,  # [B, S] per-token positions
    self_mask: Optional[jax.Array] = None,  # [B, S, S] in-suffix mask
) -> Tuple[jax.Array, Params]:
    """Shared body of paged prefill and speculative verify: run suffix
    tokens at global positions ``starts[b] + s`` against the gathered
    cache view (offset causal mask, same math as
    :func:`prefill_batched_from`), scattering their K/V into each row's
    blocks. Pad positions (``s >= lengths[b]``) and inactive rows route
    their writes to the trash block — which retires the contiguous
    path's dispatch-time graft-overflow fixup for paged engines: a
    clamped write can only ever land in garbage, never inside a row.
    Returns (final-norm hidden states [B, S, D], updated cache).

    ``self_contained=True`` is the READ-ONLY scoring mode behind
    :func:`paged_verify_multi`: the pool is never written (so several
    candidate suffixes can share one row's blocks in a single forward)
    — each query attends committed pool history (``t < starts``) merged
    with the suffix's own fresh K/V under an in-suffix causal mask,
    which is the same key set the write path would have seen. The
    returned cache is the input cache, untouched.

    ``positions`` overrides the default consecutive position layout
    ``starts[b] + s`` — the tree-verify hook, where several trie nodes
    share a depth (and so a RoPE angle). ``self_mask[b, s, t]`` replaces
    the in-suffix causal block with an arbitrary visibility mask (the
    trie's ancestor mask). Both are read-only-mode-only: the write path
    demands consecutive causal suffixes."""
    _check_kv_attention(kv_attention)
    if (positions is not None or self_mask is not None) \
            and not self_contained:
        raise ValueError(
            "positions/self_mask require self_contained=True"
        )
    B, S = tokens.shape
    hd = cfg.head_dim
    bt = cache["bt"]
    BS = cache["k"].shape[2]
    max_s = bt.shape[1] * BS
    active = lengths > 0
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos_full, sin_full = rope_freqs(cfg, max_s)
    if positions is None:
        posq = jnp.minimum(
            starts[:, None] + jnp.arange(S)[None, :], max_s - 1
        )  # [B, S]
    else:
        posq = jnp.minimum(positions, max_s - 1)
    cos_t = cos_full[posq][:, :, None, :]
    sin_t = sin_full[posq][:, :, None, :]
    if self_contained:
        # pool history (t < starts) ++ in-suffix causal block: the same
        # key set the write path exposes, without the writes
        hist = jnp.broadcast_to(
            jnp.arange(max_s)[None, None, :] < starts[:, None, None],
            (B, S, max_s),
        )
        if self_mask is None:
            causal_self = jnp.broadcast_to(
                (jnp.arange(S)[None, :] <= jnp.arange(S)[:, None])[None],
                (B, S, S),
            )
        else:
            causal_self = self_mask
        mask = jnp.concatenate([hist, causal_self], axis=-1)[:, None, None]
    else:
        mask = (
            jnp.arange(max_s)[None, None, :] <= posq[:, :, None]
        )[:, None, None]  # [B, 1, 1, S, T]
    # scatter targets: pad/inactive positions write to the trash block
    writable = active[:, None] & (jnp.arange(S)[None, :] < lengths[:, None])
    blk = jnp.where(writable, bt[jnp.arange(B)[:, None], posq // BS], 0)
    off = posq % BS

    def rot(t):
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_t - t2 * sin_t, t1 * sin_t + t2 * cos_t], axis=-1
        ).astype(t.dtype)

    def body(x, inp):
        lp, ckp, cvp = inp
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = rot((h @ deq(lp["wq"])).reshape(B, S, cfg.n_heads, hd))
        k = rot((h @ deq(lp["wk"])).reshape(B, S, cfg.n_kv_heads, hd))
        v = (h @ deq(lp["wv"])).reshape(B, S, cfg.n_kv_heads, hd)
        if not self_contained:
            ckp = ckp.at[blk, off].set(k)
            cvp = cvp.at[blk, off].set(v)
        if kv_attention == "blocked":
            attn = blocked_attention.paged_attention(
                q, ckp, cvp, bt, starts,
                self_k=k if self_contained else None,
                self_v=v if self_contained else None,
                self_mask=self_mask,
            )
        elif self_contained:
            attn = attention(
                q,
                jnp.concatenate([_paged_view(ckp, bt), k], axis=1),
                jnp.concatenate([_paged_view(cvp, bt), v], axis=1),
                causal=False, mask=mask,
            )
        else:
            attn = attention(
                q, _paged_view(ckp, bt), _paged_view(cvp, bt),
                causal=False, mask=mask,
            )
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        return x, (ckp, cvp)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    if self_contained:
        return x, cache
    pos = jnp.where(
        active, jnp.minimum(starts + lengths, max_s - 1), cache["pos"]
    )
    return x, {
        "k": new_k, "v": new_v, "pos": pos.astype(jnp.int32), "bt": bt,
    }


def paged_prefill_batched(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    lengths: jax.Array,
    cfg: LlamaConfig,
) -> Tuple[jax.Array, Params]:
    """Block-table twin of :func:`prefill_batched` (whole prompts from
    position 0): last-token logits + updated cache.

    NOT routed through the suffix forward: prompts starting at 0 attend
    only to their own fresh K/V, so this mirrors `prefill_batched`'s
    LOCAL causal attention — identical ops on identical inputs, which is
    what makes the tier-1 bit-identity gate hold for the prefill leg —
    and only the cache WRITE differs (scatter into blocks instead of a
    contiguous row update)."""
    B, S = tokens.shape
    hd = cfg.head_dim
    bt = cache["bt"]
    BS = cache["k"].shape[2]
    max_s = bt.shape[1] * BS
    active = lengths > 0
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, S)
    posw = jnp.minimum(jnp.arange(S), max_s - 1)
    writable = active[:, None] & (jnp.arange(S)[None, :] < lengths[:, None])
    blk = jnp.where(writable, bt[:, posw // BS], 0)  # [B, S]
    off = jnp.broadcast_to((posw % BS)[None, :], (B, S))

    def body(x, inp):
        lp, ckp, cvp = inp
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = apply_rope((h @ deq(lp["wq"])).reshape(B, S, cfg.n_heads, hd), cos, sin)
        k = apply_rope((h @ deq(lp["wk"])).reshape(B, S, cfg.n_kv_heads, hd), cos, sin)
        v = (h @ deq(lp["wv"])).reshape(B, S, cfg.n_kv_heads, hd)
        attn = attention(q, k, v, causal=True)
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
        ckp = ckp.at[blk, off].set(k)
        cvp = cvp.at[blk, off].set(v)
        return x, (ckp, cvp)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    logits = (x_last @ lm_head_of(params, cfg)).astype(jnp.float32)
    pos = jnp.where(active, jnp.minimum(lengths, max_s - 1), cache["pos"])
    return logits, {
        "k": new_k, "v": new_v, "pos": pos.astype(jnp.int32), "bt": bt,
    }


def paged_prefill_from(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    lengths: jax.Array,
    starts: jax.Array,
    cfg: LlamaConfig,
    kv_attention: str = "gather",
) -> Tuple[jax.Array, Params]:
    """Block-table twin of :func:`prefill_batched_from` (suffix-only
    prefill over a grafted prefix): last-token logits + updated cache."""
    x, cache = _paged_suffix_forward(
        params, cache, tokens, lengths, starts, cfg,
        kv_attention=kv_attention,
    )
    idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    logits = (x_last @ lm_head_of(params, cfg)).astype(jnp.float32)
    return logits, cache


def paged_verify(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, S]: [last accepted token, draft_1..draft_k]
    lengths: jax.Array,  # [B] k+1 for verifying rows, 0 = untouched
    starts: jax.Array,  # [B] row position before the verify
    cfg: LlamaConfig,
    kv_attention: str = "gather",
) -> Tuple[jax.Array, Params]:
    """Speculative verify: score a draft-extended suffix in ONE forward
    and return the target model's GREEDY token after every position —
    ``ids[b, j]`` is the argmax continuation after consuming
    ``tokens[b, j]``. The host accepts the longest prefix where
    ``draft_j == ids[:, j-1]`` plus the bonus token ``ids[:, a]``; greedy
    acceptance is exact by construction because every emitted token is
    the target's own argmax given only accepted history. Rejected-suffix
    KV stays in the row's blocks as garbage beyond the rolled-back pos
    (the engine rewinds its host pos mirror and frees now-unneeded
    blocks)."""
    x, cache = _paged_suffix_forward(
        params, cache, tokens, lengths, starts, cfg,
        kv_attention=kv_attention,
    )
    logits = (x @ lm_head_of(params, cfg)).astype(jnp.float32)  # [B, S, V]
    ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return ids, cache


def paged_verify_multi(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, N, S]: N candidate suffixes per row
    lengths: jax.Array,  # [B] suffix length (shared by a row's candidates)
    starts: jax.Array,  # [B] row position before the verify
    cfg: LlamaConfig,
    kv_attention: str = "gather",
) -> jax.Array:
    """Score N candidate continuations per row in ONE read-only forward:
    returns the target's greedy ids ``[B, N, S]`` (``ids[b, n, j]`` =
    argmax after consuming ``tokens[b, n, j]``). Candidates are flattened
    to ``B*N`` rows SHARING each row's block table and start — legal only
    because the self-contained suffix forward never writes the pool, so
    candidate n cannot leak K/V into candidate m's view. The host picks
    the candidate with the longest agreeing prefix and re-runs the
    standard write-path :func:`paged_verify` on the winner alone, which
    keeps every emitted token the target's own argmax over committed
    history (bit-exact vs the single-candidate path). No cache is
    returned: with nothing donated, XLA drops all cache updates."""
    B, N, S = tokens.shape
    rep = lambda a: jnp.repeat(a, N, axis=0)  # noqa: E731
    flat_cache = {
        "k": cache["k"], "v": cache["v"],
        "pos": rep(cache["pos"]), "bt": rep(cache["bt"]),
    }
    x, _ = _paged_suffix_forward(
        params, flat_cache, tokens.reshape(B * N, S), rep(lengths),
        rep(starts), cfg, kv_attention=kv_attention, self_contained=True,
    )
    logits = (x @ lm_head_of(params, cfg)).astype(jnp.float32)
    ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return ids.reshape(B, N, S)


def paged_verify_tree(
    params: Params,
    cache: Params,
    tokens: jax.Array,  # [B, M] trie-node tokens (node 0 = last accepted)
    positions: jax.Array,  # [B, M] global position of each node
    tree_mask: jax.Array,  # [B, M, M] bool: node m sees node t
    lengths: jax.Array,  # [B] live node count; 0 = row untouched
    starts: jax.Array,  # [B] row position before the verify
    cfg: LlamaConfig,
    kv_attention: str = "gather",
) -> jax.Array:
    """Score a prefix-trie of draft continuations in ONE read-only
    forward: returns the target's greedy ids ``[B, M]`` — ``ids[b, m]``
    is the argmax continuation after consuming trie node m along its
    root path. The trie generalizes :func:`paged_verify_multi`'s flat
    candidate list: candidates sharing a prefix share nodes, so the
    verify window is the trie size M, not candidates x depth.

    ``tree_mask[b, m, t]`` must be True exactly when t is m itself or an
    ancestor of m, and ``positions[b, m] = starts[b] + depth(m)`` (node
    0, the last accepted token, sits at depth 0). Under that mask each
    node attends committed pool history plus its own root path — the
    identical key set a chain verify of that path would see, so a
    single-chain trie reproduces :func:`paged_verify` bit-exactly. The
    host walks the deepest accepted path and re-runs the write-path
    verify on it alone; like multi-verify, nothing here writes the pool
    and no cache is returned."""
    x, _ = _paged_suffix_forward(
        params, cache, tokens, lengths, starts, cfg,
        kv_attention=kv_attention, self_contained=True,
        positions=positions, self_mask=tree_mask,
    )
    logits = (x @ lm_head_of(params, cfg)).astype(jnp.float32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, M]


def copy_kv_block(cache: Params, src, dst) -> Params:
    """Copy one block's K/V across all layers (``src`` -> ``dst``, traced
    scalars: one compile total). The copy-on-write primitive: the engine
    calls it when a row must append inside a SHARED block — the partial
    tail of a grafted prefix — so the write lands in a private copy and
    the prefix entry's block stays immutable for its other readers."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    return {
        "k": cache["k"].at[:, dst].set(cache["k"][:, src]),
        "v": cache["v"].at[:, dst].set(cache["v"][:, src]),
        "pos": cache["pos"],
        "bt": cache["bt"],
    }


def paged_graft_prefix(
    cache: Params,
    k: jax.Array,  # [L, P, KV, hd] array-payload prefix entry (padded)
    v: jax.Array,
    row,  # scalar int: batch row to graft into
    length,  # scalar int: true prefix length (<= P)
) -> Params:
    """Array-payload twin of :func:`copy_prefix_into_row` for paged rows:
    scatter a prefix entry's K/V into ``row``'s blocks at positions
    [0, P) and set pos to ``length``. Block-ref entries never need this
    (the engine splices the block table host-side at zero device cost);
    it exists for entries holding materialized arrays — e.g. inserted by
    tests or migrated from a contiguous engine. Pad positions beyond the
    row's allocated blocks hit trash-block table entries and vanish."""
    L, P, KV, hd = k.shape
    bt = cache["bt"]
    BS = cache["k"].shape[2]
    posw = jnp.minimum(jnp.arange(P), bt.shape[1] * BS - 1)
    blk = bt[row][posw // BS]  # [P]
    off = posw % BS
    length = jnp.asarray(length, jnp.int32)
    pos = lax.dynamic_update_slice(cache["pos"], length[None], (row,))
    return {
        "k": cache["k"].at[:, blk, off].set(k),
        "v": cache["v"].at[:, blk, off].set(v),
        "pos": pos,
        "bt": bt,
    }


def export_kv_blocks(
    cache: Params, blocks
) -> Tuple[jax.Array, jax.Array]:
    """Gather ``blocks``' K/V payloads out of the pool for a disaggregated
    handoff: (k, v) each [L, n_blocks, BS, KV, hd]. A fresh gather, not a
    view — the result stays valid after the source cache is donated into
    later dispatches or the blocks are freed back to the allocator."""
    idx = jnp.asarray(blocks, jnp.int32)
    return cache["k"][:, idx], cache["v"][:, idx]


def import_kv_blocks(cache: Params, k, v, blocks) -> Params:
    """Scatter a handoff's K/V payloads ([L, n, BS, KV, hd]) into ``blocks``
    of the adopting engine's pool. Inverse of :func:`export_kv_blocks`; the
    block ids come from the adopter's OWN allocator — block numbering never
    survives the transfer, only payloads and the logical table order do."""
    idx = jnp.asarray(blocks, jnp.int32)
    return {
        "k": cache["k"].at[:, idx].set(jnp.asarray(k, cache["k"].dtype)),
        "v": cache["v"].at[:, idx].set(jnp.asarray(v, cache["v"].dtype)),
        "pos": cache["pos"],
        "bt": cache["bt"],
    }


def decode_step(
    params: Params, cache: Params, tokens: jax.Array, cfg: LlamaConfig
) -> Tuple[jax.Array, Params]:
    """One decode step: tokens [B, 1] -> (logits [B, V], updated cache).

    Static shapes throughout (cache is pre-allocated to max_seq) so the step
    compiles once and never re-traces — the XLA serving requirement.
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    pos = cache["pos"]
    x = gather_embed(params["embed"], tokens).astype(cfg.dtype)  # [B, 1, D]
    if cfg.embed_scale:
        x = x * math.sqrt(cfg.dim)
    cos, sin = rope_freqs(cfg, cfg.max_seq)
    cos_t = lax.dynamic_slice_in_dim(cos, pos, 1)
    sin_t = lax.dynamic_slice_in_dim(sin, pos, 1)
    max_s = cache["k"].shape[2]
    valid = (jnp.arange(max_s) <= pos)[None, None, None, :]  # [1,1,1,T]

    new_k, new_v = [], []
    for layer in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_plus_one)
        q = (h @ deq(lp["wq"])).reshape(B, 1, cfg.n_heads, hd)
        k = (h @ deq(lp["wk"])).reshape(B, 1, cfg.n_kv_heads, hd)
        v = (h @ deq(lp["wv"])).reshape(B, 1, cfg.n_kv_heads, hd)
        q = apply_rope(q, cos_t, sin_t)
        k = apply_rope(k, cos_t, sin_t)
        ck = lax.dynamic_update_slice_in_dim(cache["k"][layer], k, pos, axis=1)
        cv = lax.dynamic_update_slice_in_dim(cache["v"][layer], v, pos, axis=1)
        new_k.append(ck)
        new_v.append(cv)
        attn = attention(q, ck, cv, causal=False, mask=valid)
        x = x + attn.reshape(B, 1, cfg.n_heads * hd) @ deq(lp["wo"])
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, cfg.norm_plus_one)
        gate = _act(cfg)((h @ deq(lp["w_gate"])).astype(jnp.float32)).astype(h.dtype)
        x = x + (gate * (h @ deq(lp["w_up"]))) @ deq(lp["w_down"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    logits = (x[:, 0] @ lm_head_of(params, cfg)).astype(jnp.float32)
    cache = {
        "k": jnp.stack(new_k),
        "v": jnp.stack(new_v),
        "pos": pos + 1,
    }
    return logits, cache

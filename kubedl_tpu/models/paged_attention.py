"""Blocked paged attention: attend against the KV block pool directly.

The gather path (`llama._paged_view`) materializes the full logical
``[B, MB*BS, KV, hd]`` view of every row's cache via ``pool[bt]`` before
running dense attention — at high decode concurrency that gather is pure
data movement and dominates step time (ROADMAP item 2). This module walks
the block table instead: a flash-style online-softmax recurrence folds the
pool in ``tile``-sized chunks of blocks, so the logical view never exists
and the garbage in unowned/trash blocks contributes an exact 0.0 through
the same -1e30 mask contract the gather path relies on.

Two implementations behind ONE interface (:func:`paged_attention`):

- ``lax``: a `lax.scan` over chunks of C blocks (C = the largest divisor
  of MB with C*BS <= tile keys). Chunking is what makes this a win — a
  one-block-per-step scan loses to the gather at production block sizes
  (BS=16/32) because scan-iteration overhead swamps the per-block math;
  at tile=256 the chunked scan beats the gather at every benched shape.
  Runs everywhere (tier-1 exercises it on CPU).
- ``pallas``: a TPU kernel on grid (B, KV, MB) with the block table and
  per-row starts as scalar-prefetch operands, so the BlockSpec index map
  streams exactly each row's own pool blocks through VMEM — no gather,
  no logical view, O(tile) live keys. Interpret mode covers CPU parity
  tests.

Numerics: the online softmax reorders the reduction, so outputs are
fp-close (observed ~4e-7 f32) but NOT bit-identical to the gather+dense
oracle. The engine therefore defaults to ``kv_attention="gather"`` (the
tier-1 bit-exactness oracle) and selects ``"blocked"`` as the opt-in fast
path; greedy decode chains are token-identical in tier-1 either way.

Masking contract (matches ``llama._paged_suffix_forward``): query s of
row b sits at global position ``posq = min(starts[b] + s, max_s - 1)``
and attends pool keys at positions ``t <= posq``. With ``self_k``/
``self_v`` (the read-only multi-candidate verify), pool keys are history
only (``t < starts[b]``) and the fresh suffix K/V are folded as one extra
online-softmax step under an in-suffix mask — the pool is never
written, which is what lets XLA drop the scatter entirely. The default
in-suffix mask is causal; ``self_mask`` (a [B, S, S] bool, True = key
visible) overrides it for tree-structured verification where node s may
only see its trie ancestors.

Fused KV-write (decode, S=1): passing ``new_k``/``new_v`` ([B, KV, hd],
this step's K/V) makes :func:`paged_attention` write them into each
row's current pool block at ``(bt[b, starts//BS], starts % BS)`` inside
the same call and return ``(out, k_pool, v_pool)`` — retiring the
separate per-layer scatter dispatch the decode step used to pay. The
lax path folds the scatter in front of the chunk scan (identical ops to
the old scatter-then-attend call-site sequence, so bit-identical); the
pallas kernel aliases the pools in/out and patches the written row in
VMEM at the write block, so the fresh token is attended from the
patched tile and only the ONE dirty block per (row, kv-head) is copied
back to HBM.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30
#: exp2 domain in the pallas kernel (same rationale as ops.flash_attention:
#: the VPU's transcendental unit is a 2^x evaluator).
LOG2E = math.log2(math.e)

#: default key-tile width (keys folded per lax-scan step). 256 is the
#: measured CPU sweet spot for BS=16/32; the pallas kernel tiles by BS.
DEFAULT_TILE = 256

#: kernel picked when callers pass ``kernel=None``: "auto" resolves to
#: pallas on TPU and the lax scan elsewhere. Tests override this module
#: global to force the pallas kernel (interpret mode) through the full
#: model stack on CPU.
DEFAULT_KERNEL = "auto"

#: trace-time counters per implementation — bench asserts the blocked
#: path is actually in the compiled hot graph, not silently the oracle.
#: "fused" counts paged_attention calls that carried the decode step's
#: K/V write (either implementation).
TRACE_COUNT = {"lax": 0, "pallas": 0, "fused": 0}


def blocks_per_chunk(num_blocks: int, block_size: int,
                     tile: int = DEFAULT_TILE) -> int:
    """Largest divisor C of ``num_blocks`` with C*block_size <= tile
    (>= 1 even when a single block exceeds the tile)."""
    best = 1
    for c in range(1, num_blocks + 1):
        if num_blocks % c == 0 and c * block_size <= tile:
            best = c
    return best


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _online_fold(m, l, acc, s, vb, einsum_pv: str):
    """One online-softmax step: fold masked scores ``s`` (-1e30 where
    invalid) and values ``vb`` into the running (max, sum, acc) triple.
    The -1e29 clamp makes a FULLY-masked chunk contribute exact zeros
    (p = exp(-1e30 + 1e29) underflows to 0.0) instead of the classic
    exp(-1e30 - (-1e30)) = 1 poisoning — reachable in self_k mode where
    a row with starts=0 has no pool history at all."""
    m_new = jnp.maximum(jnp.maximum(m, s.max(axis=-1)), -1e29)
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(einsum_pv, p, vb)
    return m_new, l_new, acc_new


def _lax_paged_attention(
    q: jax.Array,  # [B, S, H, hd]
    k_pool: jax.Array,  # [NB, BS, KV, hd]
    v_pool: jax.Array,
    bt: jax.Array,  # [B, MB] int32
    starts: jax.Array,  # [B] int32 (decode: pos; suffix: row start)
    self_k: Optional[jax.Array],  # [B, S, KV, hd] fresh suffix K (or None)
    self_v: Optional[jax.Array],
    tile: int,
    self_mask: Optional[jax.Array] = None,  # [B, S, S] bool (tree verify)
) -> jax.Array:
    TRACE_COUNT["lax"] += 1
    B, S, H, hd = q.shape
    BS, KV = k_pool.shape[1], k_pool.shape[2]
    MB = bt.shape[1]
    max_s = MB * BS
    group = H // KV
    scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(B, S, KV, group, hd).astype(jnp.float32)
    posq = jnp.minimum(starts[:, None] + jnp.arange(S)[None, :], max_s - 1)
    C = blocks_per_chunk(MB, BS, tile)
    NC = MB // C
    btc = bt.reshape(B, NC, C)

    def body(carry, inp):
        btj, c = inp  # btj [B, C], c scalar chunk index
        kb = k_pool[btj].reshape(B, C * BS, KV, hd).astype(jnp.float32)
        vb = v_pool[btj].reshape(B, C * BS, KV, hd).astype(jnp.float32)
        s = jnp.einsum("bskgh,btkh->bkgst", qg, kb) * scale
        t = c * (C * BS) + jnp.arange(C * BS)
        if self_k is None:
            valid = t[None, None, :] <= posq[:, :, None]  # [B, S, C*BS]
        else:
            # read-only mode: pool keys are committed history only
            valid = jnp.broadcast_to(
                t[None, None, :] < starts[:, None, None], (B, S, C * BS)
            )
        s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
        return _online_fold(*carry, s, vb, "bkgst,btkh->bkgsh"), None

    m0 = jnp.full((B, KV, group, S), -1e29, jnp.float32)
    l0 = jnp.zeros_like(m0)
    a0 = jnp.zeros((B, KV, group, S, hd), jnp.float32)
    (m, l, acc), _ = lax.scan(
        body, (m0, l0, a0), (btc.transpose(1, 0, 2), jnp.arange(NC))
    )
    if self_k is not None:
        kb = self_k.reshape(B, S, KV, hd).astype(jnp.float32)
        vb = self_v.reshape(B, S, KV, hd).astype(jnp.float32)
        s = jnp.einsum("bskgh,btkh->bkgst", qg, kb) * scale  # [B,KV,G,S,S]
        if self_mask is None:
            causal = (
                jnp.arange(S)[None, :] <= jnp.arange(S)[:, None]
            )  # [Sq, Sk]
            s = jnp.where(causal[None, None, None], s, NEG_INF)
        else:
            # tree verify: node s sees exactly its trie ancestors + itself
            s = jnp.where(self_mask[:, None, None], s, NEG_INF)
        m, l, acc = _online_fold(m, l, acc, s, vb, "bkgst,btkh->bkgsh")
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd).astype(q.dtype)


def _blocked_kernel(
    bt_ref, st_ref,  # scalar-prefetch: [B, MB] block table, [B] starts
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, group: int, block_size: int, n_blocks: int,
    max_s: int,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    hd = q_ref.shape[-1]
    R = q_ref.shape[2]  # S * group query rows for this kv head

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0]  # [R, hd], row r = s*group + u (s-major)
    k = k_ref[0, :, 0]  # [BS, hd] — row b's j-th pool block via index map
    v = v_ref[0, :, 0]
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * LOG2E)  # [R, BS], base-2 domain
    start = st_ref[b]
    sidx = lax.broadcasted_iota(jnp.int32, (R, block_size), 0) // group
    qpos = jnp.minimum(start + sidx, max_s - 1)
    t = j * block_size + lax.broadcasted_iota(
        jnp.int32, (R, block_size), 1
    )
    s = jnp.where(t <= qpos, s, NEG_INF)
    m_prev = m_ref[:, :1]  # [R, 1]
    m_new = jnp.maximum(
        jnp.maximum(m_prev, s.max(axis=-1, keepdims=True)), -1e29
    )
    p = jnp.exp2(s - m_new)
    corr = jnp.exp2(m_prev - m_new)
    pv = lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] = acc_ref[:] * corr + pv
    l_ref[:, :1] = l_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
    m_ref[:, :1] = m_new

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pallas_paged_attention(
    q: jax.Array,  # [B, S, H, hd]
    k_pool: jax.Array,  # [NB, BS, KV, hd]
    v_pool: jax.Array,
    bt: jax.Array,  # [B, MB] int32
    starts: jax.Array,  # [B] int32
    interpret: bool,
) -> jax.Array:
    from jax.experimental.pallas import tpu as pltpu

    TRACE_COUNT["pallas"] += 1
    B, S, H, hd = q.shape
    BS, KV = k_pool.shape[1], k_pool.shape[2]
    MB = bt.shape[1]
    group = H // KV
    R = S * group
    # [B, KV, R, hd] with row r = s*group + u: one contiguous query tile
    # per (row, kv-head) grid cell, GQA folded into the tile rows
    qr = q.reshape(B, S, KV, group, hd).transpose(0, 2, 1, 3, 4)
    qr = qr.reshape(B, KV, R, hd)
    kernel = lambda *refs: _blocked_kernel(  # noqa: E731
        *refs, scale=1.0 / math.sqrt(hd), group=group, block_size=BS,
        n_blocks=MB, max_s=MB * BS,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, MB),  # j innermost: scratch carries across blocks
        in_specs=[
            pl.BlockSpec((1, 1, R, hd), lambda b, g, j, bt, st: (b, g, 0, 0)),
            # the whole point: stream row b's OWN j-th block from the pool
            pl.BlockSpec(
                (1, BS, 1, hd), lambda b, g, j, bt, st: (bt[b, j], 0, g, 0)
            ),
            pl.BlockSpec(
                (1, BS, 1, hd), lambda b, g, j, bt, st: (bt[b, j], 0, g, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, R, hd), lambda b, g, j, bt, st: (b, g, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, R, hd), q.dtype),
        compiler_params=getattr(
            pltpu, "CompilerParams", pltpu.TPUCompilerParams
        )(dimension_semantics=("arbitrary",) * 3),
        interpret=interpret,
    )(bt.astype(jnp.int32), starts.astype(jnp.int32), qr, k_pool, v_pool)
    out = out.reshape(B, KV, S, group, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, S, H, hd)


def _fused_kernel(
    bt_ref, st_ref,  # scalar-prefetch: [B, MB] block table, [B] starts
    q_ref, k_ref, v_ref, nk_ref, nv_ref,
    o_ref, ok_ref, ov_ref, acc_ref, m_ref, l_ref,
    *, scale: float, group: int, block_size: int, n_blocks: int,
    max_s: int,
):
    """Decode-step (S=1) blocked kernel with the KV write fused in: at
    the block holding ``starts[b]`` the kernel patches row ``starts%BS``
    with this step's K/V in VMEM, attends the patched tile, and writes
    the patched block through the aliased pool output — the only block
    whose copy-out the revolving out buffer performs (the out index map
    is constant in j). Untouched pool blocks survive via the aliasing."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    R = q_ref.shape[2]  # group query rows (S == 1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    start = st_ref[b]
    jw = start // block_size
    off = start % block_size
    q = q_ref[0, 0]  # [R, hd]
    k = k_ref[0, :, 0]  # [BS, hd]
    v = v_ref[0, :, 0]
    sel = (
        lax.broadcasted_iota(jnp.int32, k.shape, 0) == off
    ) & (j == jw)  # [BS, hd]
    kj = jnp.where(sel, nk_ref[0, 0][None, :], k)
    vj = jnp.where(sel, nv_ref[0, 0][None, :], v)

    @pl.when(j == jw)
    def _write():
        ok_ref[0, :, 0] = kj
        ov_ref[0, :, 0] = vj

    s = lax.dot_general(
        q, kj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * LOG2E)  # [R, BS]
    qpos = jnp.minimum(start, max_s - 1)
    t = j * block_size + lax.broadcasted_iota(
        jnp.int32, (R, block_size), 1
    )
    s = jnp.where(t <= qpos, s, NEG_INF)
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(
        jnp.maximum(m_prev, s.max(axis=-1, keepdims=True)), -1e29
    )
    p = jnp.exp2(s - m_new)
    corr = jnp.exp2(m_prev - m_new)
    pv = lax.dot_general(
        p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] = acc_ref[:] * corr + pv
    l_ref[:, :1] = l_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
    m_ref[:, :1] = m_new

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pallas_paged_attention_fused(
    q: jax.Array,  # [B, 1, H, hd]
    k_pool: jax.Array,  # [NB, BS, KV, hd]
    v_pool: jax.Array,
    bt: jax.Array,  # [B, MB] int32
    starts: jax.Array,  # [B] int32 (= the written position)
    new_k: jax.Array,  # [B, KV, hd] this step's K
    new_v: jax.Array,
    interpret: bool,
):
    from jax.experimental.pallas import tpu as pltpu

    TRACE_COUNT["pallas"] += 1
    B, S, H, hd = q.shape
    BS, KV = k_pool.shape[1], k_pool.shape[2]
    MB = bt.shape[1]
    group = H // KV
    R = S * group
    qr = q.reshape(B, S, KV, group, hd).transpose(0, 2, 1, 3, 4)
    qr = qr.reshape(B, KV, R, hd)
    kernel = lambda *refs: _fused_kernel(  # noqa: E731
        *refs, scale=1.0 / math.sqrt(hd), group=group, block_size=BS,
        n_blocks=MB, max_s=MB * BS,
    )
    pool_spec = pl.BlockSpec(
        (1, BS, 1, hd), lambda b, g, j, bt, st: (bt[b, j], 0, g, 0)
    )
    # write-block spec: CONSTANT in j, so the revolving out buffer only
    # copies the one dirty block back per (row, kv-head) group. Rows own
    # their blocks exclusively (unowned entries all point at the trash
    # block, where colliding writes are garbage by contract).
    wb_spec = pl.BlockSpec(
        (1, BS, 1, hd),
        lambda b, g, j, bt, st: (bt[b, st[b] // BS], 0, g, 0),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, MB),
        in_specs=[
            pl.BlockSpec((1, 1, R, hd), lambda b, g, j, bt, st: (b, g, 0, 0)),
            pool_spec,
            pool_spec,
            pl.BlockSpec((1, 1, hd), lambda b, g, j, bt, st: (b, g, 0)),
            pl.BlockSpec((1, 1, hd), lambda b, g, j, bt, st: (b, g, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, R, hd), lambda b, g, j, bt, st: (b, g, 0, 0)
            ),
            wb_spec,
            wb_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
            pltpu.VMEM((R, 128), jnp.float32),
        ],
    )
    out, kp, vp = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, KV, R, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ],
        # inputs count the 2 scalar-prefetch operands: 3/4 = the pools
        input_output_aliases={3: 1, 4: 2},
        compiler_params=getattr(
            pltpu, "CompilerParams", pltpu.TPUCompilerParams
        )(dimension_semantics=("arbitrary",) * 3),
        interpret=interpret,
    )(
        bt.astype(jnp.int32), starts.astype(jnp.int32), qr, k_pool, v_pool,
        new_k, new_v,
    )
    out = out.reshape(B, KV, S, group, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, S, H, hd), kp, vp


def _fused_write_lax(k_pool, v_pool, bt, starts, new_k, new_v):
    """The scatter the decode call site used to dispatch separately,
    folded behind the fused-call interface: write row b's step K/V at
    ``(bt[b, starts//BS], starts % BS)``. Identical ops in identical
    order to the old external scatter — bit-identical by construction."""
    B = starts.shape[0]
    BS = k_pool.shape[1]
    blk = bt[jnp.arange(B), starts // BS]
    off = starts % BS
    return (
        k_pool.at[blk, off].set(new_k),
        v_pool.at[blk, off].set(new_v),
    )


def paged_attention(
    q: jax.Array,  # [B, S, H, hd]
    k_pool: jax.Array,  # [NB, BS, KV, hd] (one layer's pool)
    v_pool: jax.Array,
    bt: jax.Array,  # [B, MB] block table
    starts: jax.Array,  # [B] first query's global position per row
    *,
    self_k: Optional[jax.Array] = None,  # [B, S, KV, hd] (read-only mode)
    self_v: Optional[jax.Array] = None,
    self_mask: Optional[jax.Array] = None,  # [B, S, S] bool (tree verify)
    new_k: Optional[jax.Array] = None,  # [B, KV, hd] (fused decode write)
    new_v: Optional[jax.Array] = None,
    kernel: Optional[str] = None,  # None/"auto" | "lax" | "pallas"
    tile: int = DEFAULT_TILE,
    interpret: Optional[bool] = None,
):
    """Blocked paged attention over the pool — returns [B, S, H, hd],
    or ``(out, k_pool, v_pool)`` when ``new_k``/``new_v`` carry a fused
    decode-step KV write (S must be 1; the write lands at ``starts``).

    Query s of row b sits at global position ``min(starts[b]+s, max_s-1)``
    and sees pool keys at ``t <= posq`` — identical math to the gather
    oracle's masked dense attention, without ever building the gathered
    view. With ``self_k``/``self_v``, pool keys are restricted to
    ``t < starts`` and the fresh suffix attends itself under the causal
    (default) or ``self_mask`` tree mask (the read-only verify modes;
    lax path only — the pallas kernel serves the write-path decode hot
    loop).
    """
    if kernel is None:
        kernel = DEFAULT_KERNEL
    if kernel == "auto":
        kernel = "pallas" if jax.default_backend() == "tpu" else "lax"
    if kernel not in ("lax", "pallas"):
        raise ValueError(f"unknown paged-attention kernel {kernel!r}")
    if self_mask is not None and self_k is None:
        raise ValueError("self_mask requires self_k/self_v")
    if new_k is not None:
        if self_k is not None:
            raise ValueError("fused KV write excludes self_k/self_v")
        if q.shape[1] != 1:
            raise ValueError(
                f"fused KV write is decode-only (S=1), got S={q.shape[1]}"
            )
        TRACE_COUNT["fused"] += 1
        if kernel == "pallas":
            if interpret is None:
                interpret = _default_interpret()
            return _pallas_paged_attention_fused(
                q, k_pool, v_pool, bt, starts, new_k, new_v,
                interpret=interpret,
            )
        k_pool, v_pool = _fused_write_lax(
            k_pool, v_pool, bt, starts, new_k, new_v
        )
        out = _lax_paged_attention(
            q, k_pool, v_pool, bt, starts, None, None, tile
        )
        return out, k_pool, v_pool
    if kernel == "pallas" and self_k is None:
        if interpret is None:
            interpret = _default_interpret()
        return _pallas_paged_attention(
            q, k_pool, v_pool, bt, starts, interpret=interpret
        )
    return _lax_paged_attention(
        q, k_pool, v_pool, bt, starts, self_k, self_v, tile,
        self_mask=self_mask,
    )


__all__ = [
    "paged_attention",
    "blocks_per_chunk",
    "DEFAULT_TILE",
    "DEFAULT_KERNEL",
    "TRACE_COUNT",
]

"""Small convnet family (the MNIST-class workload, BASELINE target 1:
"example/tf MNIST ... converge[s] on a slice scheduled end-to-end by the
operator"; reference example: example/tf/mnist).

TPU-first shape: NHWC layout (XLA's native conv layout on TPU), bf16-able
`lax.conv_general_dilated` so the convolutions tile onto the MXU, pure
functional params, one jitted train step with donated state. Small on
purpose — this is the convergence-proof workload, not the flagship — but
it exercises the conv path none of the LM families touch.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class ConvNetConfig:
    image_size: int = 28
    channels: int = 1
    classes: int = 10
    width: int = 32  # first conv's filters; second doubles it
    hidden: int = 128
    dtype: Any = jnp.float32


def convnet_init(key: jax.Array, cfg: ConvNetConfig) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def he(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) * math.sqrt(2.0 / fan_in)
        ).astype(cfg.dtype)

    w1, w2 = cfg.width, cfg.width * 2
    flat = (cfg.image_size // 4) ** 2 * w2  # two 2x2 pools
    return {
        "conv1": he(k1, (3, 3, cfg.channels, w1), 9 * cfg.channels),
        "b1": jnp.zeros((w1,), cfg.dtype),
        "conv2": he(k2, (3, 3, w1, w2), 9 * w1),
        "b2": jnp.zeros((w2,), cfg.dtype),
        "dense": he(k3, (flat, cfg.hidden), flat),
        "b3": jnp.zeros((cfg.hidden,), cfg.dtype),
        "head": he(k4, (cfg.hidden, cfg.classes), cfg.hidden),
        "b4": jnp.zeros((cfg.classes,), cfg.dtype),
    }


def _conv(x: jax.Array, w: jax.Array) -> jax.Array:
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _pool(x: jax.Array) -> jax.Array:
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def convnet_forward(params: Params, images: jax.Array, cfg: ConvNetConfig) -> jax.Array:
    """images [B, H, W, C] -> logits [B, classes] (fp32)."""
    x = images.astype(cfg.dtype)
    x = _pool(jax.nn.relu(_conv(x, params["conv1"]) + params["b1"]))
    x = _pool(jax.nn.relu(_conv(x, params["conv2"]) + params["b2"]))
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense"] + params["b3"])
    return (x @ params["head"] + params["b4"]).astype(jnp.float32)


def convnet_loss(
    params: Params, batch: Tuple[jax.Array, jax.Array], cfg: ConvNetConfig
) -> jax.Array:
    images, labels = batch
    logits = convnet_forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def accuracy(params: Params, images, labels, cfg: ConvNetConfig) -> float:
    logits = convnet_forward(params, jnp.asarray(images), cfg)
    return float((jnp.argmax(logits, -1) == jnp.asarray(labels)).mean())


class SyntheticDigits:
    """MNIST-shaped synthetic data with REAL learnable structure: each
    class k is a fixed random template + noise, so a correct training
    loop must converge to high accuracy while a broken one stays at
    chance. No dataset download (zero-egress environments)."""

    def __init__(self, cfg: ConvNetConfig, batch: int, seed: int = 0,
                 noise: float = 0.3, template_seed: int = 1234) -> None:
        self.cfg = cfg
        self.batch = batch
        self.noise = noise
        # templates are the TASK (fixed across train/eval splits);
        # ``seed`` only drives the sampling/noise stream
        key = jax.random.PRNGKey(template_seed)
        self.templates = jax.random.uniform(
            key, (cfg.classes, cfg.image_size, cfg.image_size, cfg.channels)
        )
        self._key = jax.random.PRNGKey(seed + 1)

        @jax.jit
        def sample(key):
            key, k1, k2 = jax.random.split(key, 3)
            labels = jax.random.randint(k1, (batch,), 0, cfg.classes)
            images = self.templates[labels]
            images = images + self.noise * jax.random.normal(
                k2, images.shape
            )
            return key, images, labels

        self._sample = sample

    def __iter__(self) -> Iterator[Tuple[jax.Array, jax.Array]]:
        return self

    def __next__(self):
        self._key, images, labels = self._sample(self._key)
        return images, labels


def fit(
    cfg: ConvNetConfig,
    data: Iterator,
    steps: int = 100,
    learning_rate: float = 1e-3,
    seed: int = 0,
    params: Optional[Params] = None,
) -> Tuple[Params, Dict[str, float]]:
    """Minimal adam loop, one jitted donated step (the example-workload
    trainer; the LM families use training.Trainer)."""
    import optax

    tx = optax.adam(learning_rate)
    params = params or convnet_init(jax.random.PRNGKey(seed), cfg)
    state = {"params": params, "opt": tx.init(params)}

    @jax.jit
    def step(state, batch):
        loss, grads = jax.value_and_grad(convnet_loss)(
            state["params"], batch, cfg
        )
        updates, opt = tx.update(grads, state["opt"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt": opt,
        }, loss

    first = last = None
    for i in range(steps):
        state, loss = step(state, next(data))
        if i == 0:
            first = float(loss)
    last = float(loss)
    return state["params"], {"first_loss": first, "final_loss": last,
                             "steps": steps}

"""Mixture-of-Experts transformer: expert parallelism over an "expert" axis.

TPU-idiomatic MoE (net-new vs the reference, which has no in-process
parallelism — SURVEY.md §2.5): switch-style top-1 routing with *dense
one-hot dispatch*. Instead of data-dependent gather/scatter (dynamic shapes
XLA can't tile), token->expert assignment becomes two einsums against a
one-hot dispatch tensor — static shapes, MXU-friendly, and when expert
weights are sharded P("expert", ...) XLA lowers the dispatch/combine
einsums to all-to-all/psum collectives over the expert axis on its own.
Capacity-factor truncation keeps per-expert work static; an auxiliary
load-balancing loss (Switch Transformer form) keeps routing uniform.

Reuses the Llama building blocks (rmsnorm/rope/attention) so the attention
path stays identical to the flagship model.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubedl_tpu.models.llama import (
    apply_rope,
    attention,
    next_token_nll,
    rmsnorm,
    rope_table,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32768
    dim: int = 1024
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 8
    n_experts: int = 8
    ffn_dim: int = 2048
    max_seq: int = 2048
    #: per-expert token capacity = capacity_factor * tokens / n_experts
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: see llama.remat_policy_for — "dots_flash" keeps the flash kernel's
    #: residuals saved so backward never re-runs the forward kernel
    remat_policy: str = "dots_flash"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        hd = self.head_dim
        per_layer = (
            self.dim * (self.n_heads * hd)
            + 2 * self.dim * (self.n_kv_heads * hd)
            + (self.n_heads * hd) * self.dim
            + self.dim * self.n_experts  # router
            + 2 * self.n_experts * self.dim * self.ffn_dim  # w_in, w_out
            + 2 * self.dim  # norms
        )
        return (
            self.vocab_size * self.dim  # embed
            + self.n_layers * per_layer
            + self.dim  # final norm
            + self.dim * self.vocab_size  # lm_head
        )

    def flops_per_token(self) -> float:
        """Training FLOPs/token ~= 6 * activated params (top-1 routing
        activates one expert of n_experts per token)."""
        hd = self.head_dim
        per_layer_active = (
            self.dim * (self.n_heads * hd)
            + 2 * self.dim * (self.n_kv_heads * hd)
            + (self.n_heads * hd) * self.dim
            + self.dim * self.n_experts
            + 2 * self.dim * self.ffn_dim  # one expert's w_in + w_out
        )
        active = (
            self.vocab_size * self.dim
            + self.n_layers * per_layer_active
            + self.dim * self.vocab_size
        )
        return 6.0 * active


TINY_MOE = MoEConfig(
    vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4, n_experts=4,
    ffn_dim=128, max_seq=128, dtype=jnp.float32, remat=False,
)

#: bench-scale MoE that fits one v5e chip with a real batch
BENCH_MOE = MoEConfig(
    vocab_size=32768, dim=1024, n_layers=12, n_heads=16, n_kv_heads=8,
    n_experts=8, ffn_dim=2048, max_seq=2048,
)


def preset(name: str) -> MoEConfig:
    return {"tiny-moe": TINY_MOE, "bench-moe": BENCH_MOE}[name]


def moe_init(key: jax.Array, cfg: MoEConfig) -> Params:
    hd = cfg.head_dim
    k = iter(jax.random.split(key, 12))

    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)
        ).astype(cfg.dtype)

    L, D, F, E, V = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts, cfg.vocab_size
    return {
        "embed": dense(next(k), (V, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), cfg.dtype),
            "wq": dense(next(k), (L, D, cfg.n_heads * hd), D),
            "wk": dense(next(k), (L, D, cfg.n_kv_heads * hd), D),
            "wv": dense(next(k), (L, D, cfg.n_kv_heads * hd), D),
            "wo": dense(next(k), (L, cfg.n_heads * hd, D), cfg.n_heads * hd),
            "mlp_norm": jnp.ones((L, D), cfg.dtype),
            "router": dense(next(k), (L, D, E), D),
            "w_in": dense(next(k), (L, E, D, F), D),
            "w_out": dense(next(k), (L, E, F, D), F),
        },
        "final_norm": jnp.ones((D,), cfg.dtype),
        "lm_head": dense(next(k), (D, V), D),
    }


def param_pspecs(cfg: MoEConfig) -> Params:
    """Expert weights shard over the "expert" axis; dense weights over fsdp/
    tensor as in the Llama rules."""
    return {
        "embed": P("tensor", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tensor"),
            "wk": P(None, "fsdp", "tensor"),
            "wv": P(None, "fsdp", "tensor"),
            "wo": P(None, "tensor", "fsdp"),
            "mlp_norm": P(None, None),
            "router": P(None, "fsdp", None),
            "w_in": P(None, "expert", "fsdp", "tensor"),
            "w_out": P(None, "expert", "tensor", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tensor"),
    }


def moe_ffn(
    x: jax.Array,  # [B, S, D]
    router_w: jax.Array,  # [D, E] (always the FULL expert count)
    w_in: jax.Array,  # [E(, local), D, F(, local)]
    w_out: jax.Array,  # [E(, local), F(, local), D]
    cfg: MoEConfig,
    ep_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 switch layer with dense dispatch. Returns (out, aux_loss).

    Two execution modes, same math:
    - global arrays under pjit (default): expert sharding P("expert", ...)
      makes XLA lower the dispatch/combine einsums to collectives;
    - inside a shard_map (the GPipe stage body): ``ep_axis`` names the
      expert mesh axis — routing runs on the full E, each device computes
      its LOCAL slice of experts and a psum combines; ``tp_axis`` splits
      every expert's ffn_dim (column-parallel w_in, row-parallel w_out
      + psum). This is what lets MoE compose with pipeline parallelism.
    """
    B, S, D = x.shape
    E = router_w.shape[-1]
    T = B * S
    cap = max(1, int(cfg.capacity_factor * T / E))
    xt = x.reshape(T, D)

    logits = (xt @ router_w).astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = probs.max(axis=-1)  # [T]
    choice = probs.argmax(axis=-1)  # [T]
    onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)  # [T, E]

    # position of each token within its expert's queue; beyond-capacity
    # tokens are dropped (contribute zero — residual carries them)
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
    keep = (pos_in_expert < cap) & (onehot > 0)
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), cap, dtype=jnp.float32)
    dispatch = jnp.where(keep[..., None], slot, 0.0)  # [T, E, cap]
    combine = dispatch * gate[:, None, None]  # weight by router prob

    if ep_axis is not None:
        # expert-parallel inside shard_map: slice THIS device's experts out
        # of the (replicated) dispatch/combine tensors
        ei = lax.axis_index(ep_axis)
        e_local = w_in.shape[0]
        dispatch = lax.dynamic_slice_in_dim(dispatch, ei * e_local, e_local, axis=1)
        combine = lax.dynamic_slice_in_dim(combine, ei * e_local, e_local, axis=1)

    # dispatch -> per-expert batches, expert matmuls, combine (einsum-only)
    xe = jnp.einsum("td,tec->ecd", xt.astype(jnp.float32), dispatch).astype(
        cfg.dtype
    )  # [E_local, cap, D]
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, w_in).astype(jnp.float32))
    ye = jnp.einsum("ecf,efd->ecd", h.astype(cfg.dtype), w_out)  # [E_local, cap, D]
    yt = jnp.einsum("ecd,tec->td", ye.astype(jnp.float32), combine)
    if ep_axis is not None:
        yt = lax.psum(yt, ep_axis)  # sum over expert shards
    if tp_axis is not None:
        yt = lax.psum(yt, tp_axis)  # row-parallel w_out partial sums

    # Switch load-balancing loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return yt.reshape(B, S, D).astype(x.dtype), aux


def _block(x, lp, cfg: MoEConfig, cos, sin, attn_fn=None,
           tp_axis: Optional[str] = None, ep_axis: Optional[str] = None):
    B, S, D = x.shape
    hd = cfg.head_dim
    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    n_heads = lp["wq"].shape[-1] // hd  # local under tensor split
    n_kv = lp["wk"].shape[-1] // hd
    q = (h @ lp["wq"]).reshape(B, S, n_heads, hd)
    k = (h @ lp["wk"]).reshape(B, S, n_kv, hd)
    v = (h @ lp["wv"]).reshape(B, S, n_kv, hd)
    if getattr(attn_fn, "fused_rope", False):
        # rotary fused into the pallas kernel — see models.llama._block
        attn = attn_fn(q, k, v, rope_cos=cos, rope_sin=sin)
    else:
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = (attn_fn or attention)(q, k, v)
    attn = attn.reshape(B, S, n_heads * hd)
    attn_out = attn @ lp["wo"]
    if tp_axis:
        attn_out = lax.psum(attn_out, tp_axis)
    x = x + attn_out
    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    ffn, aux = moe_ffn(h, lp["router"], lp["w_in"], lp["w_out"], cfg,
                       ep_axis=ep_axis, tp_axis=tp_axis)
    return x + ffn, aux


def moe_forward(
    params: Params, tokens: jax.Array, cfg: MoEConfig, attn_fn=None
) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] -> (logits [B, S, V] fp32, mean aux loss). ``attn_fn``
    swaps the attention impl (flash kernel / ring attention), exactly as in
    llama_forward."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    cos, sin = rope_table(cfg.head_dim, cfg.rope_theta, S)

    def body(carry, lp):
        x = carry
        x, aux = _block(x, lp, cfg, cos, sin, attn_fn)
        return x, aux

    if cfg.remat:
        from kubedl_tpu.models.llama import remat_policy_for

        body = jax.checkpoint(body, policy=remat_policy_for(cfg.remat_policy))
    x, auxes = lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, auxes.mean()


def moe_loss(
    params: Params, tokens: jax.Array, cfg: MoEConfig, attn_fn=None
) -> jax.Array:
    logits, aux = moe_forward(params, tokens, cfg, attn_fn)
    return next_token_nll(logits, tokens) + cfg.aux_loss_weight * aux


def pipeline_hooks(cfg: MoEConfig):
    """GPipe adapter (VERDICT r2 #5: 'MoE can never pipe'): the stage body
    scans this stage's layers, accumulating the switch aux loss, with
    optional expert (ep_axis) and tensor (tp_axis) parallelism inside the
    shard_map via `moe_ffn`'s sliced-dispatch path."""
    from kubedl_tpu.parallel.pipeline import PipelineHooks

    def embed(params, tokens):
        return params["embed"][tokens].astype(cfg.dtype)

    def make_stage(attn_fn, cos, sin, tp_axis=None, ep_axis=None):
        def stage_fn(layer_params, x):
            def body(carry, lp):
                x, aux = _block(carry, lp, cfg, cos, sin, attn_fn,
                                tp_axis=tp_axis, ep_axis=ep_axis)
                return x, aux

            if cfg.remat:
                from kubedl_tpu.models.llama import remat_policy_for

                body = jax.checkpoint(
                    body, policy=remat_policy_for(cfg.remat_policy)
                )
            x, auxes = lax.scan(body, x, layer_params)
            return x, auxes.sum().astype(jnp.float32)

        return stage_fn

    def head_loss(params, h, tokens, aux_mean):
        h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        return next_token_nll(logits, tokens) + cfg.aux_loss_weight * aux_mean

    return PipelineHooks(
        embed=embed,
        rope=lambda S: rope_table(cfg.head_dim, cfg.rope_theta, S),
        make_stage=make_stage,
        head_loss=head_loss,
        n_layers=cfg.n_layers,
    )

"""HTTP client against a ConsoleServer (reference: external Go consumers
of the generated clientset; here the console REST API is the wire
protocol, console/backend/pkg/routers/api/job.go:29-43)."""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from kubedl_tpu import chaos
from kubedl_tpu.api import codec
from kubedl_tpu.client.base import ApiException, BaseClient


class KubeDLClient(BaseClient):
    def __init__(self, base_url: str, token: str = "", timeout: float = 30.0) -> None:
        super().__init__()
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    # -- transport ---------------------------------------------------------

    def _call_once(self, method: str, path: str, body: Optional[dict] = None) -> Any:
        chaos.check("client.http")
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
                msg = payload.get("data", str(payload))
            except Exception:
                msg = str(e)
            raise ApiException(e.code, str(msg)) from None
        return payload.get("data", payload)

    def _call(self, method: str, path: str, body: Optional[dict] = None) -> Any:
        """Transport with the shared retry policy: transient failures (5xx,
        connection refused mid-restart, injected chaos) retry with jittered
        backoff; 4xx API errors are permanent and surface immediately."""
        policy = chaos.RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=0.5)
        return policy.call(
            lambda: self._call_once(method, path, body),
            retry_on=(ApiException, urllib.error.URLError, chaos.FaultInjected),
            giveup=lambda e: isinstance(e, ApiException) and e.status < 500,
        )

    def login(self, username: str, password: str) -> str:
        """Session login; stores and returns the bearer token."""
        data = self._call(
            "POST", "/api/v1/login",
            {"username": username, "password": password},
        )
        self.token = data.get("token", "")
        return self.token

    # -- BaseClient verbs --------------------------------------------------

    def submit(self, job) -> Dict[str, Any]:
        return self._call("POST", "/api/v1/job/submit", codec.encode(job))

    def get_job(self, kind: str, name: str, namespace: str = "default"):
        data = self._call(
            "GET", f"/api/v1/job/json/{namespace}/{name}?kind={kind}"
        )
        return codec.decode_object(data)

    def list_jobs(self, kind: str = "", namespace: str = "default") -> List:
        q = urllib.parse.urlencode(
            {k: v for k, v in (("kind", kind), ("namespace", namespace)) if v}
        )
        data = self._call("GET", f"/api/v1/job/list?{q}")
        out = []
        for row in data.get("jobInfos", []):
            try:
                out.append(
                    self.get_job(row["kind"], row["name"], row["namespace"])
                )
            except ApiException:
                pass  # raced a deletion between list and get
        return out

    def stop_job(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("POST", f"/api/v1/job/stop/{namespace}/{name}?kind={kind}")

    def delete_job(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/api/v1/job/delete/{namespace}/{name}?kind={kind}")

    def job_logs(self, pod: str, namespace: str = "default") -> List[str]:
        data = self._call("GET", f"/api/v1/log/logs/{namespace}/{pod}")
        return data.get("logs", [])

    def job_events(self, kind: str, name: str, namespace: str = "default") -> List[dict]:
        data = self._call("GET", f"/api/v1/event/events/{namespace}/{kind}/{name}")
        return data.get("events", data) if isinstance(data, dict) else data

    def overview(self) -> Dict[str, Any]:
        return self._call("GET", "/api/v1/data/overview")

    def statistics(self) -> Dict[str, Any]:
        return self._call("GET", "/api/v1/job/statistics")

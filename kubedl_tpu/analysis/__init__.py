"""Project-specific static analysis + runtime lock witness.

``python -m kubedl_tpu.analysis`` runs the lint engine (rule catalog in
docs/static-analysis.md); :mod:`kubedl_tpu.analysis.lockwitness` provides
the KUBEDL_LOCKWITNESS=1 runtime lock-order witness tier-1 runs under.
"""

from kubedl_tpu.analysis.engine import (  # noqa: F401
    Finding,
    analyze,
    analyze_file,
    apply_baseline,
    load_baseline,
    run,
    write_baseline,
)

import sys

from kubedl_tpu.analysis.engine import run

if __name__ == "__main__":
    sys.exit(run())

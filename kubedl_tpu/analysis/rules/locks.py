"""KTL002 — blocking call lexically inside a lock-held body.

Historical bug pinned: speculative-decode draft proposal ran a full model
forward under the engine cv (fixed by the PR 11 ``_spec_tick`` refactor:
snapshot under the lock, propose outside, recheck slot identity on
re-acquire). The same shape — HTTP requests, ``block_until_ready``,
device ``np.array(...)`` harvests, ``time.sleep``, subprocess waits —
inside ``with self._cv:`` / ``with self._lock:`` stalls every other
thread that needs the lock for the duration of device/network latency.

Lexical scope only: calls inside nested ``def``/``lambda`` are deferred
work, not executed under the lock. ``cv.wait()`` is exempt (it releases
the subject lock by design). ``np.array`` under a lock is flagged because
the device-harvest variant blocks on the device stream; host-side uses
are accepted via pragma or baseline (each carries a justification).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

RULE_ID = "KTL002"

#: ``with <expr>:`` subjects that look like locks/conditions
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|cv|cond|mu|mutex)(_|$)|lock$|_cv$")

#: receiver names that mark ``.wait()``/``.communicate()`` as subprocess
_PROC_NAME_RE = re.compile(r"proc|popen|child|pipe", re.I)


def _expr_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_lock_subject(node: ast.AST) -> bool:
    name = _expr_name(node)
    return bool(name and _LOCK_NAME_RE.search(name))


def _blocking_reason(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = _expr_name(f.value)
        attr = f.attr
        if attr == "sleep" and recv == "time":
            return "time.sleep under a held lock"
        if attr == "block_until_ready":
            return "block_until_ready (device sync) under a held lock"
        if attr == "device_get":
            return "device_get (device->host copy) under a held lock"
        if attr == "array" and recv in ("np", "numpy"):
            return ("np.array harvest under a held lock (blocks on the "
                    "device stream when the source is a device buffer)")
        if recv == "requests" and attr in ("get", "post", "put", "request"):
            return f"requests.{attr} (network) under a held lock"
        if attr == "urlopen":
            return "urlopen (network) under a held lock"
        if recv == "subprocess" and attr in (
            "run", "call", "check_call", "check_output"
        ):
            return f"subprocess.{attr} under a held lock"
        if attr in ("wait", "communicate") and recv \
                and _PROC_NAME_RE.search(recv) \
                and not _LOCK_NAME_RE.search(recv):
            return f"subprocess {attr}() under a held lock"
    elif isinstance(f, ast.Name):
        if f.id == "urlopen":
            return "urlopen (network) under a held lock"
        if f.id == "sleep":
            return "sleep under a held lock"
    return None


class _BodyScanner(ast.NodeVisitor):
    """Scan a lock-held body; stop at nested function boundaries."""

    def __init__(self, ctx, subject: str) -> None:
        self.ctx = ctx
        self.subject = subject
        self.findings: List = []

    def visit_FunctionDef(self, node) -> None:
        return  # deferred execution: not under the lock

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        reason = _blocking_reason(node)
        if reason:
            self.findings.append(self.ctx.finding(
                RULE_ID, node,
                f"{reason} (with {self.subject}: opened at an enclosing "
                f"line) — move the blocking work outside the critical "
                f"section (_spec_tick pattern: snapshot, work, recheck)",
            ))
        self.generic_visit(node)


class _WithFinder(ast.NodeVisitor):
    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self.findings: List = []
        self._in_lock: List[str] = []

    def visit_With(self, node: ast.With) -> None:
        subjects = [
            item.context_expr for item in node.items
            if _is_lock_subject(item.context_expr)
        ]
        if not subjects:
            self.generic_visit(node)
            return
        name = _expr_name(subjects[0]) or "lock"
        scanner = _BodyScanner(self.ctx, f"self.{name}"
                               if isinstance(subjects[0], ast.Attribute)
                               else name)
        for stmt in node.body:
            scanner.visit(stmt)
        self.findings.extend(scanner.findings)
        # nested withs inside the body were already visited by scanner's
        # generic walk; do not recurse again
        return


def check_file(ctx) -> List:
    finder = _WithFinder(ctx)
    finder.visit(ctx.tree)
    # dedupe: nested lock withs can scan the same call twice
    seen = set()
    out = []
    for f in finder.findings:
        key = (f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out

"""KTL006 — rendered-schema drift (the "forgot to re-render" class).

PRs 3, 9, and 12 each changed API dataclasses; the committed
``deploy/rendered/schemas/*.json`` artifacts lag unless someone remembers
``make render-deploy``. This rule regenerates the schemas in memory
(``kubedl_tpu.api.schema.workload_schemas`` — reflection only, no JAX)
and requires the committed files to be byte-identical, exactly what
``deploy/render.py`` would write.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List

from kubedl_tpu.analysis.engine import Finding

RULE_ID = "KTL006"

SCHEMA_DIR = "deploy/rendered/schemas"


def check_project(root: Path, contexts) -> List[Finding]:
    schema_dir = root / SCHEMA_DIR
    if not schema_dir.exists():
        return []  # not a full checkout (fixture runs)
    try:
        from kubedl_tpu.api.schema import workload_schemas

        expected = {
            kind: json.dumps(schema, indent=2) + "\n"
            for kind, schema in workload_schemas().items()
        }
    except Exception as e:  # schema generation itself broke
        return [Finding(
            RULE_ID, "kubedl_tpu/api/schema.py", 1,
            f"workload_schemas() failed: {type(e).__name__}: {e}",
            snippet="schema-generation-failed",
        )]
    findings: List[Finding] = []
    committed = {p.stem: p for p in sorted(schema_dir.glob("*.json"))}
    for kind, body in sorted(expected.items()):
        p = committed.get(kind)
        if p is None:
            findings.append(Finding(
                RULE_ID, f"{SCHEMA_DIR}/{kind}.json", 1,
                f"schema for kind {kind} not committed — run "
                f"`make render-deploy`",
                snippet=f"schema-missing:{kind}",
            ))
        elif p.read_text() != body:
            findings.append(Finding(
                RULE_ID, f"{SCHEMA_DIR}/{kind}.json", 1,
                f"committed schema for kind {kind} differs from the API "
                f"dataclasses — run `make render-deploy`",
                snippet=f"schema-drift:{kind}",
            ))
    for kind in sorted(set(committed) - set(expected)):
        findings.append(Finding(
            RULE_ID, f"{SCHEMA_DIR}/{kind}.json", 1,
            f"committed schema for unknown kind {kind} (removed from the "
            f"API?) — delete it or re-register the kind",
            snippet=f"schema-orphan:{kind}",
        ))
    return findings

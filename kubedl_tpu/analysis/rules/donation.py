"""KTL001 — donation aliasing.

Historical bugs pinned: PR 6 (checkpoint restore leaves zero-copied from
aligned host arrays, donated on the first step, heap recycled under live
weights) and PR 8 (``jnp.asarray`` borrowing the numpy ``self._bt_host``
/ ``self._pos_host`` mirrors while the donated cache let XLA alias
segment outputs onto them). Canonical fix: ``serving/server.py``
``_upload_mirror`` — ``jnp.asarray(arr) + 0`` forces an XLA-owned buffer.

What makes a borrow dangerous is *persistence*: ``jnp.asarray`` of a
local list copies, and a borrow of a transient array nobody mutates is
harmless. The rule therefore flags, per file (given at least one
``jit(..., donate_argnums=...)``):

1. a borrow of a **self attribute** (``jnp.asarray(self._bt_host)`` /
   ``np.frombuffer(self._buf)`` — a host mirror that outlives the call)
   passed at ANY argument of a donated call without a defensive copy
   (``+ 0``, ``jnp.copy``, ``np.array``);
2. ANY borrow passed at a **donated position** (donation frees XLA to
   recycle the borrowed numpy heap under live data — the PR 6 restore
   shape);
3. ANY borrow stored into a **donated-cache attribute** (an attribute
   that is itself passed at a donated position somewhere in the file).

Taint propagates through simple local assignment and is cleared by the
defensive copies above.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

RULE_ID = "KTL001"

_BORROW_FUNCS = {"asarray", "frombuffer"}
_COPY_FUNCS = {"copy", "array", "deepcopy"}

#: taint levels
_BORROW = 1          # borrow of a transient value
_MIRROR_BORROW = 2   # borrow of a persistent self attribute


def _call_attr(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_self_attr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _attr_key(node: ast.AST) -> Optional[str]:
    if _is_self_attr(node):
        return node.attr
    return None


def _is_jit_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _call_attr(node) == "jit"
        and any(kw.arg == "donate_argnums" for kw in node.keywords)
    )


def _donated_positions(node: ast.Call) -> Optional[Set[int]]:
    """Parse donate_argnums=(1,) -> {1}; None when not statically known."""
    for kw in node.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.add(elt.value)
                else:
                    return None
            return out
    return None


def _callee_key(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


class _DonationIndex(ast.NodeVisitor):
    """Pass 1: donated callables (name -> donated positions, None=any)
    plus donated-attribute names (attrs passed at donated positions)."""

    def __init__(self) -> None:
        self.donated_fns: Dict[str, Optional[Set[int]]] = {}
        self.donated_attrs: Set[str] = set()
        self._calls: List[ast.Call] = []

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_jit_call(node.value):
            pos = _donated_positions(node.value)
            for t in node.targets:
                key = _attr_key(t) or (t.id if isinstance(t, ast.Name) else None)
                if key:
                    self.donated_fns[key] = pos
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._calls.append(node)
        self.generic_visit(node)

    def _positions_for(self, call: ast.Call) -> Optional[Set[int]]:
        """Donated positions for a call, or None if the call isn't donated
        (note: a donated call with unparseable argnums returns set())."""
        key = _callee_key(call)
        if key is not None and key in self.donated_fns:
            return self.donated_fns[key] or set()
        if _is_jit_call(call.func):
            return _donated_positions(call.func) or set()
        return None

    def finish(self) -> None:
        for call in self._calls:
            pos = self._positions_for(call)
            if pos is None:
                continue
            for i, arg in enumerate(call.args):
                if i in pos:
                    attr = _attr_key(arg)
                    if attr:
                        self.donated_attrs.add(attr)


class _TaintChecker(ast.NodeVisitor):
    """Pass 2: per-function borrow-taint propagation + flagging."""

    def __init__(self, ctx, index: _DonationIndex) -> None:
        self.ctx = ctx
        self.index = index
        self.findings: List = []
        self._tainted: List[Dict[str, int]] = [{}]

    def visit_FunctionDef(self, node) -> None:
        self._tainted.append({})
        self.generic_visit(node)
        self._tainted.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _taint_of(self, node: ast.AST) -> int:
        """0 = clean, _BORROW, or _MIRROR_BORROW."""
        if isinstance(node, ast.Call) and _call_attr(node) in _BORROW_FUNCS:
            if node.args and (
                _is_self_attr(node.args[0])
                or self._taint_of(node.args[0]) >= _MIRROR_BORROW
            ):
                return _MIRROR_BORROW
            return _BORROW
        if isinstance(node, ast.Name):
            return self._tainted[-1].get(node.id, 0)
        return 0

    def _is_defensive(self, node: ast.AST) -> bool:
        if isinstance(node, ast.BinOp):
            return True  # asarray(x) + 0 and friends materialize
        if isinstance(node, ast.Call) and _call_attr(node) in _COPY_FUNCS:
            return True
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        value = node.value
        taint = 0 if self._is_defensive(value) else self._taint_of(value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                if taint:
                    self._tainted[-1][t.id] = taint
                else:
                    self._tainted[-1].pop(t.id, None)
            else:
                attr = _attr_key(t)
                if attr and taint and attr in self.index.donated_attrs:
                    self.findings.append(self.ctx.finding(
                        RULE_ID, node,
                        f"borrowed buffer stored into donated attribute "
                        f"self.{attr} without a defensive copy "
                        f"(jnp.copy / np.array / `+ 0`): donation lets XLA "
                        f"recycle the borrowed host memory under live data",
                    ))

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        pos = self.index._positions_for(node)
        if pos is None:
            return
        key = _callee_key(node) or "jit(...)"
        for i, arg in enumerate(node.args):
            if self._is_defensive(arg):
                continue
            taint = self._taint_of(arg)
            if taint >= _MIRROR_BORROW:
                self.findings.append(self.ctx.finding(
                    RULE_ID, arg,
                    f"borrow of a persistent host mirror "
                    f"(jnp.asarray/np.frombuffer of a self attribute) "
                    f"passed to donated call {key}() at arg {i} without a "
                    f"defensive copy — the PR 8 aliasing bug shape "
                    f"(see serving/server.py _upload_mirror)",
                ))
            elif taint and i in pos:
                self.findings.append(self.ctx.finding(
                    RULE_ID, arg,
                    f"borrowed buffer donated at arg {i} of {key}() "
                    f"without a defensive copy — donation recycles the "
                    f"borrowed numpy heap (the PR 6 restore bug shape)",
                ))


def check_file(ctx) -> List:
    index = _DonationIndex()
    index.visit(ctx.tree)
    index.finish()
    if not index.donated_fns:
        return []
    checker = _TaintChecker(ctx, index)
    checker.visit(ctx.tree)
    return checker.findings

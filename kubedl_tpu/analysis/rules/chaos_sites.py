"""KTL004 — chaos-site drift.

Generalizes the PR 6 doc-drift test from "docstring table matches the
registry" to a machine check across all three surfaces:

1. every string literal at a ``chaos.check(<site>)`` /
   ``chaos.should_fail(<site>)`` call site must exist in
   ``chaos.plan.SITES`` (parsed statically — the rule never imports
   production code);
2. every registered site must be consulted somewhere (dead registry
   rows rot into false documentation);
3. every registered site must have a row in the docs/robustness.md
   failure-modes table (the `| Site | ... |` table), so the operator
   runbook can never silently lag the wired surface.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set, Tuple

from kubedl_tpu.analysis.engine import Finding

RULE_ID = "KTL004"

PLAN_PATH = "kubedl_tpu/chaos/plan.py"
DOC_PATH = "docs/robustness.md"


def _registry_sites(root: Path) -> Tuple[Set[str], int]:
    """Parse the SITES dict literal out of chaos/plan.py."""
    plan = root / PLAN_PATH
    if not plan.exists():
        return set(), 0
    tree = ast.parse(plan.read_text())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "SITES" \
                    and isinstance(node.value, ast.Dict):
                keys = {
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
                return keys, node.lineno
    return set(), 0


def _call_sites(contexts) -> Dict[str, List[Tuple[str, int]]]:
    """site -> [(relpath, line)] for every chaos.check/should_fail literal."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("check", "should_fail")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "chaos"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                out.setdefault(site, []).append((ctx.relpath, node.lineno))
    return out


def _doc_table_sites(root: Path) -> Set[str]:
    doc = root / DOC_PATH
    if not doc.exists():
        return set()
    sites: Set[str] = set()
    in_table = False
    for line in doc.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("|") and "Site" in stripped \
                and "Layer" in stripped:
            in_table = True
            continue
        if in_table:
            if not stripped.startswith("|"):
                break
            first_col = stripped.strip("|").split("|")[0]
            for tok in re.findall(r"`([^`]+)`", first_col):
                sites.add(tok.strip())
    return sites


def check_project(root: Path, contexts) -> List[Finding]:
    registered, reg_line = _registry_sites(root)
    if not registered:
        return [Finding(RULE_ID, PLAN_PATH, 1,
                        "could not parse chaos.plan.SITES registry")]
    consulted = _call_sites(contexts)
    documented = _doc_table_sites(root)
    findings: List[Finding] = []
    for site, where in sorted(consulted.items()):
        if site not in registered:
            path, line = where[0]
            findings.append(Finding(
                RULE_ID, path, line,
                f"chaos site '{site}' consulted here but missing from "
                f"chaos.plan.SITES — register it first",
                snippet=f"chaos-site:{site}",
            ))
    for site in sorted(registered - set(consulted)):
        findings.append(Finding(
            RULE_ID, PLAN_PATH, reg_line,
            f"chaos site '{site}' registered but consulted nowhere "
            f"(dead registry row)",
            snippet=f"dead-site:{site}",
        ))
    for site in sorted(registered - documented):
        findings.append(Finding(
            RULE_ID, DOC_PATH, 1,
            f"chaos site '{site}' has no row in the {DOC_PATH} "
            f"failure-modes table (| Site | Layer | ... |)",
            snippet=f"undocumented-site:{site}",
        ))
    return findings

"""KTL008 — PS chaos sites without seeded test coverage.

The parameter-service tier is the one place where an unexercised fault
path silently costs training progress instead of a request: a ``ps.push``
drop that nobody has ever injected under a seed means the bounded-
staleness retry contract is folklore, not a pinned behavior. This rule
makes the coverage drift-proof the same way KTL004 made the site registry
drift-proof — by literal cross-reference, never by importing production
code:

1. collect every string literal at a ``chaos.check(<site>)`` /
   ``chaos.should_fail(<site>)`` call under ``kubedl_tpu/ps/``;
2. require each such site to appear as a string literal somewhere in
   ``tests/test_ps.py`` (a seeded FaultPlan case arms sites by literal,
   so a missing literal IS a missing case);
3. a consulted PS site with no test file at all is the degenerate form of
   the same finding.

The reverse direction (sites named in the test but wired nowhere) is
already covered by KTL004's dead-registry check, so it is not repeated
here.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Set, Tuple

from kubedl_tpu.analysis.engine import Finding

RULE_ID = "KTL008"

PS_PREFIX = "kubedl_tpu/ps/"
TEST_PATH = "tests/test_ps.py"


def _ps_call_sites(contexts) -> Dict[str, List[Tuple[str, int]]]:
    """site -> [(relpath, line)] for chaos literals under kubedl_tpu/ps/."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        if not ctx.relpath.startswith(PS_PREFIX):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("check", "should_fail")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "chaos"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                out.setdefault(site, []).append((ctx.relpath, node.lineno))
    return out


def _test_literals(root: Path) -> Set[str]:
    """Every string constant in tests/test_ps.py (a seeded case arms its
    site by literal, so presence-of-literal == presence-of-case)."""
    test = root / TEST_PATH
    if not test.exists():
        return set()
    try:
        tree = ast.parse(test.read_text())
    except SyntaxError:
        return set()
    return {
        node.value
        for node in ast.walk(tree)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }


def check_project(root: Path, contexts) -> List[Finding]:
    consulted = _ps_call_sites(contexts)
    if not consulted:
        return []
    covered = _test_literals(root)
    findings: List[Finding] = []
    if not (root / TEST_PATH).exists():
        path, line = sorted(consulted.values())[0][0]
        return [Finding(
            RULE_ID, path, line,
            f"PS tier consults chaos sites but {TEST_PATH} does not exist "
            f"— every ps.* injection site needs a seeded case there",
            snippet="missing-test-file",
        )]
    for site, where in sorted(consulted.items()):
        if site not in covered:
            path, line = where[0]
            findings.append(Finding(
                RULE_ID, path, line,
                f"chaos site '{site}' is consulted in the PS tier but has "
                f"no seeded case in {TEST_PATH} (no string literal "
                f"'{site}' found there)",
                snippet=f"uncovered-ps-site:{site}",
            ))
    return findings

"""KTL009 — unsharded store construction.

PR 18 split the control plane into reconcile-domain shards behind one
client-facing surface (:class:`kubedl_tpu.shards.store.ShardedObjectStore`).
The failure mode this rule pins: a controller (or a future subsystem)
quietly building its own bare ``ObjectStore`` — its objects then live
outside every shard map, skip the per-shard WAL/lease fencing, and its
watches never reach the sharded fan-out, which is exactly the
split-brain-by-construction bug the fencing discipline exists to prevent.

All object access must go through the sharded client API. Direct
``ObjectStore(...)`` construction is allowed only in:

- ``kubedl_tpu/shards/`` (the facade owns its shard-local stores), and
- blessed entry points with their OWN partitioning/fencing discipline
  (the parameter service mirrors PS-shard state in a private store).

Everything else must take a store as a dependency or build a
``ShardedObjectStore`` (``shards=1`` is behaviorally identical to the
old bare store).
"""

from __future__ import annotations

import ast
from typing import List

RULE_ID = "KTL009"

#: directories whose files may construct shard-local stores directly
ALLOWED_PREFIXES = ("kubedl_tpu/shards/",)

#: entry points with their own partitioning/fencing discipline
BLESSED_FILES = {
    # PS service keeps a private mirror store per PS shard (PR 15's
    # lease-fenced discipline — the pattern this rule generalizes)
    "kubedl_tpu/ps/service.py",
}


def _constructs_object_store(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "ObjectStore"
    if isinstance(f, ast.Attribute):
        return f.attr == "ObjectStore"
    return False


def check_file(ctx) -> List["Finding"]:  # noqa: F821 — engine's Finding
    if ctx.relpath.startswith(ALLOWED_PREFIXES) \
            or ctx.relpath in BLESSED_FILES:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _constructs_object_store(node):
            findings.append(ctx.finding(
                RULE_ID, node.lineno,
                "direct ObjectStore construction outside kubedl_tpu/shards/ "
                "— objects built here bypass the shard map, per-shard "
                "WAL/lease fencing, and sharded watch fan-out; take a store "
                "as a dependency or build shards.ShardedObjectStore "
                "(shards=1 is the old behavior)",
            ))
    return findings

"""KTL010 — per-iteration durability barrier inside a loop.

PR 19's group-commit work exists because the control plane was paying one
fsync per WAL append: at 10k jobs / 100k pods the log issued 220,000
fsyncs for 220,000 appends and every arm of BENCH_r18 flatlined at the
same throughput regardless of shard count. The bug class this rule pins:
a loop that re-pays the durability barrier every iteration —

    for rec in records:
        f.write(rec)
        os.fsync(f.fileno())        # N barriers for one logical batch

    for obj in batch:
        ticket = wal.append(...)
        wal.wait_durable(ticket)    # re-serializes the group commit

The batched shape costs the same durability and O(batches) barriers:
write/stage everything, then fsync (or ``wait_durable``) ONCE on the
last ticket. ``kubedl_tpu/core/wal.py`` is exempt — its committer loop
IS the amortized fsync (one per batch window, by construction).

A loop writing N *distinct* files can legitimately fsync each one; that
is still usually better written as write-all-then-fsync-all, but when the
per-file barrier is required, say so with
``# ktl: disable=KTL010 -- <why>``.
"""

from __future__ import annotations

import ast
from typing import List

RULE_ID = "KTL010"

#: the committer loop in here is the group-commit mechanism itself
ALLOWED_FILES = {"kubedl_tpu/core/wal.py"}

#: terminal callable names that are durability barriers
_BARRIERS = {"fsync", "fdatasync", "_fsync", "wait_durable", "_wait_durable"}

_LOOPS = (ast.For, ast.While, ast.AsyncFor)
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _barrier_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id in _BARRIERS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in _BARRIERS:
        return f.attr
    return ""


class _Visitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.loop_depth = 0
        self.hits: List[ast.Call] = []

    def generic_visit(self, node: ast.AST) -> None:
        if isinstance(node, _SCOPES):
            # a def/lambda inside a loop body doesn't RUN per iteration;
            # its own loops are visited with a fresh depth
            depth, self.loop_depth = self.loop_depth, 0
            super().generic_visit(node)
            self.loop_depth = depth
            return
        if isinstance(node, _LOOPS):
            self.loop_depth += 1
            super().generic_visit(node)
            self.loop_depth -= 1
            return
        if (
            self.loop_depth > 0
            and isinstance(node, ast.Call)
            and _barrier_name(node)
        ):
            self.hits.append(node)
        super().generic_visit(node)


def check_file(ctx) -> List["Finding"]:  # noqa: F821 — engine's Finding
    if ctx.relpath in ALLOWED_FILES:
        return []
    v = _Visitor()
    v.visit(ctx.tree)
    return [
        ctx.finding(
            RULE_ID, node.lineno,
            f"durability barrier '{_barrier_name(node)}' inside a loop "
            "pays one commit per iteration — batch it: write/stage the "
            "whole set, then fsync (or wait_durable on the LAST ticket) "
            "once; BENCH_r18's 220k fsyncs for 220k appends is this shape "
            "at scale",
        )
        for node in v.hits
    ]

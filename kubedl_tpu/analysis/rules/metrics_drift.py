"""KTL005 — metrics drift.

Two mechanical failure modes around ``observability/metrics.py``:

1. a metric registered in a family (``self.X = r.counter(...)``) that no
   production code ever touches — it renders forever at zero, which
   dashboards read as "healthy" instead of "not wired";
2. label-set drift: the same metric attribute mutated with different
   label keysets at different call sites (``.inc(reason=...)`` here,
   bare ``.inc()`` there) — Prometheus treats those as disjoint series,
   so sums silently split.

Attribute names shared by multiple families (e.g. ``probe_failures`` on
both ServingMetrics and RouterMetrics) are exempt from the label check —
call sites can't be attributed to a family statically.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set, Tuple

from kubedl_tpu.analysis.engine import Finding

RULE_ID = "KTL005"

METRICS_PATH = "kubedl_tpu/observability/metrics.py"

_REG_METHODS = {"counter", "gauge", "histogram"}
_MUTATORS = {"inc", "observe", "set"}
#: kwargs of the mutators that are values, not labels (exemplar is the
#: optional trace-id payload on Histogram.observe, never a label)
_VALUE_KWARGS = {"amount", "value", "exemplar"}


def _registered_metrics(ctx) -> List[Tuple[str, str, int]]:
    """[(attr, prom_name, line)] from self.X = r.counter("name", ...)."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr in _REG_METHODS):
            continue
        if v.args and isinstance(v.args[0], ast.Constant) \
                and isinstance(v.args[0].value, str):
            out.append((t.attr, v.args[0].value, node.lineno))
    return out


def _usage_and_labels(
    contexts, attrs: Set[str]
) -> Tuple[Set[str], Dict[str, Dict[frozenset, Tuple[str, int]]]]:
    """(attrs referenced anywhere outside metrics.py,
    attr -> {label-keyset -> example (path, line)} across mutator calls)."""
    used: Set[str] = set()
    labels: Dict[str, Dict[frozenset, Tuple[str, int]]] = {}
    for ctx in contexts:
        if ctx.relpath.endswith("observability/metrics.py"):
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr in attrs:
                used.add(node.attr)
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
                continue
            recv = f.value
            if not (isinstance(recv, ast.Attribute) and recv.attr in attrs):
                continue
            keyset = frozenset(
                kw.arg for kw in node.keywords
                if kw.arg and kw.arg not in _VALUE_KWARGS
            )
            labels.setdefault(recv.attr, {}).setdefault(
                keyset, (ctx.relpath, node.lineno)
            )
    return used, labels


def check_project(root: Path, contexts) -> List[Finding]:
    metrics_ctx = next(
        (c for c in contexts if c.relpath.endswith("observability/metrics.py")),
        None,
    )
    if metrics_ctx is None:
        return []
    registered = _registered_metrics(metrics_ctx)
    attr_count: Dict[str, int] = {}
    for attr, _, _ in registered:
        attr_count[attr] = attr_count.get(attr, 0) + 1
    attrs = set(attr_count)
    used, labels = _usage_and_labels(contexts, attrs)
    findings: List[Finding] = []
    seen_unused: Set[str] = set()
    for attr, prom_name, line in registered:
        if attr not in used and attr not in seen_unused:
            seen_unused.add(attr)
            findings.append(Finding(
                RULE_ID, METRICS_PATH, line,
                f"metric {prom_name} (attr .{attr}) registered but never "
                f"referenced outside metrics.py — renders forever at zero",
                snippet=f"unused-metric:{attr}",
            ))
    for attr, keysets in sorted(labels.items()):
        if attr_count.get(attr, 0) > 1:
            continue  # shared attr name across families: not attributable
        if len(keysets) > 1:
            desc = "; ".join(
                f"{{{', '.join(sorted(ks)) or 'no labels'}}} at {p}:{ln}"
                for ks, (p, ln) in sorted(
                    keysets.items(), key=lambda kv: sorted(kv[0])
                )
            )
            findings.append(Finding(
                RULE_ID, METRICS_PATH, 1,
                f"metric attr .{attr} mutated with inconsistent label "
                f"keysets: {desc} — series split silently",
                snippet=f"label-drift:{attr}",
            ))
    return findings

"""Rule registry. Each module pins one historical bug class; the catalog
with the postmortem each rule encodes lives in docs/static-analysis.md."""

from kubedl_tpu.analysis.rules import (
    chaos_sites,
    donation,
    envmut,
    fenced_actuation,
    fsync_loop,
    locks,
    metrics_drift,
    ps_chaos_tests,
    schema_drift,
    span_names,
    store_construction,
)

#: engine iterates this; order = report order
ALL_RULES = [
    donation,        # KTL001
    locks,           # KTL002
    envmut,          # KTL003
    chaos_sites,     # KTL004
    metrics_drift,   # KTL005
    schema_drift,    # KTL006
    span_names,      # KTL007
    ps_chaos_tests,  # KTL008
    store_construction,  # KTL009
    fsync_loop,      # KTL010
    fenced_actuation,  # KTL011
]

RULE_IDS = {m.RULE_ID: m for m in ALL_RULES}

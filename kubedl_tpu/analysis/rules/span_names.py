"""KTL007 — tracing span-name drift.

Same three-surface discipline as KTL004, applied to the distributed
tracing added with the span catalog in docs/observability.md:

1. every string literal at a ``TRACER.span(...)`` / ``TRACER.begin(...)``
   / ``TRACER.record(...)`` call site must have a row in the
   docs/observability.md span-catalog table (the ``| Span | Layer |``
   table) — trace consumers (``scripts/tracemerge.py``, the verify
   drives, dashboards keying on span names) read that table as the
   contract;
2. every documented span name must be emitted somewhere — a stale doc
   row describes spans that no trace will ever contain.

Only the module-level ``TRACER`` singleton is matched (locally
constructed ``Tracer()`` instances in tests/benchmarks are out of
contract), and only ``kubedl_tpu/`` sources are scanned (engine policy).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set, Tuple

from kubedl_tpu.analysis.engine import Finding

RULE_ID = "KTL007"

DOC_PATH = "docs/observability.md"

_EMIT_METHODS = {"span", "begin", "record"}

#: emission-site WRAPPERS: method name -> positional index of the span
#: name literal (JobEngine._trace_job_milestone(job, "job.submit", ...)
#: wraps TRACER.record, so its literal is part of the contract too)
_WRAPPERS = {"_trace_job_milestone": 1}


def _call_sites(contexts) -> Dict[str, List[Tuple[str, int]]]:
    """name -> [(relpath, line)] for every TRACER.span/begin/record
    (or known wrapper) call whose span-name argument is a string
    literal."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if (f.attr in _EMIT_METHODS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "TRACER"):
                idx = 0
            elif f.attr in _WRAPPERS:
                idx = _WRAPPERS[f.attr]
            else:
                continue
            if len(node.args) > idx \
                    and isinstance(node.args[idx], ast.Constant) \
                    and isinstance(node.args[idx].value, str):
                name = node.args[idx].value
                out.setdefault(name, []).append((ctx.relpath, node.lineno))
    return out


def _doc_table_spans(root: Path) -> Set[str]:
    """Backticked first-column tokens of the ``| Span | Layer |`` table."""
    doc = root / DOC_PATH
    if not doc.exists():
        return set()
    spans: Set[str] = set()
    in_table = False
    for line in doc.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("|") and "Span" in stripped \
                and "Layer" in stripped:
            in_table = True
            continue
        if in_table:
            if not stripped.startswith("|"):
                in_table = False
                continue
            first_col = stripped.strip("|").split("|")[0]
            for tok in re.findall(r"`([^`]+)`", first_col):
                spans.add(tok.strip())
    return spans


def check_project(root: Path, contexts) -> List[Finding]:
    emitted = _call_sites(contexts)
    documented = _doc_table_spans(root)
    if not documented and not emitted:
        return []
    findings: List[Finding] = []
    if not documented:
        return [Finding(
            RULE_ID, DOC_PATH, 1,
            f"no span-catalog table (| Span | Layer | ... |) found in "
            f"{DOC_PATH} while {len(emitted)} span name(s) are emitted",
            snippet="missing-span-table",
        )]
    for name, where in sorted(emitted.items()):
        if name not in documented:
            path, line = where[0]
            findings.append(Finding(
                RULE_ID, path, line,
                f"span '{name}' emitted here but missing from the "
                f"{DOC_PATH} span catalog — document it first",
                snippet=f"undocumented-span:{name}",
            ))
    for name in sorted(documented - set(emitted)):
        findings.append(Finding(
            RULE_ID, DOC_PATH, 1,
            f"span '{name}' documented in the catalog but emitted "
            f"nowhere (stale doc row)",
            snippet=f"dead-span:{name}",
        ))
    return findings

"""KTL011 — externally-visible actuation without a fencing-token check.

PR 20's federation runs N operator processes over one lease/WAL root.
The store's own write router fences every shard-local mutation, but a
reconcile's side effects are wider than store writes: reserving slice
capacity in the in-memory inventory, launching a pod batch, reaping a
pod the kubelet will SIGKILL. A SIGSTOP'd owner that resumes after its
lease expired still holds those calls queued mid-reconcile — each one
must be gated by :func:`kubedl_tpu.federation.actuation.
assert_fenced_actuation` BEFORE it fires, or the stale owner acts on a
shard a live member now owns (docs/robustness.md "Federation demotion
and takeover"). The bug class this rule pins::

    def try_admit(self, gang):
        assigned = self.inventory.try_reserve(...)   # memory — unfenced
        self.store.update_with_retry(...)            # fenced, but SECOND

The fixed shape calls ``assert_fenced_actuation(...)`` in the same
function, before (or on the same line as) the actuation.

Matched actuations: slice reservations (``.try_reserve(...)``,
``.reserve_exact(...)``), batched pod launches (``.create_many(...)``),
and pod reaps (``.try_delete("Pod", ...)``). Bench/driver harnesses
that own every shard by construction are exempt via ``ALLOWED_FILES``;
anything else that must act unfenced says why with
``# ktl: disable=KTL011 -- <why>``.
"""

from __future__ import annotations

import ast
from typing import List

RULE_ID = "KTL011"

ALLOWED_FILES = {
    # the facade's create_many IS the fenced write path (each shard-local
    # batch goes through the FencedWal it mounted)
    "kubedl_tpu/shards/store.py",
    # single-process churn harness: constructed owning every shard; its
    # create_many calls are the workload generator, not a reconcile
    "kubedl_tpu/shards/churn.py",
}

#: attribute calls that ARE externally-visible actuations
_ACTUATIONS = {"try_reserve", "reserve_exact", "create_many"}

#: the gate — seeing a call to it anywhere earlier in the same function
#: satisfies the rule for that function's actuations
_GUARD = "assert_fenced_actuation"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _actuation_name(node: ast.Call) -> str:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return ""
    if f.attr in _ACTUATIONS:
        return f.attr
    if f.attr == "try_delete" and node.args:
        first = node.args[0]
        if isinstance(first, ast.Constant) and first.value == "Pod":
            return 'try_delete("Pod", ...)'
    return ""


def _is_guard(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == _GUARD
    if isinstance(f, ast.Attribute):
        return f.attr == _GUARD
    return False


def _scope_calls(fn: ast.AST) -> List[ast.Call]:
    """Calls lexically in ``fn``'s own body, pruning nested defs — they
    are walked as their own scope, and a guard in the outer body does
    not cover a closure that may run later."""
    calls: List[ast.Call] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS):
                continue
            if isinstance(child, ast.Call):
                calls.append(child)
            walk(child)

    walk(fn)
    return calls


def _check_function(fn: ast.AST, ctx, out: List) -> None:
    guard_line = None
    hits: List[ast.Call] = []
    for node in _scope_calls(fn):
        if _is_guard(node):
            if guard_line is None or node.lineno < guard_line:
                guard_line = node.lineno
        elif _actuation_name(node):
            hits.append(node)
    for node in hits:
        if guard_line is not None and guard_line <= node.lineno:
            continue
        out.append(
            ctx.finding(
                RULE_ID, node.lineno,
                f"externally-visible actuation '{_actuation_name(node)}' "
                "without a fencing-token check: call "
                "assert_fenced_actuation(store, namespace, root_name) "
                "earlier in this function so a deposed/stale owner "
                "(SIGSTOP resumed past its lease TTL, partitioned member) "
                "rejects the side effect instead of racing the live owner",
            )
        )


def check_file(ctx) -> List["Finding"]:  # noqa: F821 — engine's Finding
    if ctx.relpath in ALLOWED_FILES:
        return []
    out: List = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNCS):
            _check_function(node, ctx, out)
    return out

"""KTL003 — os.environ mutation outside the sanctioned entry guard.

Historical bug pinned (PR 6): glibc ``setenv``/``putenv`` may realloc the
process environ block, racing native ``getenv`` from XLA's persistent
worker threads — one process hosts every gang attempt, so a steady-state
restart that rewrites an *unchanged* var can corrupt a concurrent read.
``utils/envguard.py`` owns the sanctioned pattern: set only vars whose
value actually changes, before JAX wakes its threads; entrypoints call
``apply_env``. ``training/entry.py`` keeps its pre-jax LIBTPU flag
append (read-modify-write of one var before the first trace).

Flags ``os.environ[...] = ...``, ``del os.environ[...]``, and
``os.environ.{update,setdefault,pop,clear}`` / ``os.putenv`` /
``os.unsetenv`` everywhere under ``kubedl_tpu/`` except the sanctioned
files. Pre-JAX writes in fresh subprocess entry points are accepted
with an inline pragma carrying the justification.
"""

from __future__ import annotations

import ast
from typing import List, Optional

RULE_ID = "KTL003"

SANCTIONED_FILES = ("training/entry.py", "utils/envguard.py")

_MUTATORS = {"update", "setdefault", "pop", "clear", "__setitem__",
             "__delitem__"}


def _is_os_environ(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    ) or (isinstance(node, ast.Name) and node.id == "environ")


class _EnvVisitor(ast.NodeVisitor):
    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self.findings: List = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(self.ctx.finding(
            RULE_ID, node,
            f"{what} outside training/entry.py's changed-vars guard: "
            f"setenv can realloc environ under XLA's native getenv "
            f"(PR 6 race) — route through the entry guard, or pragma "
            f"with a fresh-subprocess / pre-jax-init justification",
        ))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                self._flag(node, "os.environ[...] assignment")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                self._flag(node, "del os.environ[...]")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if _is_os_environ(f.value) and f.attr in _MUTATORS:
                self._flag(node, f"os.environ.{f.attr}(...)")
            elif (
                isinstance(f.value, ast.Name) and f.value.id == "os"
                and f.attr in ("putenv", "unsetenv")
            ):
                self._flag(node, f"os.{f.attr}(...)")
        self.generic_visit(node)


def check_file(ctx) -> List:
    if any(ctx.relpath.endswith(s) for s in SANCTIONED_FILES):
        return []
    v = _EnvVisitor(ctx)
    v.visit(ctx.tree)
    return v.findings

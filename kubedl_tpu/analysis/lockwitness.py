"""Runtime lock-order witness: drop-in Lock/RLock/Condition wrappers.

The static KTL002 rule (docs/static-analysis.md) finds *lexically*
blocking work under a lock; this module finds *dynamic* ordering bugs the
AST cannot see — two code paths that acquire the same pair of lock
classes in opposite orders (a potential deadlock the moment both paths
run concurrently), and locks held across registered blocking calls.

Design mirrors the chaos layer's disarmed fast path: when
``KUBEDL_LOCKWITNESS`` is unset the module-level factories return *bare*
``threading`` primitives — zero wrapper, zero bookkeeping — so
production code can route lock creation through :func:`Lock` /
:func:`RLock` / :func:`Condition` at no cost. When armed (env var ``=1``
or :func:`install`), every lock created from repo code is tagged with its
*creation site* (file:line — the lock "class" in witness terms, the same
granularity FreeBSD's witness(4) uses), and each acquisition records a
``held-site -> acquired-site`` edge in a global order graph. A cycle in
that graph is a potential deadlock even if the run never actually
deadlocked; :func:`check` (and the tier-1 conftest hook) fails on any.

``install()`` additionally monkeypatches ``threading.Lock`` /
``threading.RLock`` / ``threading.Condition`` so *existing* code that
calls ``threading.Lock()`` directly is witnessed without modification,
and wraps ``time.sleep`` to flag sleeps executed while a witnessed lock
is held (the ``_spec_tick`` bug class, at runtime). Locks created from
outside the repo tree (stdlib, site-packages) pass through unwitnessed —
their ordering is not ours to police and the noise would drown the graph.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_SLEEP = time.sleep

#: repo root: locks created outside this tree are passed through bare.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

ENV_VAR = "KUBEDL_LOCKWITNESS"


def _creation_site() -> Tuple[str, int, bool]:
    """(filename, lineno, interesting) of the frame that created the lock,
    skipping this module and threading.py (``Condition()`` creates its
    default RLock from inside threading)."""
    f = sys._getframe(2)
    here = os.path.abspath(__file__)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != here and not fn.endswith("threading.py"):
            path = os.path.abspath(fn)
            interesting = path.startswith(_REPO_ROOT) and (
                "site-packages" not in path
            )
            return path, f.f_lineno, interesting
        f = f.f_back
    return "<unknown>", 0, False


@dataclass
class OrderCycle:
    """A cycle in the lock-order graph: potential deadlock."""

    sites: Tuple[str, ...]                  # site names along the cycle
    edges: Tuple[Tuple[str, str], ...]      # the edges that close it

    def __str__(self) -> str:
        return "lock-order cycle: " + " -> ".join(self.sites + (self.sites[0],))


@dataclass
class BlockingFinding:
    """A registered blocking call executed while witnessed locks were held."""

    call: str                               # e.g. "time.sleep"
    caller: str                             # file:line of the blocking call
    held: Tuple[str, ...]                   # creation sites of held locks

    def __str__(self) -> str:
        return (
            f"{self.call} at {self.caller} while holding "
            + ", ".join(self.held)
        )


class _TLS(threading.local):
    def __init__(self) -> None:
        self.held: List["_WitnessBase"] = []
        self.seen_edges: Set[Tuple[str, str]] = set()


class Witness:
    """One witness instance: order graph + runtime blocking findings.

    The module singleton (armed via env / :func:`install`) is one of
    these; tests may build private instances so assertions never touch
    global state."""

    def __init__(self) -> None:
        self._mu = _ORIG_LOCK()
        self._tls = _TLS()
        # edge -> example (held stack site, acquire site) human context
        self.edges: Dict[Tuple[str, str], int] = {}
        self._blocking: List[BlockingFinding] = []
        self._blocking_seen: Set[Tuple[str, Tuple[str, ...]]] = set()

    # -- factories ---------------------------------------------------------

    def Lock(self):
        site, line, interesting = _creation_site()
        if not interesting:
            return _ORIG_LOCK()
        return _WitnessLock(self, f"{_rel(site)}:{line}")

    def RLock(self):
        site, line, interesting = _creation_site()
        if not interesting:
            return _ORIG_RLOCK()
        return _WitnessRLock(self, f"{_rel(site)}:{line}")

    def Condition(self, lock=None):
        if lock is None:
            site, line, interesting = _creation_site()
            if not interesting:
                return _ORIG_CONDITION()
            lock = _WitnessRLock(self, f"{_rel(site)}:{line}")
        return _ORIG_CONDITION(lock)

    # -- bookkeeping (called by wrappers) ----------------------------------

    def note_acquire(self, wrapper: "_WitnessBase") -> None:
        tls = self._tls
        for h in tls.held:
            if h.site == wrapper.site:
                continue  # same lock class nested: ordered by convention
            edge = (h.site, wrapper.site)
            if edge in tls.seen_edges:
                continue
            tls.seen_edges.add(edge)
            with self._mu:
                self.edges[edge] = self.edges.get(edge, 0) + 1
        tls.held.append(wrapper)

    def note_release(self, wrapper: "_WitnessBase") -> None:
        held = self._tls.held
        for i in range(len(held) - 1, -1, -1):
            if held[i] is wrapper:
                del held[i]
                return

    def note_blocking(self, call: str, caller: str) -> None:
        held = tuple(w.site for w in self._tls.held)
        if not held:
            return
        key = (caller, held)
        if key in self._blocking_seen:
            return
        with self._mu:
            self._blocking_seen.add(key)
            self._blocking.append(BlockingFinding(call, caller, held))

    def held_sites(self) -> Tuple[str, ...]:
        return tuple(w.site for w in self._tls.held)

    # -- analysis ----------------------------------------------------------

    def cycles(self) -> List[OrderCycle]:
        """Strongly-connected components of the order graph with more
        than one node — each is a set of lock classes acquired in
        conflicting orders somewhere in the run."""
        with self._mu:
            edge_list = list(self.edges)
        graph: Dict[str, List[str]] = {}
        for a, b in edge_list:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        # iterative Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in graph:
            if root in index:
                continue
            work = [(root, iter(graph[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(graph[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)
        out = []
        for scc in sccs:
            members = set(scc)
            cyc_edges = tuple(
                (a, b) for (a, b) in edge_list if a in members and b in members
            )
            out.append(OrderCycle(tuple(sorted(members)), cyc_edges))
        return out

    def blocking_findings(self) -> List[BlockingFinding]:
        with self._mu:
            return list(self._blocking)

    def report(self) -> str:
        lines = []
        cycles = self.cycles()
        if cycles:
            lines.append(f"lockwitness: {len(cycles)} order cycle(s):")
            lines.extend(f"  {c}" for c in cycles)
            for c in cycles:
                for a, b in c.edges:
                    lines.append(f"    edge {a} -> {b}")
        blocking = self.blocking_findings()
        if blocking:
            lines.append(
                f"lockwitness: {len(blocking)} blocking call(s) under a lock:"
            )
            lines.extend(f"  {b}" for b in blocking)
        with self._mu:
            lines.append(
                f"lockwitness: {len(self.edges)} order edge(s) observed"
            )
        return "\n".join(lines)


def _rel(path: str) -> str:
    try:
        return os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        return path


class _WitnessBase:
    __slots__ = ("_witness", "_raw", "site")


class _WitnessLock(_WitnessBase):
    """Drop-in for threading.Lock with acquisition-order recording."""

    def __init__(self, witness: Witness, site: str) -> None:
        self._witness = witness
        self._raw = _ORIG_LOCK()
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._witness.note_acquire(self)
        return got

    def release(self) -> None:
        self._witness.note_release(self)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):  # _at_fork_reinit etc.
        return getattr(self._raw, name)


class _WitnessRLock(_WitnessBase):
    """Drop-in for threading.RLock; also Condition-compatible
    (_is_owned/_release_save/_acquire_restore delegate with the depth
    bookkeeping the witness needs)."""

    __slots__ = ("_depth",)

    def __init__(self, witness: Witness, site: str) -> None:
        self._witness = witness
        self._raw = _ORIG_RLOCK()
        self.site = site
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._depth += 1
            if self._depth == 1:
                self._witness.note_acquire(self)
        return got

    __enter__ = acquire

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._witness.note_release(self)
        self._raw.release()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol
    def _is_owned(self) -> bool:
        return self._raw._is_owned()

    def _release_save(self):
        depth, self._depth = self._depth, 0
        self._witness.note_release(self)
        return (self._raw._release_save(), depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._raw._acquire_restore(state)
        self._depth = depth
        self._witness.note_acquire(self)

    def __getattr__(self, name):
        return getattr(self._raw, name)


# ---- module singleton / global install ------------------------------------

_GLOBAL: Optional[Witness] = None
_INSTALLED = False


def active() -> Optional[Witness]:
    return _GLOBAL


def armed() -> bool:
    return _GLOBAL is not None


def Lock():
    """Factory for production code: bare threading.Lock when disarmed."""
    w = _GLOBAL
    if w is None:
        return _ORIG_LOCK()
    return w.Lock()


def RLock():
    w = _GLOBAL
    if w is None:
        return _ORIG_RLOCK()
    return w.RLock()


def Condition(lock=None):
    w = _GLOBAL
    if w is None:
        return _ORIG_CONDITION(lock)
    return w.Condition(lock)


def _witness_sleep(secs):
    w = _GLOBAL
    if w is not None and secs and secs > 0:
        f = sys._getframe(1)
        w.note_blocking(
            "time.sleep", f"{_rel(f.f_code.co_filename)}:{f.f_lineno}"
        )
    _ORIG_SLEEP(secs)


def install(force: bool = False) -> Optional[Witness]:
    """Arm the global witness and monkeypatch ``threading.Lock`` /
    ``RLock`` / ``Condition`` (+ ``time.sleep``) so existing code is
    witnessed unmodified. No-op unless ``KUBEDL_LOCKWITNESS=1`` or
    ``force``. Idempotent. Call BEFORE the modules whose locks you want
    witnessed create them (conftest does this at import)."""
    global _GLOBAL, _INSTALLED
    if not force and os.environ.get(ENV_VAR, "") != "1":
        return None
    if _GLOBAL is None:
        _GLOBAL = Witness()
    if not _INSTALLED:
        threading.Lock = lambda: _GLOBAL.Lock()
        threading.RLock = lambda: _GLOBAL.RLock()
        threading.Condition = lambda lock=None: _GLOBAL.Condition(lock)
        time.sleep = _witness_sleep
        atexit.register(_atexit_report)
        _INSTALLED = True
    return _GLOBAL


def uninstall() -> None:
    """Disarm and restore the patched primitives (test hygiene). Locks
    already created stay witnessed but the graph stops growing only for
    new edges recorded against the old witness."""
    global _GLOBAL, _INSTALLED
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    time.sleep = _ORIG_SLEEP
    _GLOBAL = None
    _INSTALLED = False


def check(fail_on_blocking: bool = False) -> List[OrderCycle]:
    """The gate: return order cycles on the global witness (empty when
    disarmed). ``fail_on_blocking`` folds runtime blocking-under-lock
    findings in as failures too (default: report-only — the static
    KTL002 rule owns that class with baseline/pragma workflow)."""
    w = _GLOBAL
    if w is None:
        return []
    cycles = w.cycles()
    if fail_on_blocking:
        cycles = cycles + [
            OrderCycle((str(b),), ()) for b in w.blocking_findings()
        ]
    return cycles


def _atexit_report() -> None:
    w = _GLOBAL
    if w is None:
        return
    cycles = w.cycles()
    blocking = w.blocking_findings()
    if cycles or blocking:
        sys.stderr.write(w.report() + "\n")

"""Project-specific static analysis engine.

Walks every ``.py`` file under ``kubedl_tpu/`` through the rule set in
:mod:`kubedl_tpu.analysis.rules` — each rule pins one *historical* bug
class from this repo's postmortems (docs/static-analysis.md has the
catalog). Findings can be suppressed two ways:

- inline pragma on the flagged line or the line above::

      os.environ["X"] = "y"  # ktl: disable=KTL003 -- fresh subprocess, pre-jax

  (``# ktl: disable-file=KTL003`` in the first 10 lines suppresses the
  rule for the whole file);
- the committed ``analysis/baseline.json``: accepted pre-existing
  findings, keyed by a line-content fingerprint so pure line-number
  drift never invalidates them. New findings beyond the baseline fail.

``python -m kubedl_tpu.analysis`` is the CLI; tier-1 runs it via
``tests/test_analysis.py`` the same way ``check_readme_numbers.py`` is
gated.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

_PRAGMA_RE = re.compile(r"#\s*ktl:\s*disable=([A-Z0-9, ]+)")
_FILE_PRAGMA_RE = re.compile(r"#\s*ktl:\s*disable-file=([A-Z0-9, ]+)")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    snippet: str = ""  # stripped source line, the fingerprint anchor

    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.snippet or self.message}".encode()
        )
        return h.hexdigest()[:16]

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


@dataclass
class FileContext:
    """Parsed view of one source file handed to AST rules."""

    path: Path
    relpath: str
    source: str
    lines: List[str]
    tree: ast.AST

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        snippet = ""
        if 1 <= line <= len(self.lines):
            snippet = self.lines[line - 1].strip()
        return Finding(rule, self.relpath, line, message, snippet)


def _rule_modules():
    from kubedl_tpu.analysis.rules import ALL_RULES

    return ALL_RULES


def iter_source_files(root: Path) -> List[Path]:
    pkg = root / "kubedl_tpu"
    files = [
        p for p in sorted(pkg.rglob("*.py"))
        if "__pycache__" not in p.parts
    ]
    return files


def parse_file(path: Path, root: Path) -> Optional[FileContext]:
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    rel = path.relative_to(root).as_posix()
    return FileContext(path, rel, source, source.splitlines(), tree)


def _apply_pragmas(findings: List[Finding], ctx: FileContext) -> List[Finding]:
    file_disabled: set = set()
    for line in ctx.lines[:10]:
        m = _FILE_PRAGMA_RE.search(line)
        if m:
            file_disabled |= {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
    out = []
    for f in findings:
        if f.rule in file_disabled or "ALL" in file_disabled:
            continue
        suppressed = False
        for ln in (f.line, f.line - 1):
            if 1 <= ln <= len(ctx.lines):
                m = _PRAGMA_RE.search(ctx.lines[ln - 1])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if f.rule in rules or "ALL" in rules:
                        suppressed = True
                        break
        if not suppressed:
            out.append(f)
    return out


def analyze_file(path: Path, root: Optional[Path] = None) -> List[Finding]:
    """Run every AST rule over one file (fixture tests use this)."""
    root = root or REPO_ROOT
    try:
        rel_root = root if path.is_relative_to(root) else path.parent
    except AttributeError:  # <3.9 compat, not expected
        rel_root = root
    ctx = parse_file(path, rel_root)
    if ctx is None:
        return [Finding("KTL000", str(path), 1, "file does not parse")]
    findings: List[Finding] = []
    for rule in _rule_modules():
        check = getattr(rule, "check_file", None)
        if check is not None:
            findings.extend(check(ctx))
    return _apply_pragmas(findings, ctx)


def analyze(root: Optional[Path] = None) -> List[Finding]:
    """Full-project run: AST rules over every file plus project rules
    (chaos-site drift, metrics drift, schema drift)."""
    root = root or REPO_ROOT
    files = iter_source_files(root)
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for p in files:
        ctx = parse_file(p, root)
        if ctx is None:
            findings.append(
                Finding("KTL000", p.relative_to(root).as_posix(), 1,
                        "file does not parse")
            )
            continue
        contexts.append(ctx)
        file_findings: List[Finding] = []
        for rule in _rule_modules():
            check = getattr(rule, "check_file", None)
            if check is not None:
                file_findings.extend(check(ctx))
        findings.extend(_apply_pragmas(file_findings, ctx))
    for rule in _rule_modules():
        check = getattr(rule, "check_project", None)
        if check is not None:
            findings.extend(check(root, contexts))
    return findings


# ---- baseline -------------------------------------------------------------


def load_baseline(path: Path = BASELINE_PATH) -> Dict[str, int]:
    """fingerprint -> accepted count."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    out: Dict[str, int] = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = out.get(entry["fingerprint"], 0) + 1
    return out


def write_baseline(findings: Sequence[Finding],
                   path: Path = BASELINE_PATH) -> None:
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "rule": f.rule,
            "path": f.path,
            "snippet": f.snippet,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2
    ) + "\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[str]]:
    """(new findings beyond the baseline, stale baseline fingerprints)."""
    budget = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    stale = [fp for fp, n in budget.items() if n > 0]
    return new, stale


# ---- CLI ------------------------------------------------------------------


def run(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m kubedl_tpu.analysis",
        description="Project-specific static analysis (rule catalog: "
                    "docs/static-analysis.md)",
    )
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="repo root (default: this checkout)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring baseline.json")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings into baseline.json")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    findings = analyze(root)

    if args.write_baseline:
        write_baseline(findings)
        print(f"baseline.json: accepted {len(findings)} finding(s)")
        return 0

    stale: List[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(findings, load_baseline())

    if args.json:
        print(json.dumps({
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message, "fingerprint": f.fingerprint()}
                for f in findings
            ],
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in findings:
            print(f)
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings "
                  "still listed in analysis/baseline.json — prune with "
                  "--write-baseline)")
        if findings:
            print(f"{len(findings)} new finding(s) — fix, pragma "
                  "(# ktl: disable=KTLxxx), or accept via --write-baseline")
        else:
            print("static analysis clean")
    return 1 if findings else 0

"""The workload-controller plugin contract.

Reference analogue: `ControllerInterface` (pkg/job_controller/api/v1/
interface.go:12-70) — 17 methods covering identity, cache reads, pod/service
claiming, status updates, cluster-spec injection, reconcile order and master
detection. The TPU build needs fewer: the store handles reads/claims
generically, so what remains is exactly the per-framework knowledge:

- ``set_cluster_spec`` → here ``set_mesh_spec``: emit the bootstrap env
  (coordinator address, process id/count, TPU_WORKER_HOSTNAMES, mesh-axis
  hints) instead of TF_CONFIG / MASTER_ADDR / hostfiles.
- ``reconcile_orders`` and DAG defaults (PS before workers, etc.).
- success semantics (``update_job_status``) and master-role detection.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.types import JobSpec, JobStatus, ReplicaType
from kubedl_tpu.core.objects import BaseObject, Pod, Service


@dataclass
class JobObject(BaseObject):
    """Base class every workload kind derives from (TPUJob, TorchXLAJob...).

    The reference's per-kind CRD structs all reduce to {ReplicaSpecs,
    RunPolicy, Status} plus kind-specific extras; subclasses add those extras
    as new fields.
    """

    KIND = "Job"
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


@dataclass
class ReconcileContext:
    """Per-reconcile scratch carried through the engine (reference:
    pkg/job_controller/context.go:21-27 — used there for host-port wiring)."""

    job: JobObject
    pods: List[Pod] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    #: host ports chosen for host-network pods, keyed "rtype-index"
    host_ports: Dict[str, int] = field(default_factory=dict)
    #: gang placement: replica "rtype-index" -> node name
    placements: Dict[str, str] = field(default_factory=dict)


class WorkloadController:
    """Subclass per workload kind; the engine drives everything else."""

    #: Store kind, e.g. "TPUJob".
    KIND: str = "Job"
    #: Controller name for logs/metrics.
    NAME: str = "job-controller"
    #: Replica types this kind accepts; None = no restriction. Unknown
    #: types are pruned during defaulting (a bad spec must degrade, not
    #: wedge reconcile with a KeyError).
    ALLOWED_REPLICA_TYPES: Optional[tuple] = None

    def __init__(self, cluster_domain: str = "", local_addresses: bool = False) -> None:
        #: local_addresses=True emits 127.0.0.1 instead of service DNS —
        #: used when pods run as local processes (tests, the single-host
        #: dev loop, CI's kind-style smoke).
        self.cluster_domain = cluster_domain
        self.local_addresses = local_addresses

    # ---- identity --------------------------------------------------------

    def object_factory(self) -> JobObject:
        raise NotImplementedError

    def apply_defaults(self, job: JobObject) -> None:
        """Scheme defaulting hook (reference: scheme.Default before
        ReconcileJobs, tfjob_controller.go:163). Kinds with extra knobs
        (e.g. TPUJob.num_slices) override."""
        from kubedl_tpu.api.types import job_spec_defaults

        if self.ALLOWED_REPLICA_TYPES is not None:
            for rtype in list(job.spec.replica_specs):
                if rtype not in self.ALLOWED_REPLICA_TYPES:
                    del job.spec.replica_specs[rtype]
        job_spec_defaults(job.spec)

    def validate(self, job: JobObject) -> List[str]:
        """Admission validation (the reference's validating-webhook
        analogue, apis/*/zz_generated + webhook configs): returns human
        errors; non-empty rejects the submit. Runs BEFORE apply_defaults
        so a disallowed group is rejected, not silently pruned (replicas
        <= 0 stays legal: defaulting bumps it to 1). Kinds add their own
        rules on top of the base checks."""
        errs: List[str] = []
        if not job.spec.replica_specs:
            errs.append("spec.replicaSpecs must declare at least one replica type")
        slice_type = ""
        for rtype, rs in job.spec.replica_specs.items():
            if (
                self.ALLOWED_REPLICA_TYPES is not None
                and rtype not in self.ALLOWED_REPLICA_TYPES
            ):
                errs.append(f"replica type {rtype.value} not allowed for {self.KIND}")
            if rs.replicas < 0:
                errs.append(f"{rtype.value}.replicas must not be negative")
            if rs.topology is not None:
                if slice_type and rs.topology.name != slice_type:
                    errs.append("mixed slice types in one job are not supported")
                slice_type = rs.topology.name
        bl = job.spec.run_policy.backoff_limit
        if bl is not None and bl < 0:
            errs.append("runPolicy.backoffLimit must be >= 0")
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and ttl < 0:
            errs.append("runPolicy.ttlSecondsAfterFinished must be >= 0")
        return errs

    # ---- elastic slice scaling (kubedl_tpu/elastic/) ---------------------

    def elastic_range(self, job: JobObject) -> Optional[tuple]:
        """``(min_slices, max_slices)`` when this job opted into elastic
        scaling; None (the default) = fixed-size, the ElasticPolicy leaves
        it alone."""
        return None

    def get_num_slices(self, job: JobObject) -> int:
        """Current desired slice count in the job's spec."""
        return 1

    def elastic_cooldown(self, job: JobObject) -> Optional[float]:
        """Per-job override of the grow-cooldown window (seconds); None
        uses the operator-wide default (OperatorOptions
        .elastic_cooldown_seconds)."""
        return None

    def set_num_slices(self, job: JobObject, n: int) -> None:
        """Write a new desired slice count onto the job's spec (the engine
        detects the demand change and executes the resize protocol)."""
        raise NotImplementedError(
            f"{self.KIND} does not support elastic resize"
        )

    # ---- auto-parallelism planning (kubedl_tpu/planner/) -----------------

    def plan_mesh(self, job: JobObject):
        """Compute (or refresh) the job's auto-parallelism plan.

        Called by the engine early in every reconcile, before pods are
        built. Return a ``kubedl_tpu.planner.Plan`` when a NEW plan was
        computed this pass — the engine stamps the planned-mesh annotation,
        ``status.plan``, a ``Planned`` condition/event and planner metrics.
        Return None when the kind does not plan (the default) or the cached
        plan is still valid for the current (topology, num_slices). May
        raise ``kubedl_tpu.planner.PlanError`` when no feasible layout
        exists — the engine fails the job with reason PlanInfeasible.
        """
        return None

    # ---- topology / ordering --------------------------------------------

    def reconcile_orders(self) -> List[ReplicaType]:
        """Replica types in startup order (reference: GetReconcileOrders,
        e.g. TF PS->Master->Chief->Worker, tfjob_controller.go:318-325)."""
        return [ReplicaType.MASTER, ReplicaType.WORKER]

    def is_master_role(self, rtype: ReplicaType) -> bool:
        return rtype in (ReplicaType.MASTER, ReplicaType.CHIEF, ReplicaType.LAUNCHER)

    def needs_service(
        self, rtype: ReplicaType, job: Optional[JobObject] = None
    ) -> bool:
        """Whether replicas of this type get a headless service. The
        reference skips services for ElasticDL and MPI entirely and creates
        master-only services for PyTorch (job.go:253-263). ``job`` lets
        kinds decide per-spec (e.g. masterless PyTorch needs worker-0
        addressable)."""
        return True

    # ---- the process-boundary payload ------------------------------------

    def prepare(self, job: JobObject, ctx: ReconcileContext, store) -> None:
        """Create kind-owned side objects before pods are built (reference:
        MPI getOrCreateJobConfig, controllers/mpi/mpi_config.go:48-123 —
        the hostfile/rsh-agent ConfigMap). Most kinds need nothing."""

    def set_mesh_spec(
        self,
        job: JobObject,
        pod: Pod,
        rtype: ReplicaType,
        index: int,
        ctx: ReconcileContext,
    ) -> None:
        """Inject the distributed-bootstrap environment into ``pod``.

        Reference: SetClusterSpec — genTFConfigJSONStr for TF
        (controllers/tensorflow/tensorflow.go:75-152), MASTER_ADDR/RANK for
        PyTorch (pytorchjob_controller.go:195-245), hostfile ConfigMap for
        MPI (mpi_config.go:48-123).
        """
        raise NotImplementedError

    # ---- status ----------------------------------------------------------

    def evaluate(self, job: JobObject, pods: List[Pod]):
        """Compute the job-level condition implied by pod states. Defaults
        to the shared status machine; kinds with custom success semantics
        (e.g. XDL's partial-worker success) override or filter the result.
        Returns (condition|None, reason, message)."""
        from kubedl_tpu.engine import status as status_machine

        return status_machine.evaluate(job, self, pods)

    def update_job_status(
        self, job: JobObject, pods: List[Pod], ctx: ReconcileContext
    ) -> None:
        """Kind-specific success/failure semantics; the engine supplies a
        default (see engine.status.default_update_job_status) and calls this
        hook afterwards for overrides."""

    def get_node_for_model_output(self, pods: List[Pod]) -> Optional[str]:
        """Node that holds the model artifact (reference:
        GetNodeForModelOutput — chief/master/worker-0's node,
        tfjob_controller.go:86-121). Prefers a master-role or Worker
        index-0 pod with a real node binding."""
        from kubedl_tpu.api import constants

        def index0_node(rtypes) -> Optional[str]:
            for pod in pods:
                labels = pod.metadata.labels
                if (
                    labels.get(constants.LABEL_REPLICA_INDEX) == "0"
                    and labels.get(constants.LABEL_REPLICA_TYPE) in rtypes
                    and pod.spec.node_name
                ):
                    return pod.spec.node_name
            return None

        masters = tuple(rt.value for rt in ReplicaType if self.is_master_role(rt))
        return index0_node(masters) or index0_node((ReplicaType.WORKER.value,))

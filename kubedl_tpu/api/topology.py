"""TPU slice topology catalog and logical mesh specs.

This is the TPU-native replacement for the reference's GPU-count resource
model (`nvidia.com/gpu` detection, pkg/util/resource_utils/resources.go:69-123)
and its port/hostfile communication wiring (SURVEY.md §2.5): jobs declare a
*slice* (an atomically-allocated ICI domain) and a *logical mesh* laid over
it; the operator's job is to hand every worker its coordinates so
`jax.distributed.initialize` + `jax.sharding.Mesh` can do the rest.

Conventions:

- A slice is named ``<generation>-<chips>`` (v5e-32 = 32 chips). One *pod*
  (process) runs per TPU host; hosts within a slice are wired by ICI (no
  ports to allocate), slices are wired to each other over DCN (multislice).
- ``physical_mesh`` is the chip grid (e.g. 4x8 for v5e-32); logical mesh
  axes (data/fsdp/tensor/sequence/expert) are laid over it so that
  the most communication-hungry axis rides the fastest ICI dimension.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class SliceTopology:
    """One atomically-schedulable TPU slice."""

    name: str  # e.g. "v5e-32"
    chips: int
    hosts: int  # pods per slice == hosts
    chips_per_host: int
    physical_mesh: Tuple[int, ...]  # chip grid, e.g. (4, 8)
    #: Per-chip peak bf16 TFLOP/s — used for MFU accounting, not scheduling.
    peak_bf16_tflops: float = 197.0
    hbm_gib_per_chip: float = 16.0
    #: Per-chip HBM bandwidth GB/s (spec sheet) — used for bench sanity
    #: floors (a training step cannot beat one full param read from HBM).
    hbm_gbps: float = 819.0
    #: Per-chip aggregate ICI bandwidth GB/s (sum over links, one
    #: direction) — prices intra-slice collectives in the auto-parallelism
    #: planner's cost model (kubedl_tpu/planner/costmodel.py).
    ici_gbps: float = 180.0
    #: Per-chip DCN bandwidth GB/s — prices cross-slice (multislice)
    #: collectives; one to two orders of magnitude below ICI, which is why
    #: only the outermost (replica) mesh axis may cross slices.
    dcn_gbps: float = 6.25

    @property
    def total_devices(self) -> int:
        return self.chips

    def coordinates(self, host_index: int) -> Tuple[int, ...]:
        """Host coordinate within the slice's host grid (row-major)."""
        hosts_mesh = self.host_mesh()
        coord = []
        rem = host_index
        for dim in reversed(hosts_mesh):
            coord.append(rem % dim)
            rem //= dim
        return tuple(reversed(coord))

    def host_mesh(self) -> Tuple[int, ...]:
        """Host grid: physical mesh divided by the per-host chip block.

        v5e hosts own a 2x2 chip block; we fold chips_per_host into the last
        axes of the physical mesh.
        """
        rem = self.chips_per_host
        dims = list(self.physical_mesh)
        # Peel factors of 2 round-robin across dims (last dim first) so the
        # host block comes out near-square (v5e: 2x2), matching hardware.
        i = len(dims) - 1
        stuck = 0
        while rem > 1 and stuck < len(dims):
            if dims[i] % 2 == 0:
                dims[i] //= 2
                rem //= 2
                stuck = 0
            else:
                stuck += 1
            i = (i - 1) % len(dims)
        if rem > 1:  # non-power-of-two remainder: divide any divisible dim
            for j, d in enumerate(dims):
                g = math.gcd(d, rem)
                dims[j] //= g
                rem //= g
        return tuple(dims)


#: Catalog of schedulable slice shapes. Peak flops: v4 ~275 bf16 TFLOP/s,
#: v5e ~197, v5p ~459 (public spec-sheet numbers).
SLICE_CATALOG: Dict[str, SliceTopology] = {}


def _register(*topos: SliceTopology) -> None:
    for t in topos:
        SLICE_CATALOG[t.name] = t


_register(
    # v5e: 1 host = 4 chips (2x2), 197 bf16 TFLOP/s, 16 GiB HBM
    SliceTopology("v5e-4", 4, 1, 4, (2, 2), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-8", 8, 2, 4, (2, 4), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-16", 16, 4, 4, (4, 4), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-32", 32, 8, 4, (4, 8), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-64", 64, 16, 4, (8, 8), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-128", 128, 32, 4, (8, 16), 197.0, 16.0, 819.0, 180.0, 6.25),
    SliceTopology("v5e-256", 256, 64, 4, (16, 16), 197.0, 16.0, 819.0, 180.0, 6.25),
    # v4: 1 host = 4 chips, 3D torus, 275 bf16 TFLOP/s, 32 GiB
    SliceTopology("v4-8", 8, 1, 4, (2, 2, 1), 275.0, 32.0, 1228.0, 270.0, 6.25),
    SliceTopology("v4-16", 16, 2, 4, (2, 2, 2), 275.0, 32.0, 1228.0, 270.0, 6.25),
    SliceTopology("v4-32", 32, 4, 4, (2, 2, 4), 275.0, 32.0, 1228.0, 270.0, 6.25),
    SliceTopology("v4-64", 64, 8, 4, (2, 4, 4), 275.0, 32.0, 1228.0, 270.0, 6.25),
    # v5p: 1 host = 4 chips, 459 bf16 TFLOP/s, 95 GiB
    SliceTopology("v5p-8", 8, 2, 4, (2, 2, 1), 459.0, 95.0, 2765.0, 540.0, 6.25),
    SliceTopology("v5p-16", 16, 4, 4, (2, 2, 2), 459.0, 95.0, 2765.0, 540.0, 6.25),
    SliceTopology("v5p-32", 32, 8, 4, (2, 2, 4), 459.0, 95.0, 2765.0, 540.0, 6.25),
    # v6e (Trillium): 1 host = 4 chips, ~918 bf16 TFLOP/s, 32 GiB
    SliceTopology("v6e-4", 4, 1, 4, (2, 2), 918.0, 32.0, 1640.0, 360.0, 12.5),
    SliceTopology("v6e-8", 8, 2, 4, (2, 4), 918.0, 32.0, 1640.0, 360.0, 12.5),
    SliceTopology("v6e-16", 16, 4, 4, (4, 4), 918.0, 32.0, 1640.0, 360.0, 12.5),
    SliceTopology("v6e-32", 32, 8, 4, (4, 8), 918.0, 32.0, 1640.0, 360.0, 12.5),
    # CPU stand-in used by tests / kind-style local clusters
    SliceTopology("cpu-1", 1, 1, 1, (1,), 0.5, 8.0, 50.0, 1.0, 0.5),
    SliceTopology("cpu-8", 8, 8, 1, (8,), 0.5, 8.0, 50.0, 1.0, 0.5),
)


#: device_kind substrings (as PJRT reports them) -> catalog generation token
_DEVICE_KIND_ALIASES = {
    "v5 lite": "v5e", "v5litepod": "v5e", "v5e": "v5e",
    "v6 lite": "v6e", "v6e": "v6e",
    "v5p": "v5p",
    "v4": "v4",
}


def _catalog_lookup(kind: str, getter) -> float:
    """Resolve a PJRT device_kind string to a per-chip spec value via the
    slice catalog (single source of truth for hardware numbers). 0.0 for
    CPU/unknown kinds."""
    kind = kind.lower()
    gens = {t.name.split("-")[0]: getter(t) for t in SLICE_CATALOG.values()}
    for sub, gen in _DEVICE_KIND_ALIASES.items():
        if sub in kind and gen in gens:
            return gens[gen]
    return 0.0


def peak_flops_for_device_kind(kind: str) -> float:
    """Per-chip peak bf16 FLOP/s — used for MFU accounting."""
    return _catalog_lookup(kind, lambda t: t.peak_bf16_tflops * 1e12)


def hbm_bandwidth_for_device_kind(kind: str) -> float:
    """Per-chip HBM bandwidth bytes/s — used for bench sanity floors."""
    return _catalog_lookup(kind, lambda t: t.hbm_gbps * 1e9)


def get_slice(name: str) -> SliceTopology:
    try:
        return SLICE_CATALOG[name]
    except KeyError:
        raise KeyError(
            f"unknown slice type {name!r}; known: {sorted(SLICE_CATALOG)}"
        ) from None


@dataclass
class MeshSpec:
    """Logical mesh laid over one or more slices.

    The operator passes this down as the `KUBEDL_MESH_AXES` env hint; the
    in-process training harness (`kubedl_tpu.parallel.mesh`) turns it into a
    concrete `jax.sharding.Mesh`. Axis order is outermost-first; by
    convention DCN-crossing axes (data across slices) come first and
    ICI-hungry axes (tensor) last, matching the scaling-book recipe.
    """

    axes: Dict[str, int] = field(default_factory=dict)

    #: outermost-first; DCN-crossing (replica/data) out, ICI-hungry in.
    #: "sp" = sequence/context parallel (ring attention), "pipe" = pipeline
    #: stages, "expert" = MoE expert parallel.
    AXIS_ORDER = ("replica", "data", "fsdp", "pipe", "expert", "sp", "tensor")

    def size(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n

    def ordered(self) -> List[Tuple[str, int]]:
        known = [(a, self.axes[a]) for a in self.AXIS_ORDER if a in self.axes]
        extra = [(a, v) for a, v in self.axes.items() if a not in self.AXIS_ORDER]
        return known + extra

    def to_env(self) -> str:
        return ",".join(f"{a}={v}" for a, v in self.ordered())

    @classmethod
    def from_env(cls, s: str) -> "MeshSpec":
        axes: Dict[str, int] = {}
        for part in filter(None, (p.strip() for p in s.split(","))):
            k, _, v = part.partition("=")
            axes[k] = int(v)
        return cls(axes=axes)

    @classmethod
    def for_slice(
        cls, topo: SliceTopology, tensor: int = 1, num_slices: int = 1
    ) -> "MeshSpec":
        """Default mesh: pure data parallel over chips, optionally carving a
        tensor axis out of the fastest ICI dimension; multislice adds an
        outer DCN data axis."""
        chips = topo.chips * num_slices
        if chips % tensor:
            raise ValueError(f"tensor={tensor} does not divide {chips} chips")
        axes: Dict[str, int] = {}
        if num_slices > 1:
            axes["replica"] = num_slices
            chips //= num_slices
        axes["data"] = chips // tensor
        if tensor > 1:
            axes["tensor"] = tensor
        return cls(axes=axes)


def validate_mesh_for_slice(
    mesh: MeshSpec, topo: SliceTopology, num_slices: int = 1
) -> Optional[str]:
    """Return an error message if the logical mesh cannot tile the slice.

    Checked at job admission (workloads validate) so a bad mesh is rejected
    on submit instead of failing inside the worker at ``build_mesh`` time.
    """
    for axis, size in mesh.axes.items():
        if axis not in MeshSpec.AXIS_ORDER:
            return (
                f"unknown mesh axis {axis!r}; known axes: "
                + ", ".join(MeshSpec.AXIS_ORDER)
            )
        if size < 1:
            return f"mesh axis {axis}={size} must be >= 1"
    want = topo.chips * num_slices
    if mesh.size() != want:
        return f"mesh covers {mesh.size()} devices but topology has {want} chips"
    return None

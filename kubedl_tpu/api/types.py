"""Vendor-neutral job/replica model shared by every workload kind.

Capability parity with the reference's common job API
(pkg/job_controller/api/v1/types.go:26-224): ReplicaSpec, JobStatus with
typed conditions, RunPolicy {clean-pod policy, TTL, active deadline, backoff
limit, gang min-available}, RestartPolicy incl. exit-code classification
(1-127 permanent / 128-255 retryable, types.go:169-182), SuccessPolicy, and
DAG startup conditions (types.go:219-224).

TPU-first departures:

- Replicas may pin a :class:`~kubedl_tpu.api.topology.SliceTopology`; the gang
  scheduler treats a slice as atomic (a partially placed ICI job wedges the
  whole slice), so ``SchedulingPolicy.min_available`` defaults to *all* pods.
- Restart semantics are slice-granular by default
  (:attr:`RestartPolicy.ON_FAILURE_SLICE`): one failed worker restarts the
  gang from the latest checkpoint, since ICI collectives cannot survive a
  single lost participant.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.topology import MeshSpec, SliceTopology
from kubedl_tpu.core.objects import PodTemplateSpec


class ReplicaType(str, enum.Enum):
    """Union of replica roles across all workload kinds.

    Reference analogues: TF PS/Worker/Chief/Master/Evaluator
    (apis/training/v1alpha1/tfjob_types.go:79-98), PyTorch Master/Worker, MPI
    Launcher/Worker, XGBoost Master/Worker, Mars Scheduler/Worker/WebService,
    XDL PS/Worker/Scheduler.
    """

    MASTER = "Master"
    CHIEF = "Chief"
    WORKER = "Worker"
    PS = "PS"
    EVALUATOR = "Evaluator"
    SCHEDULER = "Scheduler"
    LAUNCHER = "Launcher"
    WEBSERVICE = "WebService"


class RestartPolicy(str, enum.Enum):
    """Per-replica restart policy (reference: types.go:169-182)."""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    #: Restart only on retryable exit codes 128-255 (reference: ExitCode
    #: policy, pkg/job_controller/pod.go:305-317, pkg/util/train/train_util.go).
    EXIT_CODE = "ExitCode"
    #: TPU addition: any worker failure restarts the whole gang from the
    #: latest checkpoint (ICI jobs die whole-slice; SURVEY.md §7 hard part b).
    ON_FAILURE_SLICE = "OnFailureSlice"


#: Exit codes in [1, 127] are permanent failures; [128, 255] retryable
#: (reference: pkg/util/train/train_util.go).
RETRYABLE_EXIT_CODE_MIN = 128


def is_retryable_exit_code(code: int) -> bool:
    return code >= RETRYABLE_EXIT_CODE_MIN


class CleanPodPolicy(str, enum.Enum):
    """What to delete when a job terminates (reference: types.go:188-199)."""

    RUNNING = "Running"  # delete only still-running pods
    ALL = "All"
    NONE = "None"


class SuccessPolicy(str, enum.Enum):
    """When a job counts as succeeded (reference: types.go:146-153)."""

    #: Chief/master completion, or worker-0 for master-less jobs
    #: (reference: controllers/tensorflow/status.go:56-215).
    DEFAULT = "Default"
    ALL_WORKERS = "AllWorkers"


class JobConditionType(str, enum.Enum):
    """Job lifecycle conditions (reference: types.go:117-143)."""

    CREATED = "Created"
    QUEUED = "Queued"  # TPU addition: gang admitted, waiting for slice
    RUNNING = "Running"
    RESTARTING = "Restarting"
    #: TPU addition (elastic slice scaling): the gang was resized IN PLACE
    #: (partial slice release/reserve, kubedl_tpu/elastic/) and replicas
    #: are restarting from checkpoint at the new world size — unlike
    #: RESTARTING, the job never released its remaining slices.
    RESIZING = "Resizing"
    #: TPU addition (kueue-style): pods torn down, slices FREED, progress
    #: kept via checkpoints; unsuspending re-admits and resumes
    SUSPENDED = "Suspended"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    #: TPU addition (poison-pill protection): reconcile raised repeatedly,
    #: the job is parked — pods torn down, slices freed — instead of
    #: hot-looping the workqueue. NOT terminal: the job is neither
    #: succeeded nor failed, it is awaiting operator intervention.
    QUARANTINED = "Quarantined"
    #: TPU addition (auto-parallelism planner, kubedl_tpu/planner/): the
    #: cost model chose a mesh layout for this (topology, world size) and
    #: the engine injected it via KUBEDL_MESH_AXES. Informational — it does
    #: not gate the phase machine; message carries the chosen layout plus
    #: predicted step time / HBM. Re-stamped after every elastic resize.
    PLANNED = "Planned"
    #: TPU addition (progress watchdog, kubedl_tpu/watchdog/): a replica
    #: stopped making training progress WITHOUT exiting — a wedged step
    #: loop (hang), a host whose beacons stopped while the pod stayed
    #: RUNNING (silent death). The watchdog fails the replica retryably,
    #: so the next reconcile takes the normal gang-restart path and
    #: supersedes this condition with RESTARTING. NOT terminal.
    HANG_DETECTED = "HangDetected"


TERMINAL_CONDITIONS = (JobConditionType.SUCCEEDED, JobConditionType.FAILED)


class ReplicaPhase(str, enum.Enum):
    """Aggregate phase a DAG condition can gate on (reference:
    dag_sched.go:92-106 phase comparator)."""

    CREATED = "Created"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"

    def rank(self) -> int:
        return {"Created": 0, "Running": 1, "Succeeded": 2}[self.value]


@dataclass
class DAGCondition:
    """Startup-ordering edge: this replica type waits until ``upstream``
    reaches ``on_phase`` (reference: types.go:219-224, dag_sched.go:29-68)."""

    upstream: ReplicaType
    on_phase: ReplicaPhase = ReplicaPhase.RUNNING


@dataclass
class SchedulingPolicy:
    """Gang scheduling knobs (reference: types.go:206-217).

    ``min_available=None`` means *all* replicas — the TPU default, since
    partial placement wedges a slice.
    """

    min_available: Optional[int] = None
    queue: str = "default"
    priority: int = 0


@dataclass
class ElasticSpec:
    """Elastic slice-scaling bounds (kubedl_tpu/elastic/): the gang size
    becomes a runtime variable in ``[min_slices, max_slices]``. The
    ElasticPolicy controller shrinks jobs off draining (preemption-noticed)
    slices and grows them back into free capacity, with ``cooldown_seconds``
    of hysteresis between voluntary grows (shrinks are urgent and bypass
    it). Reference analogue: ElasticDL's master-driven worker scaling
    (controllers/elasticdl/) — TPU-native semantics are whole-gang
    restart-from-checkpoint at the new shape."""

    min_slices: int = 1
    max_slices: int = 1
    #: minimum seconds between voluntary (grow) resizes of one job
    cooldown_seconds: float = 30.0

    def validate(self, prefix: str = "elastic") -> List[str]:
        errs: List[str] = []
        if self.min_slices < 1:
            errs.append(f"{prefix}.minSlices must be >= 1")
        if self.max_slices < self.min_slices:
            errs.append(f"{prefix}.maxSlices must be >= minSlices")
        if self.cooldown_seconds < 0:
            errs.append(f"{prefix}.cooldownSeconds must be >= 0")
        return errs

    def clamp(self, n: int) -> int:
        return max(self.min_slices, min(n, self.max_slices))


@dataclass
class AggregationSpec:
    """Gradient-aggregation mode (kubedl_tpu/ps/, docs/elasticity.md
    "Parameter-service mode"). ``mode: "sync"`` is the default synchronous
    gang — every resize is a whole-gang restart-from-checkpoint.
    ``mode: "ps"`` hash-partitions the model across ``ps_shards``
    parameter-service shards; workers push parameter deltas and pull fresh
    shards asynchronously under a bounded-staleness window, so a worker
    departure (preemption notice, watchdog eviction, chaos kill) never
    stops the survivors."""

    #: "sync" (gang restart on every membership change) or "ps"
    mode: str = "sync"
    #: parameter-service shards the model is hash-partitioned across
    ps_shards: int = 2
    #: bounded staleness: a push whose pulled shard version lags the
    #: shard head by more than this many aggregate steps is REJECTED and
    #: the worker re-pulls; pushes within the bound are decay-weighted
    max_staleness: int = 4
    #: per-step-of-staleness decay applied to in-bound stale pushes
    #: (weight = decay ** staleness)
    decay: float = 0.5
    #: worker cadence: push the accumulated delta every N local steps
    push_every: int = 1

    def validate(self, prefix: str = "aggregation") -> List[str]:
        errs: List[str] = []
        if self.mode not in ("sync", "ps"):
            errs.append(f'{prefix}.mode must be "sync" or "ps"')
        if self.ps_shards < 1:
            errs.append(f"{prefix}.psShards must be >= 1")
        if self.max_staleness < 0:
            errs.append(f"{prefix}.maxStaleness must be >= 0")
        if not (0.0 < self.decay <= 1.0):
            errs.append(f"{prefix}.decay must be in (0, 1]")
        if self.push_every < 1:
            errs.append(f"{prefix}.pushEvery must be >= 1")
        return errs


@dataclass
class RunPolicy:
    """Job-level execution policy (reference: types.go:188-217)."""

    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.RUNNING
    ttl_seconds_after_finished: Optional[float] = None
    active_deadline_seconds: Optional[float] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: SchedulingPolicy = field(default_factory=SchedulingPolicy)
    #: Suspend execution (kueue-style, net-new vs reference): pods are torn
    #: down and the gang's SLICES ARE RELEASED for other jobs; flipping
    #: back re-admits and training resumes from the latest checkpoint.
    suspend: bool = False


@dataclass
class ReplicaSpec:
    """Desired state for one replica type (reference: types.go:75-95)."""

    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE_SLICE
    #: TPU: the slice this replica group collectively occupies. One pod per
    #: TPU host; replicas must equal topology.hosts when set.
    topology: Optional[SliceTopology] = None
    #: Logical mesh hint passed to the workload (data/fsdp/tensor/... axes).
    mesh: Optional[MeshSpec] = None
    #: DAG-ordered startup: wait for these upstreams first.
    depends_on: List[DAGCondition] = field(default_factory=list)


@dataclass
class ReplicaStatus:
    """Observed counts per replica type (reference: types.go:53-73)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0
    evicted: int = 0  # counted separately (reference: types.go:68-70)


@dataclass
class JobCondition:
    """One observed lifecycle condition (reference: types.go:98-115)."""

    type: JobConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class PlanStatus:
    """The auto-parallelism planner's published verdict (kubedl_tpu/planner/).

    Surfaced on JobStatus so ``kubectl get -o yaml`` shows the chosen
    layout and predictions without digging through events; refreshed after
    every elastic resize (the plan is keyed on (topology, num_slices))."""

    #: chosen layout in KUBEDL_MESH_AXES form, e.g. "data=4,fsdp=2"
    mesh: str = ""
    topology: str = ""
    num_slices: int = 1
    predicted_step_ms: float = 0.0
    predicted_hbm_gib: float = 0.0
    candidates_evaluated: int = 0
    #: host wall time plan() spent (budgeted in scheduler_microbench.py)
    plan_ms: float = 0.0


@dataclass
class JobStatus:
    """Observed job state (reference: types.go:26-51)."""

    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[ReplicaType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    #: Cumulative restart count, compared against RunPolicy.backoff_limit
    #: (reference: job.go:141-159, :396-435).
    restart_count: int = 0
    #: Name of the ModelVersion created on success, if any.
    model_version: str = ""
    #: Auto-parallelism planner verdict; None until a plan is computed
    #: (only jobs with a modelDesc / mesh:auto get one).
    plan: Optional[PlanStatus] = None

    # ---- condition helpers (reference: pkg/util/status.go) ----------------

    def condition(self, ctype: JobConditionType) -> Optional[JobCondition]:
        for c in self.conditions:
            if c.type == ctype and c.status:
                return c
        return None

    @property
    def phase(self) -> Optional[JobConditionType]:
        """Latest true condition, i.e. the current phase."""
        return self.conditions[-1].type if self.conditions else None

    def is_terminal(self) -> bool:
        return self.phase in TERMINAL_CONDITIONS

    def is_succeeded(self) -> bool:
        return self.phase == JobConditionType.SUCCEEDED

    def is_failed(self) -> bool:
        return self.phase == JobConditionType.FAILED

    def set_condition(
        self, ctype: JobConditionType, reason: str = "", message: str = ""
    ) -> bool:
        """Append/refresh a condition; newest-true-wins phase semantics.

        Returns True if the phase actually changed (callers use this to emit
        events/metrics exactly once per transition).
        """
        if self.phase == ctype:
            cur = self.conditions[-1]
            cur.reason, cur.message = reason or cur.reason, message or cur.message
            return False
        # Flip previous same-type stale entries off, then append.
        for c in self.conditions:
            if c.type == ctype:
                self.conditions.remove(c)
                break
        self.conditions.append(
            JobCondition(type=ctype, status=True, reason=reason, message=message)
        )
        return True


@dataclass
class JobSpec:
    """The common portion of every workload kind's spec.

    Workload kinds (TPUJob, TorchXLAJob, ...) embed this and add their own
    knobs, the way the reference's TFJobSpec/PyTorchJobSpec embed
    ReplicaSpecs + RunPolicy (apis/training/v1alpha1/tfjob_types.go:30-77).
    """

    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    success_policy: SuccessPolicy = SuccessPolicy.DEFAULT
    #: Build a ModelVersion from the job's model output on success
    #: (reference: apis/training/v1alpha1/tfjob_types.go:51-53).
    model_version: Optional["ModelVersionSpecRef"] = None

    def total_replicas(self) -> int:
        return sum(rs.replicas for rs in self.replica_specs.values())

    def min_available(self) -> int:
        ma = self.run_policy.scheduling_policy.min_available
        return self.total_replicas() if ma is None else ma


@dataclass
class ModelVersionSpecRef:
    """Inline request to publish the job's output as a ModelVersion
    (mirrors apis/model/v1alpha1/modelversion_types.go:35-70)."""

    model_name: str = ""
    image_repo: str = ""
    storage_root: str = ""  # host path / NFS root holding the artifact
    #: storage-union member (reference: modelversion_types.go:72-115):
    #: "shared" (NFS/EFS-style, default — multi-host jobs need it),
    #: "local" (node-pinned), or a registered plugin name
    storage_provider: str = "shared"


def job_spec_defaults(spec: JobSpec) -> JobSpec:
    """Apply defaulting the way the reference's scheme.Default does
    (apis/training/v1alpha1/*_defaults.go): fill replica counts, port, and
    clamp replicas to slice topology when one is pinned."""
    for rs in spec.replica_specs.values():
        if rs.replicas <= 0:
            rs.replicas = 1
        if rs.topology is not None:
            rs.replicas = rs.topology.hosts
        rs.template.apply_defaults()
    return spec



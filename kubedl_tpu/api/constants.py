"""Well-known labels, annotations and environment variable names.

Reference analogue: pkg/job_controller/api/v1/constants.go:5-61 (label/
annotation constants) and pkg/apis label conventions. Names are re-derived for
the TPU build (`kubedl-tpu.io/` prefix) — the *semantics* match the reference:
pods are claimed by label selector {group-name, job-name, replica-type,
replica-index, job-role}, and per-job opt-in features ride annotations.
"""

API_GROUP = "kubedl-tpu.io"

# ---- Labels stamped on every pod/service the engine creates --------------
# (reference: pkg/job_controller/pod.go:343-357 label block)
LABEL_GROUP_NAME = API_GROUP + "/group-name"
LABEL_JOB_NAME = API_GROUP + "/job-name"
LABEL_JOB_KIND = API_GROUP + "/job-kind"
LABEL_REPLICA_TYPE = API_GROUP + "/replica-type"
LABEL_REPLICA_INDEX = API_GROUP + "/replica-index"
LABEL_JOB_ROLE = API_GROUP + "/job-role"
LABEL_GANG_NAME = API_GROUP + "/gang-name"
LABEL_CRON_NAME = API_GROUP + "/cron-name"  # reference: cron_controller.go:296-346
LABEL_MODEL_NAME = API_GROUP + "/model-name"

JOB_ROLE_MASTER = "master"

# ---- Annotations (per-job opt-in features) -------------------------------
# reference: pkg/job_controller/api/v1/constants.go:26-42
ANNOTATION_GIT_SYNC_CONFIG = API_GROUP + "/git-sync-config"
ANNOTATION_TENSORBOARD_CONFIG = API_GROUP + "/tensorboard-config"
ANNOTATION_NETWORK_MODE = API_GROUP + "/network-mode"
ANNOTATION_TENANCY = API_GROUP + "/tenancy"
ANNOTATION_OWNER = API_GROUP + "/owner"  # reference: tenancy.go:25-43 user field
ANNOTATION_PROFILER_CONFIG = API_GROUP + "/profiler-config"  # TPU addition
#: monotonic timestamp stamped when the controller began draining a
#: predictor pod ahead of scale-down/GC (docs/serving.md "Router")
ANNOTATION_DRAIN_STARTED = API_GROUP + "/drain-started"
#: world size (total processes) the job was SUBMITTED with — stamped once
#: at first defaulting and stable across elastic resizes, so workers can
#: rescale gradient accumulation to preserve the effective global batch
ANNOTATION_ELASTIC_BASE_WORLD = API_GROUP + "/elastic-base-world"
#: the auto-parallelism planner's cached verdict (kubedl_tpu/planner/):
#: compact JSON {axes, topology, slices, step_ms, hbm_gib}. The cache key
#: is (topology, slices) — an elastic resize changes slices, so the next
#: reconcile re-plans for the new world size (docs/planning.md).
ANNOTATION_PLANNED_MESH = API_GROUP + "/planned-mesh"
#: data-parallel world (replica*data*fsdp of the FIRST plan) — the planner
#: analogue of elastic-base-world: workers rescale grad accumulation
#: against the planned DP degree, not the raw process count, because a
#: re-plan may move chips between data and model axes on resize
ANNOTATION_ELASTIC_BASE_DP = API_GROUP + "/elastic-base-dp"

#: Address of the job's parameter-service tier ("host:port"), stamped by
#: whoever runs it (the PS front is job-external so worker restarts never
#: move it); workers in ``aggregation.mode: ps`` read ENV_PS_ADDR from it.
ANNOTATION_PS_ADDRESS = API_GROUP + "/ps-address"

NETWORK_MODE_HOST = "host"

# ---- Environment variables injected into replicas ------------------------
# TPU bootstrap (replaces TF_CONFIG / MASTER_ADDR / hostfile wiring;
# consumed by jax.distributed.initialize in the worker container):
ENV_COORDINATOR_ADDRESS = "KUBEDL_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KUBEDL_NUM_PROCESSES"
ENV_PROCESS_ID = "KUBEDL_PROCESS_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_SLICE_TOPOLOGY = "KUBEDL_SLICE_TOPOLOGY"  # e.g. "v5e-32:4x8"
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"  # multislice DCN
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_MESH_AXES = "KUBEDL_MESH_AXES"  # logical mesh hint, e.g. "data=4,model=8"

# Elastic slice scaling (kubedl_tpu/elastic/): the base world size rides
# every elastic worker's env so entry.py can rescale grad accumulation
# (effective global batch is preserved across resizes); min/max ride the
# ElasticDLJob master's env (the reference's master scales its own workers).
ENV_ELASTIC_BASE_WORLD = "KUBEDL_ELASTIC_BASE_WORLD"
#: base data-parallel degree from the planner's first plan; when present,
#: entry.py rescales grad accumulation from base-dp -> current-dp (read
#: off KUBEDL_MESH_AXES) instead of base-world -> world
ENV_ELASTIC_BASE_DP = "KUBEDL_ELASTIC_BASE_DP"
ENV_ELASTIC_MIN_SLICES = "KUBEDL_ELASTIC_MIN_SLICES"
ENV_ELASTIC_MAX_SLICES = "KUBEDL_ELASTIC_MAX_SLICES"
ENV_ELASTIC_NUM_SLICES = "KUBEDL_ELASTIC_NUM_SLICES"

# Parameter-service aggregation (kubedl_tpu/ps/, docs/elasticity.md
# "Parameter-service mode"): a TPUJob whose `aggregation.mode` is "ps"
# stamps the service address and the staleness knobs onto every worker so
# entry.py takes the asynchronous push/pull arm instead of trainer.fit.
ENV_PS_ADDR = "KUBEDL_PS_ADDR"
ENV_PS_SHARDS = "KUBEDL_PS_SHARDS"
ENV_PS_MAX_STALENESS = "KUBEDL_PS_MAX_STALENESS"
ENV_PS_DECAY = "KUBEDL_PS_DECAY"
ENV_PS_PUSH_EVERY = "KUBEDL_PS_PUSH_EVERY"

# Model-output convention (reference: apis/model/v1alpha1/
# modelversion_types.go:23-33 — KUBEDL_MODEL_PATH + /kubedl-model):
ENV_MODEL_PATH = "KUBEDL_MODEL_PATH"
DEFAULT_MODEL_PATH = "/kubedl-model"
#: Checkpoint root for slice-granular restart-from-checkpoint (SURVEY.md §7
#: hard-part b). Defaults to <model path>/checkpoints when unset.
ENV_CKPT_DIR = "KUBEDL_CKPT_DIR"
#: Persistent XLA compilation-cache dir, operator-injected alongside the
#: checkpoint dir so gang restarts / resizes / resumes warm-hit instead of
#: re-paying first-step compile (VERDICT.md round-2 weak #1).
ENV_COMPILE_CACHE_DIR = "KUBEDL_COMPILE_CACHE_DIR"
#: Progress-beacon file (kubedl_tpu/watchdog/): operator-injected per-pod
#: path where the worker's beacon thread stamps {step, tokens, ts}; the
#: kubelet heartbeat publishes it onto the pod's Node object and the
#: watchdog classifies hangs/stragglers/silent deaths from it.
ENV_BEACON_FILE = "KUBEDL_BEACON_FILE"
#: seconds between beacon stamps (default 0.5)
ENV_BEACON_INTERVAL = "KUBEDL_BEACON_INTERVAL"
#: Peer replica root for async checkpointing (training/checkpoint.py):
#: a remote blob URL (http://host:port/prefix) each process mirrors its
#: shard files to, so restore-from-latest survives losing the owning
#: host's local checkpoint dir (preference: local -> peer -> blob store).
ENV_CKPT_PEER = "KUBEDL_CKPT_PEER"

# Default port every replica's coordinator/service listens on.
DEFAULT_PORT = 2222
DEFAULT_PORT_NAME = "kubedl-port"

# Host-network random port range (reference: pkg/job_controller/pod.go:470-486)
HOST_PORT_RANGE = (30001, 65535)

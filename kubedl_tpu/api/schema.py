"""JSON Schema generation from the API dataclasses.

The reference ships generated CRD OpenAPI schemas for every kind
(config/crd/bases/*.yaml, produced by controller-gen from the Go types).
The TPU build's types are dataclasses, so the schemas are derived by
reflection instead of codegen: :func:`json_schema` walks a dataclass's
type hints (enums, Optional, List/Dict/Tuple, nested dataclasses) into
draft-07 JSON Schema, and :func:`workload_schemas` emits one per
registered kind — the deploy surface's CRD-equivalent artifacts
(rendered into ``deploy/schemas/`` by ``deploy/render.py``).

Validation semantics match the codec: unknown fields are rejected
(`additionalProperties: false`), exactly as `kubedl_tpu.api.codec.decode`
raises on unknown keys.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Optional, Union


def _field_schema(tp: Any, defs: Dict[str, Any]) -> Dict[str, Any]:
    origin = typing.get_origin(tp)

    if tp is Any or tp is None or tp is type(None):
        return {}
    if origin is Union:
        args = list(typing.get_args(tp))
        nullable = type(None) in args
        args = [a for a in args if a is not type(None)]
        inner = (
            _field_schema(args[0], defs)
            if len(args) == 1
            else {"anyOf": [_field_schema(a, defs) for a in args]}
        )
        if nullable:
            return {"anyOf": [inner, {"type": "null"}]} if inner else {}
        return inner
    if origin in (list, tuple):
        args = typing.get_args(tp)
        elem = args[0] if args and args[0] is not Ellipsis else Any
        return {"type": "array", "items": _field_schema(elem, defs)}
    if origin is dict:
        args = typing.get_args(tp)
        vt = args[1] if len(args) == 2 else Any
        return {
            "type": "object",
            "additionalProperties": _field_schema(vt, defs) or True,
        }
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        name = tp.__name__
        if name not in defs:
            defs[name] = {"enum": [m.value for m in tp]}
        return {"$ref": f"#/definitions/{name}"}
    if dataclasses.is_dataclass(tp):
        name = tp.__name__
        if name not in defs:
            defs[name] = {"type": "object"}  # placeholder breaks cycles
            defs[name] = _dataclass_schema(tp, defs)
        return {"$ref": f"#/definitions/{name}"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    if tp is str:
        return {"type": "string"}
    return {}  # unknown/opaque types: unconstrained


def _dataclass_schema(cls: type, defs: Dict[str, Any]) -> Dict[str, Any]:
    try:
        hints = typing.get_type_hints(cls)
    except Exception:
        hints = {f.name: f.type for f in dataclasses.fields(cls)}
    props: Dict[str, Any] = {}
    required = []
    for f in dataclasses.fields(cls):
        if not f.init:
            continue
        props[f.name] = _field_schema(hints.get(f.name, Any), defs)
        no_default = (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        )
        if no_default:
            required.append(f.name)
    out: Dict[str, Any] = {
        "type": "object",
        "properties": props,
        "additionalProperties": False,
    }
    if required:
        out["required"] = required
    return out


def json_schema(cls: type, kind: Optional[str] = None) -> Dict[str, Any]:
    """Draft-07 JSON Schema for one API dataclass."""
    defs: Dict[str, Any] = {}
    body = _dataclass_schema(cls, defs)
    # stored objects carry the kind discriminator the codec dispatches on
    if kind:
        body["properties"] = {
            "kind": {"const": kind},
            **body["properties"],
        }
    out = {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "title": kind or cls.__name__,
        **body,
    }
    if defs:
        out["definitions"] = defs
    return out


def workload_schemas() -> Dict[str, Dict[str, Any]]:
    """One schema per registered workload kind plus the lineage/serving/
    cron kinds — the CRD-equivalent artifact set."""
    from kubedl_tpu.api.codec import known_kinds

    # substrate kinds (users never author these), not workload CRDs —
    # the crash-recovery WAL registers them in the codec, but they don't
    # belong in the rendered schema artifact set
    skip = {
        "Pod", "Service", "ConfigMap", "Event", "TrafficPolicy",
        "PodGroup", "Node", "IngressRoute", "Lease",
    }
    return {
        kind: json_schema(cls, kind=kind)
        for kind, cls in sorted(known_kinds().items())
        if kind not in skip
    }

"""Typed object codec: JSON dicts <-> API dataclasses.

The reference shuttles workloads through ``runtime.RawExtension`` and a
scheme-backed codec (pkg/util/runtime/runtime.go; console submit path
console/backend/pkg/routers/api/job.go:29-43 decodes user YAML/JSON into
typed CRD structs). The TPU build's analogue: :func:`encode` lowers any API
dataclass to plain JSON types (delegating to
:func:`kubedl_tpu.persist.dmo.to_jsonable`), and :func:`decode` reconstructs
a typed object from that JSON using dataclass type hints — enums, nested
dataclasses, ``Optional``/``List``/``Dict``/``Tuple`` included.

``decode_object`` dispatches on the ``kind`` discriminator through a kind
registry covering every stored kind (workload jobs, Model/ModelVersion,
Inference, Cron, core objects), the way the reference's scheme maps GVKs to
Go types (apis/apis.go:25).
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Optional, Type, Union

from kubedl_tpu.persist.dmo import to_jsonable


def encode(obj: Any) -> Any:
    """Lower a typed API object to plain JSON types. Stored objects carry
    their ``kind`` discriminator so :func:`decode_object` can round-trip."""
    data = to_jsonable(obj)
    kind = getattr(obj, "KIND", None)
    if isinstance(data, dict) and isinstance(kind, str):
        data = {"kind": kind, **data}
    return data


class DecodeError(Exception):
    pass


def _decode_value(tp: Any, data: Any, path: str) -> Any:
    """Reconstruct ``data`` as an instance of type ``tp``."""
    if data is None:
        return None

    origin = typing.get_origin(tp)

    if tp is Any or tp is None or tp is type(None):
        return data

    if origin is Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:  # Optional[T]
            return _decode_value(args[0], data, path)
        # Mixed unions: try each member in order.
        last: Optional[Exception] = None
        for a in args:
            try:
                return _decode_value(a, data, path)
            except Exception as e:  # noqa: BLE001 — tries next member
                last = e
        raise DecodeError(f"{path}: no union member of {tp} accepted {data!r}") from last

    if origin in (list, tuple):
        args = typing.get_args(tp)
        if not isinstance(data, (list, tuple)):
            raise DecodeError(f"{path}: expected list, got {type(data).__name__}")
        if origin is tuple:
            if len(args) == 2 and args[1] is Ellipsis:
                return tuple(
                    _decode_value(args[0], v, f"{path}[{i}]")
                    for i, v in enumerate(data)
                )
            if not args:
                return tuple(data)
            if len(args) != len(data):
                raise DecodeError(
                    f"{path}: expected {len(args)}-tuple, got {len(data)} elements"
                )
            return tuple(
                _decode_value(a, v, f"{path}[{i}]")
                for i, (a, v) in enumerate(zip(args, data))
            )
        elem = args[0] if args else Any
        return [_decode_value(elem, v, f"{path}[{i}]") for i, v in enumerate(data)]

    if origin is dict:
        kt, vt = (typing.get_args(tp) or (Any, Any))
        if not isinstance(data, dict):
            raise DecodeError(f"{path}: expected object, got {type(data).__name__}")
        return {
            _decode_value(kt, k, f"{path}.<key>"): _decode_value(vt, v, f"{path}.{k}")
            for k, v in data.items()
        }

    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        try:
            return tp(data)
        except ValueError as e:
            raise DecodeError(f"{path}: {data!r} is not a valid {tp.__name__}") from e

    if dataclasses.is_dataclass(tp):
        return decode(tp, data, path)

    if tp is float and isinstance(data, (int, float)):
        return float(data)
    if tp is int and isinstance(data, bool):
        raise DecodeError(f"{path}: expected int, got bool")
    if tp is int and isinstance(data, float) and data.is_integer():
        return int(data)
    if isinstance(tp, type) and isinstance(data, tp):
        return data
    # Forward references that failed to resolve, typing aliases, etc.: pass
    # through rather than guessing.
    if not isinstance(tp, type):
        return data
    raise DecodeError(f"{path}: cannot decode {data!r} as {tp}")


def decode(cls: Type, data: Any, path: str = "$") -> Any:
    """Build ``cls`` (a dataclass) from a plain-JSON dict."""
    if not dataclasses.is_dataclass(cls):
        return _decode_value(cls, data, path)
    if isinstance(data, cls):
        return data
    if not isinstance(data, dict):
        raise DecodeError(f"{path}: expected object for {cls.__name__}")
    try:
        hints = typing.get_type_hints(cls)
    except Exception:  # un-importable forward refs: fall back to raw annotations
        hints = {f.name: f.type for f in dataclasses.fields(cls)}
    kwargs: Dict[str, Any] = {}
    known = {f.name for f in dataclasses.fields(cls) if f.init}
    for key, value in data.items():
        if key in ("kind", "apiVersion") and key not in known:
            continue  # discriminators handled by decode_object
        if key not in known:
            raise DecodeError(f"{path}.{key}: unknown field for {cls.__name__}")
        kwargs[key] = _decode_value(hints.get(key, Any), value, f"{path}.{key}")
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise DecodeError(f"{path}: cannot construct {cls.__name__}: {e}") from e


# ---- kind registry --------------------------------------------------------

import threading as _threading

_KINDS: Dict[str, Type] = {}
_KINDS_LOCK = _threading.Lock()
_BUILTINS_LOADED = False


def register_kind(cls: Type) -> Type:
    with _KINDS_LOCK:
        _KINDS[cls.KIND] = cls
    return cls


def _ensure_builtin_kinds() -> None:
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    with _KINDS_LOCK:
        if _BUILTINS_LOADED:
            return

        from kubedl_tpu.core import objects as co

        for cls in (
            co.Pod, co.Service, co.ConfigMap, co.Event,
            co.PodGroup, co.Node, co.IngressRoute,
        ):
            _KINDS.setdefault(cls.KIND, cls)

        # Lease rides the store too (WAL replay must round-trip it for the
        # leader-failover drill); leases imports store, store imports codec
        # lazily, so this import is cycle-safe here
        from kubedl_tpu.core.leases import Lease

        _KINDS.setdefault(Lease.KIND, Lease)

        from kubedl_tpu.cron.types import Cron
        from kubedl_tpu.lineage.types import Model, ModelVersion
        from kubedl_tpu.serving.types import Inference, TrafficPolicy

        for cls in (Cron, Model, ModelVersion, Inference, TrafficPolicy):
            _KINDS.setdefault(cls.KIND, cls)

        from kubedl_tpu.workloads import registry  # registers builtins on import

        for kind, factory in registry.WORKLOAD_REGISTRY.items():
            try:
                obj_cls = type(factory().object_factory())
            except Exception:
                continue
            _KINDS.setdefault(kind, obj_cls)
        _BUILTINS_LOADED = True


def known_kinds() -> Dict[str, Type]:
    _ensure_builtin_kinds()
    with _KINDS_LOCK:
        return dict(_KINDS)


def decode_object(data: Dict[str, Any]):
    """Decode a full stored object, dispatching on ``data["kind"]``."""
    _ensure_builtin_kinds()
    kind = data.get("kind", "")
    with _KINDS_LOCK:
        cls = _KINDS.get(kind)
    if cls is None:
        raise DecodeError(f"unknown kind {kind!r} (known: {sorted(_KINDS)})")
    return decode(cls, data)

"""The generic job-controller engine shared by every workload kind.

Reference: pkg/job_controller/ — `ReconcileJobs` (job.go:68-308),
`ReconcilePods` (pod.go:214-323), `ReconcileServices` (service.go:190-237).
One engine instance serves one workload controller; the flow per reconcile:

1. expectations gate (expectations.go:28-47)
2. gang create + atomic slice admission (job.go:99-104; TPU: admission is
   ours, not kube-batch's)
3. code-sync injection (job.go:108-112)
4. backoff-limit / active-deadline checks (job.go:141-165)
5. terminal jobs: clean pods per CleanPodPolicy, release gang, TTL
   cleanup, ModelVersion creation (job.go:168-222, :341-382, :437-461)
6. per-replica-type loop in reconcile order with DAG gating (job.go:233-270)
   -> diff-by-index pod reconcile with restart policies (pod.go:214-387),
   headless service per replica (service.go:190-307)
7. status machine + launch-delay metrics + optimistic status write
   (job.go:272-307)

TPU-first behavioural changes, on purpose:
- Pods are only created AFTER gang admission (atomic slice semantics);
  the reference creates pods eagerly and lets kube-batch hold them.
- `RestartPolicy.ON_FAILURE_SLICE` restarts the whole gang on any worker
  failure (ICI jobs die whole-slice) instead of per-pod restart.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubedl_tpu.api import constants
from kubedl_tpu.observability.tensorboard import TensorBoardReconciler
from kubedl_tpu.observability.tracing import TRACER, trace_for_job
from kubedl_tpu.api.interface import JobObject, ReconcileContext, WorkloadController
from kubedl_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    PlanStatus,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    is_retryable_exit_code,
)
from kubedl_tpu.codesync.sync import inject_code_sync, parse_git_sync
from kubedl_tpu.core.manager import EventRecorder
from kubedl_tpu.core.objects import (
    Container,
    EnvVar,
    OwnerRef,
    Pod,
    PodPhase,
    Port,
    Service,
    Volume,
)
from kubedl_tpu.core.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubedl_tpu.engine import dag
from kubedl_tpu.engine import status as status_machine
from kubedl_tpu.federation.actuation import assert_fenced_actuation
from kubedl_tpu.engine.expectations import (
    ControllerExpectations,
    ShardedExpectations,
    expectation_key,
)
from kubedl_tpu.gang.interface import GangScheduler
from kubedl_tpu.observability.metrics import DEFAULT_JOB_METRICS, JobMetrics
from kubedl_tpu.utils.features import (
    DAG_SCHEDULING,
    DEFAULT_GATES,
    FeatureGates,
    GANG_SCHEDULING,
    HOST_NETWORK,
)

log = logging.getLogger("kubedl_tpu.engine")


def job_key(job: JobObject) -> str:
    return f"{job.metadata.namespace}/{job.metadata.name}"


def replica_name(job: JobObject, rtype: ReplicaType, index: int) -> str:
    """`<job>-<rtype>-<index>` (reference: pod.go:412-415 naming)."""
    return f"{job.metadata.name}-{rtype.value.lower()}-{index}"


class JobEngine:
    def __init__(
        self,
        store: ObjectStore,
        controller: WorkloadController,
        recorder: Optional[EventRecorder] = None,
        gang_scheduler: Optional[GangScheduler] = None,
        metrics: Optional[JobMetrics] = None,
        features: Optional[FeatureGates] = None,
        cluster_domain: str = "",
        compile_cache_dir: str = "",
        beacon_dir: str = "",
    ) -> None:
        self.store = store
        self.controller = controller
        self.recorder = recorder or EventRecorder(store)
        self.gang = gang_scheduler
        self.metrics = metrics or DEFAULT_JOB_METRICS
        self.features = features or DEFAULT_GATES
        self.cluster_domain = cluster_domain
        self.compile_cache_dir = compile_cache_dir
        self.beacon_dir = beacon_dir
        # per-reconcile-domain expectation caches against a sharded store,
        # so shard failover clears one domain instead of the whole world
        num_shards = getattr(store, "num_shards", 1)
        if num_shards > 1:
            self.expectations = ShardedExpectations(
                store.shard_for_key, num_shards
            )
        else:
            self.expectations = ControllerExpectations()
        #: poison-pill protection: consecutive reconcile exceptions per job
        #: before it is parked with a Quarantined condition instead of
        #: hot-looping the workqueue forever (docs/robustness.md)
        self.quarantine_budget = 5
        self._reconcile_failures: Dict[str, int] = {}
        #: per-job-uid milestone names already traced (job.submit/plan/
        #: gang_bind/pod_launch fire once per job, not once per reconcile)
        self._job_trace_marks: Dict[str, set] = {}
        # per-job TensorBoard lifecycle (reference: tfjob_controller.go:171-177
        # calls ReconcileTensorBoard each pass; generic here — any kind may
        # carry the annotation)
        self.tensorboard = TensorBoardReconciler(store, cluster_domain)
        self._rng = random.Random(0xC0FFEE)
        self._port_lock = threading.Lock()
        self._port_inflight: Dict[Tuple[str, int], float] = {}
        # informer-style expectation observers (reference: pod/service event
        # filters feeding expectations, pod.go:55-165, service.go:41-139)
        store.watch(self._observe_owned, kinds=("Pod", "Service"))

    def _observe_owned(self, event: str, obj, old) -> None:
        ref = obj.metadata.controller_ref()
        if ref is None or ref.kind != self.controller.KIND:
            return
        rtype = obj.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        resource = "pods" if obj.kind == "Pod" else "services"
        key = expectation_key(
            f"{obj.metadata.namespace}/{ref.name}", rtype, resource
        )
        if event == "ADDED":
            self.expectations.creation_observed(key)
        elif event == "DELETED":
            self.expectations.deletion_observed(key)

    # ------------------------------------------------------------------ API

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        """Manager entry point. Returns requeue-after seconds or None."""
        job = self.store.try_get(self.controller.KIND, name, namespace)
        if job is None:
            self.expectations.delete_job_expectations(f"{namespace}/{name}")
            return None
        assert isinstance(job, JobObject)
        expired = self.expectations.collect_expired(job_key(job))
        if expired:
            # a watch event was lost (or the expectation came from a dead
            # incarnation): proceeding is correct — the store is the source
            # of truth — but it must be loud, not silent
            log.warning(
                "%s %s: proceeding past %d timed-out expectation(s): %s",
                self.controller.KIND, job_key(job), len(expired),
                ", ".join(expired),
            )
            self.metrics.expectations_expired.inc(
                len(expired), kind=self.controller.KIND
            )
        if not self.expectations.all_satisfied(job_key(job)):
            return None  # watch events will re-trigger once caches settle
        if job.status.phase == JobConditionType.QUARANTINED:
            return None  # parked: wait for operator intervention, not CPU
        self.controller.apply_defaults(job)
        try:
            with TRACER.span(
                "reconcile", kind=self.controller.KIND, job=f"{namespace}/{name}"
            ):
                out = self.reconcile_job(job)
        except Exception as e:
            key = job_key(job)
            n = self._reconcile_failures.get(key, 0) + 1
            self._reconcile_failures[key] = n
            if n >= self.quarantine_budget:
                self._quarantine(job, e, n)
                return None  # swallow: the workqueue must forget this key
            raise  # manager rate-limits the requeue (backoff between tries)
        self._reconcile_failures.pop(job_key(job), None)
        return out

    def _trace_job_milestone(self, job: JobObject, name: str,
                             end_ts: Optional[float] = None,
                             **attrs) -> None:
        """Control-plane milestone span, once per job uid: anchored at
        the job's creation wall-clock and recorded under the DETERMINISTIC
        per-job trace (``trace_for_job``), so spans from different
        processes — engine, watchdog, console — merge into one timeline
        without any header plumbing. Each span runs creation → milestone,
        so a trace viewer shows the time-to-X ladder directly."""
        if not TRACER.enabled:
            return
        uid = job.metadata.uid or job_key(job)
        seen = self._job_trace_marks.setdefault(uid, set())
        if name in seen:
            return
        seen.add(name)
        ctx = trace_for_job(uid)
        created = job.metadata.creation_timestamp
        end = time.time() if end_ts is None else end_ts
        # job.submit takes the deterministic ROOT span id (self-parented;
        # build_span_tree treats self-parents as roots), everything else
        # parents under it
        TRACER.record(
            name, duration=max(end - created, 0.0), trace=ctx,
            span_id=ctx.span_id if name == "job.submit" else "",
            wall_ts=created, kind=self.controller.KIND,
            job=job_key(job), **attrs,
        )

    def _quarantine(self, job: JobObject, exc: BaseException, failures: int) -> None:
        """Park a poison-pill job: tear down its pods, free its slices, and
        stamp the Quarantined condition so the hot loop ends while the
        evidence (job object + condition + event) stays inspectable."""
        log.error(
            "quarantining %s %s after %d consecutive reconcile failures: %s",
            self.controller.KIND, job_key(job), failures, exc,
        )
        self.metrics.quarantined.inc(kind=self.controller.KIND)
        self.recorder.event(
            job, "Warning", "Quarantined",
            f"reconcile failed {failures}x consecutively: {exc}",
        )
        try:
            self._delete_pods(job, self.get_pods_for_job(job), CleanPodPolicy.ALL)
        except Exception:
            log.exception("quarantine pod cleanup failed for %s", job_key(job))
        if self.gang is not None:
            try:
                self.gang.delete_gang(job)
            except Exception:
                log.exception("quarantine gang release failed for %s", job_key(job))

        def mutate(obj: JobObject) -> None:  # type: ignore[type-arg]
            obj.status.set_condition(
                JobConditionType.QUARANTINED, "ReconcileBudgetExhausted",
                f"reconcile failed {failures}x consecutively: {exc}",
            )

        try:
            self.store.update_with_retry(
                self.controller.KIND, job.metadata.name, job.metadata.namespace,
                mutate,
            )
            self._reconcile_failures.pop(job_key(job), None)
        except Exception:
            # the status write itself may be the poisoned path; keep the
            # failure count so the next trigger re-quarantines immediately
            log.exception("quarantine status write failed for %s", job_key(job))

    # ----------------------------------------------------------- main loop

    def reconcile_job(self, job: JobObject) -> Optional[float]:
        import copy as _copy

        now = time.time()
        status = job.status
        snapshot = _copy.deepcopy(job.status)
        ann_snapshot = dict(job.metadata.annotations)
        if not status.conditions:
            status.set_condition(
                JobConditionType.CREATED, "JobCreated", f"{self.controller.KIND} created"
            )
            self.metrics.created.inc(kind=self.controller.KIND)
            self.recorder.event(job, "Normal", "JobCreated", "job accepted")
            self._trace_job_milestone(job, "job.submit")

        pods = self.get_pods_for_job(job)
        services = self.get_services_for_job(job)
        ctx = ReconcileContext(job=job, pods=pods, services=services)

        # Terminal jobs: clean up and (maybe) schedule TTL deletion.
        if status.is_terminal():
            return self._finalize(job, ctx)

        # --- suspend (kueue-style; net-new vs reference) ------------------
        # Suspended jobs tear everything down and RELEASE their slices so
        # other jobs can borrow the capacity; progress survives in
        # checkpoints and the resume path is the ordinary gang re-admission.
        if job.spec.run_policy.suspend:
            changed = False
            if status.phase != JobConditionType.SUSPENDED:
                status.set_condition(
                    JobConditionType.SUSPENDED, "JobSuspended",
                    "suspended by spec; slices released, resume restores "
                    "from the latest checkpoint",
                )
                # suspended wall-clock must not count against
                # activeDeadlineSeconds (kueue resets startTime the same
                # way); RUNNING re-stamps it on resume
                status.start_time = None
                status.replica_statuses = {}  # no phantom active replicas
                self.recorder.event(
                    job, "Normal", "Suspended", "pods torn down, slices freed"
                )
                changed = True
            if ctx.pods:
                self._delete_pods(job, ctx.pods, CleanPodPolicy.ALL)
                ctx.pods = []
                changed = True
            if self.gang is not None and self.gang.get_gang(job) is not None:
                self.gang.delete_gang(job)
            if changed:  # unguarded writes would hot-loop via MODIFIED events
                self._update_status(job)
            return None  # nothing to poll; unsuspend events requeue us
        if status.phase == JobConditionType.SUSPENDED:
            # spec flipped back: leave the suspended state and fall through
            # to ordinary admission (a fresh gang at current spec shape)
            status.set_condition(
                JobConditionType.CREATED, "JobResumed",
                "unsuspended; re-admitting",
            )
            self.recorder.event(job, "Normal", "Resumed", "re-admitting gang")

        # --- auto-parallelism planning (kubedl_tpu/planner/) --------------
        # BEFORE gang admission so the pods built this pass — including the
        # ones rebuilt right after an elastic resize — carry a mesh planned
        # for the CURRENT (topology, num_slices). The kind hook returns a
        # Plan only when it computed a fresh one (cache key on the
        # planned-mesh annotation); None means nothing to do this pass.
        try:
            new_plan = self.controller.plan_mesh(job)
        except Exception as exc:
            from kubedl_tpu.planner import PlanError

            if not isinstance(exc, PlanError):
                raise
            # No feasible layout can train this model on this slice shape:
            # fail fast at admission instead of letting workers OOM-loop.
            status.set_condition(
                JobConditionType.FAILED, "PlanInfeasible", str(exc)
            )
            status.completion_time = now
            self.metrics.failed.inc(kind=self.controller.KIND)
            self.recorder.event(job, "Warning", "PlanInfeasible", str(exc))
            self._delete_pods(job, ctx.pods, CleanPodPolicy.RUNNING)
            self._update_status(job)
            return None
        if new_plan is not None:
            job.metadata.annotations[constants.ANNOTATION_PLANNED_MESH] = (
                new_plan.to_annotation()
            )
            status.plan = PlanStatus(
                mesh=new_plan.mesh.to_env(),
                topology=new_plan.topology,
                num_slices=new_plan.num_slices,
                predicted_step_ms=round(new_plan.step_time_ms, 3),
                predicted_hbm_gib=round(new_plan.hbm_gib, 3),
                candidates_evaluated=new_plan.candidates_evaluated,
                plan_ms=round(new_plan.plan_ms, 3),
            )
            status.set_condition(
                JobConditionType.PLANNED, "MeshPlanned", new_plan.summary()
            )
            self.metrics.plans.inc(kind=self.controller.KIND)
            self.metrics.planner_candidates.inc(
                new_plan.candidates_evaluated, kind=self.controller.KIND
            )
            self.metrics.planner_plan_ms.observe(new_plan.plan_ms)
            self.recorder.event(job, "Normal", "Planned", new_plan.summary())
            self._trace_job_milestone(
                job, "job.plan", plan_ms=round(new_plan.plan_ms, 3)
            )

        # --- gang admission (atomic slice acquisition) --------------------
        if self.gang is not None and self.features.enabled(GANG_SCHEDULING):
            gang = self.gang.create_gang(job)
            # Elastic slice resize (reference analogue: Mars/ElasticDL
            # worker auto-scaling, mars.go:100-107 — TPU-native semantics
            # differ: an ICI domain is static, so grow/shrink is a
            # coordinated whole-gang restart-from-checkpoint at the new
            # shape; progress is kept by restore-from-latest in the
            # training entry).
            demand = self.gang.slice_demand(job)
            if (
                demand is not None
                and gang.phase == "Running"
                and (gang.slice_type, gang.num_slices) != demand
            ):
                old_slices = gang.num_slices
                # Soft path first (kubedl_tpu/elastic/): same slice type =>
                # partial release/grow IN PLACE. Surviving slices keep
                # their assignments (stable mesh coordinates), nothing is
                # re-admitted, and the job never risks losing its capacity
                # to another queued job between release and re-reserve.
                resized = (
                    demand[0] == gang.slice_type
                    and demand[1] >= 1
                    and self.gang.resize_gang(job, gang, demand[1])
                )
                job.status.restart_count += 1
                if resized:
                    status.set_condition(
                        JobConditionType.RESIZING,
                        "ElasticResize",
                        f"resized in place {old_slices}x{gang.slice_type or 'cpu'}"
                        f" -> {demand[1]}x{demand[0] or 'cpu'}; replicas restart"
                        " from checkpoint at the new world size",
                    )
                    self.metrics.resizes.inc(kind=self.controller.KIND)
                else:
                    # coarse fallback: release everything, re-admit at the
                    # new shape (slice-type change, impossible grow, or a
                    # gang scheduler without resize support)
                    status.set_condition(
                        JobConditionType.RESTARTING,
                        "SliceResize",
                        f"resizing {old_slices}x{gang.slice_type or 'cpu'} -> "
                        f"{demand[1]}x{demand[0] or 'cpu'}; gang restarts from checkpoint",
                    )
                self.recorder.event(
                    job, "Normal", "SliceResize",
                    f"slice demand changed {old_slices} -> {demand[1]}"
                    + (" (in-place)" if resized else ""),
                )
                self._delete_pods(job, ctx.pods, CleanPodPolicy.ALL)
                ctx.pods = []
                if not resized:
                    self.gang.delete_gang(job)
                self._update_status(job)
                return 0.1  # next pass restarts replicas at the new shape
            if not self.gang.try_admit(gang):
                if status.set_condition(
                    JobConditionType.QUEUED,
                    "WaitingForSlice",
                    f"waiting for {gang.num_slices}x {gang.slice_type or 'node pool'}",
                ):
                    self.recorder.event(
                        job, "Normal", "Queued", "insufficient free slices; queued"
                    )
                    self._update_status(job)
                # slice frees nudge queued jobs via the PodGroup-deletion
                # mapper (operator._engine_mapper); this slow poll is only
                # a safety net against missed events
                return 5.0
            self._trace_job_milestone(
                job, "job.gang_bind",
                slices=gang.num_slices, slice_type=gang.slice_type or "",
            )
            # Only slice-pinned replica groups get slice placements;
            # topology-less groups (e.g. evaluators) run in the CPU pool.
            for rtype, spec in job.spec.replica_specs.items():
                if spec.topology is None:
                    continue
                base = self._global_index_base(job, rtype)
                for i in range(spec.replicas):
                    ctx.placements[f"{rtype.value}-{i}"] = self._bound_node(
                        job, gang, base + i
                    )

        # --- deadline / backoff enforcement -------------------------------
        failed_msg = self._check_limits(job, now)
        if failed_msg:
            status.set_condition(JobConditionType.FAILED, *failed_msg)
            status.completion_time = now
            self.metrics.failed.inc(kind=self.controller.KIND)
            self.recorder.event(job, "Warning", failed_msg[0], failed_msg[1])
            self._delete_pods(job, ctx.pods, CleanPodPolicy.RUNNING)
            self._update_status(job)
            return None

        # --- kind-owned side objects (e.g. MPI hostfile ConfigMap) --------
        self.controller.prepare(job, ctx, self.store)

        # --- per-replica-type reconcile in DAG order ----------------------
        restarted = False
        for rtype in self._ordered_types(job):
            spec = job.spec.replica_specs[rtype]
            if self.features.enabled(DAG_SCHEDULING) and not dag.dag_conditions_ready(
                spec, job.spec.replica_specs, ctx.pods
            ):
                continue
            restarted |= self.reconcile_pods(job, ctx, rtype, spec)
            if self.controller.needs_service(rtype, job):
                self.reconcile_services(job, ctx, rtype, spec)

        # --- status machine ----------------------------------------------
        pods = self.get_pods_for_job(job)
        status.replica_statuses = status_machine.count_replica_statuses(pods)
        if restarted:
            status.set_condition(
                JobConditionType.RESTARTING, "ReplicaRestarted", "gang restarting"
            )
            self.metrics.restarted.inc(kind=self.controller.KIND)
        else:
            cond, reason, msg = self.controller.evaluate(job, pods)
            if cond is not None and status.set_condition(cond, reason, msg):
                self._on_transition(job, cond, pods)
        phase_before_hook = status.phase
        self.controller.update_job_status(job, pods, ctx)
        if status.phase != phase_before_hook and status.phase is not None:
            # kind-specific hook transitioned the job (e.g. XDL partial
            # success) — run the same bookkeeping evaluate-driven
            # transitions get
            self._on_transition(job, status.phase, pods)
        self._observe_launch_delays(job, pods)
        if not job.status.is_terminal():  # terminal pass syncs in _finalize
            self.tensorboard.reconcile(job)
        if job.status != snapshot or job.metadata.annotations != ann_snapshot:
            status.last_reconcile_time = now
            self._update_status(job)
        if job.status.is_terminal():
            return self._finalize(job, ctx)
        # active-deadline timer
        if job.spec.run_policy.active_deadline_seconds and status.start_time:
            remaining = (
                status.start_time
                + job.spec.run_policy.active_deadline_seconds
                - time.time()
            )
            return max(remaining, 0.1)
        return None

    # ----------------------------------------------------- pods / services

    def reconcile_pods(
        self, job: JobObject, ctx: ReconcileContext, rtype: ReplicaType, spec: ReplicaSpec
    ) -> bool:
        """Diff-by-index pod reconcile (reference: pod.go:214-323).

        Returns True if a slice-granular gang restart was triggered.
        """
        key = job_key(job)
        exp_key = expectation_key(key, rtype.value, "pods")
        pods = [
            p
            for p in ctx.pods
            if p.metadata.labels.get(constants.LABEL_REPLICA_TYPE) == rtype.value
        ]
        by_index: Dict[int, List[Pod]] = {}
        for p in pods:
            idx = int(p.metadata.labels.get(constants.LABEL_REPLICA_INDEX, "-1"))
            by_index.setdefault(idx, []).append(p)

        # Slice-granular restart: any retryable failure nukes the whole
        # replica group so the gang restarts from checkpoint together.
        if spec.restart_policy == RestartPolicy.ON_FAILURE_SLICE:
            failed = [
                p
                for p in pods
                if p.status.phase == PodPhase.FAILED
                and not status_machine.pod_failure_is_permanent(p, spec.restart_policy)
            ]
            if failed:
                job.status.restart_count += 1
                self.recorder.event(
                    job,
                    "Warning",
                    "SliceRestart",
                    f"{len(failed)} {rtype.value} pod(s) failed; restarting gang",
                )
                self._delete_pods(job, pods, CleanPodPolicy.ALL)
                ctx.pods = [p for p in ctx.pods if p not in pods]
                return True

        to_create: List[int] = []
        restarted = False
        for index in range(spec.replicas):
            dups = by_index.get(index, [])
            if len(dups) > 1:  # duplicated index: keep oldest, drop the rest
                dups.sort(key=lambda p: p.metadata.creation_timestamp)
                for extra in dups[1:]:
                    self._delete_pod(extra)
                    ctx.pods.remove(extra)
            if not dups:
                to_create.append(index)
                continue
            pod = dups[0]
            if pod.status.phase == PodPhase.FAILED:
                restart = self._should_restart_pod(pod, spec.restart_policy)
                if restart:
                    job.status.restart_count += 1
                    restarted = True
                    self.recorder.event(
                        job,
                        "Warning",
                        "RestartPod",
                        f"restarting {pod.metadata.name} "
                        f"(exit={pod.status.exit_code()})",
                    )
                    self._delete_pod(pod)
                    ctx.pods.remove(pod)
                    # recreated on the next reconcile pass (watch-triggered)

        # stale indices beyond replicas (scale-down)
        for index, dups in by_index.items():
            if index >= spec.replicas:
                for p in dups:
                    self._delete_pod(p)
                    if p in ctx.pods:
                        ctx.pods.remove(p)

        if to_create:
            # fenced actuation (KTL011): a pod launch is externally
            # visible — reject the whole batch up front if this process
            # lost the job's shard (create_many would also fence, but
            # expectations must not be armed for launches that never land)
            assert_fenced_actuation(
                self.store, job.metadata.namespace, job.metadata.name,
                action="pod launch",
            )
            self.expectations.expect_creations(exp_key, len(to_create))
            pods = [
                self._new_pod(job, ctx, rtype, spec, index)
                for index in to_create
            ]
            try:
                # one store round-trip for the whole gang: under group
                # commit a batch pays ONE fsync wait instead of one commit
                # window per pod
                ctx.pods.extend(self.store.create_many(pods))  # type: ignore[arg-type]
            except AlreadyExists:
                # someone raced us on at least one name (create_many is
                # all-or-nothing per shard): fall back to per-pod creates
                # so the rest of the gang still comes up
                for pod in pods:
                    if self.store.try_get(
                        "Pod", pod.metadata.name, pod.metadata.namespace
                    ) is not None:
                        self.expectations.creation_observed(exp_key)
                        continue
                    try:
                        created = self.store.create(pod)
                        ctx.pods.append(created)  # type: ignore[arg-type]
                    except AlreadyExists:
                        self.expectations.creation_observed(exp_key)
        return restarted

    def reconcile_services(
        self, job: JobObject, ctx: ReconcileContext, rtype: ReplicaType, spec: ReplicaSpec
    ) -> None:
        """One headless service per replica index (reference:
        service.go:190-307); target port re-patched when host-network pods
        fail over to a new random port (service.go:218-234)."""
        services = [
            s
            for s in ctx.services
            if s.metadata.labels.get(constants.LABEL_REPLICA_TYPE) == rtype.value
        ]
        have = {
            int(s.metadata.labels.get(constants.LABEL_REPLICA_INDEX, "-1")): s
            for s in services
        }
        port = self._default_port(spec)
        for index in range(spec.replicas):
            svc = have.get(index)
            if svc is None:
                svc = Service()
                svc.metadata.name = replica_name(job, rtype, index)
                svc.metadata.namespace = job.metadata.namespace
                svc.metadata.labels = self._replica_labels(job, rtype, index)
                svc.metadata.owner_refs.append(self._owner_ref(job))
                svc.spec.selector = self._replica_labels(job, rtype, index)
                svc.spec.ports = [Port(constants.DEFAULT_PORT_NAME, port)]
                try:
                    created = self.store.create(svc)
                    ctx.services.append(created)  # type: ignore[arg-type]
                except AlreadyExists:
                    pass
            else:
                # host-network failover: align service target port with the
                # pod's current host port
                hp = ctx.host_ports.get(f"{rtype.value}-{index}")
                if hp and svc.spec.ports and svc.spec.ports[0].host_port != hp:

                    def mutate(obj: Service) -> None:  # type: ignore[type-arg]
                        obj.spec.ports[0].host_port = hp

                    try:
                        self.store.update_with_retry(
                            "Service", svc.metadata.name, svc.metadata.namespace, mutate
                        )
                    except NotFound:
                        pass
        for index, svc in have.items():
            if index >= spec.replicas:
                self.store.try_delete(
                    "Service", svc.metadata.name, svc.metadata.namespace
                )
                if svc in ctx.services:
                    ctx.services.remove(svc)

    # ------------------------------------------------------------- helpers

    def _job_selector(self, job: JobObject) -> Dict[str, str]:
        return {
            constants.LABEL_JOB_NAME: job.metadata.name,
            constants.LABEL_JOB_KIND: self.controller.KIND,
        }

    def _claim_objects(self, job: JobObject, kind: str) -> List:
        """Ref-manager claim semantics (reference:
        pkg/job_controller/service_ref_manager.go:1-158):

        - objects matching the selector and owned by this job are kept;
        - matching ORPHANS (no controller owner) are adopted — an owner ref
          is added so GC and status accounting see them — unless the job is
          terminal;
        - objects owned by this job that no longer match the selector are
          RELEASED (owner ref removed) so a relabeled pod isn't torn down
          with the job;
        - objects owned by someone else are never touched.
        """
        ns = job.metadata.namespace
        selector = self._job_selector(job)
        claimed: List = []
        for obj in self.store.list(kind, ns, selector):
            ref = obj.metadata.controller_ref()
            if ref is not None and ref.uid == job.metadata.uid:
                claimed.append(obj)
            elif ref is None and not job.status.is_terminal():

                def adopt(o) -> None:
                    if o.metadata.controller_ref() is None:
                        o.metadata.owner_refs.append(self._owner_ref(job))

                try:
                    updated = self.store.update_with_retry(
                        kind, obj.metadata.name, ns, adopt
                    )
                except NotFound:
                    continue
                if (updated.metadata.controller_ref() or OwnerRef("", "", "")).uid == job.metadata.uid:
                    claimed.append(updated)
                    self.recorder.event(
                        job, "Normal", "Adopted",
                        f"adopted orphan {kind.lower()} {obj.metadata.name}",
                    )
            # else: owned by another controller — never touch
        # release: owned but selector no longer matches (e.g. relabeled).
        # Only ENGINE-MANAGED replicas are candidates — they always carry
        # the job-kind label. Auxiliary owned objects (TensorBoard sidecars
        # deliberately omit job-kind, observability/tensorboard.py:151-159)
        # must keep their owner ref for GC.
        for obj in self.store.list(kind, ns):
            ref = obj.metadata.controller_ref()
            if ref is None or ref.uid != job.metadata.uid:
                continue
            if constants.LABEL_JOB_KIND not in obj.metadata.labels:
                continue  # aux object, not a claimed replica
            if all(obj.metadata.labels.get(k) == v for k, v in selector.items()):
                continue

            def release(o) -> None:
                o.metadata.owner_refs = [
                    r for r in o.metadata.owner_refs if r.uid != job.metadata.uid
                ]

            try:
                self.store.update_with_retry(kind, obj.metadata.name, ns, release)
                self.recorder.event(
                    job, "Normal", "Released",
                    f"released {kind.lower()} {obj.metadata.name} (selector mismatch)",
                )
            except NotFound:
                pass
        return claimed

    def get_pods_for_job(self, job: JobObject) -> List[Pod]:
        """Claim pods with adopt/release (reference: GetPodsForJob with ref
        manager adoption, e.g. controllers/xgboost/pod.go:39-70)."""
        return self._claim_objects(job, "Pod")  # type: ignore[return-value]

    def get_services_for_job(self, job: JobObject) -> List[Service]:
        return self._claim_objects(job, "Service")  # type: ignore[return-value]

    def _ordered_types(self, job: JobObject) -> List[ReplicaType]:
        order = [
            rt for rt in self.controller.reconcile_orders() if rt in job.spec.replica_specs
        ]
        order += [rt for rt in job.spec.replica_specs if rt not in order]
        return order

    def _replica_labels(
        self, job: JobObject, rtype: ReplicaType, index: int
    ) -> Dict[str, str]:
        """The claim labels (reference: pod.go:343-357)."""
        labels = {
            constants.LABEL_GROUP_NAME: constants.API_GROUP,
            constants.LABEL_JOB_NAME: job.metadata.name,
            constants.LABEL_JOB_KIND: self.controller.KIND,
            constants.LABEL_REPLICA_TYPE: rtype.value,
            constants.LABEL_REPLICA_INDEX: str(index),
        }
        if self.controller.is_master_role(rtype):
            labels[constants.LABEL_JOB_ROLE] = constants.JOB_ROLE_MASTER
        return labels

    def _owner_ref(self, job: JobObject) -> OwnerRef:
        return OwnerRef(kind=job.kind, name=job.metadata.name, uid=job.metadata.uid)

    #: in-flight host-port reservations shared by all reconcile workers of
    #: this engine: (node, port) -> reservation time. Two concurrent
    #: workers placing pods on one node must not draw the same port in the
    #: window before the first pod lands in the store (ADVICE r2 #4).
    _INFLIGHT_TTL = 60.0

    def _port_conflicts(self, node: str, other_node: str) -> bool:
        """An unpinned ("") pod can land on ANY node, so it conflicts with
        every allocation — and every allocation conflicts with it."""
        return node == "" or other_node == "" or node == other_node

    def _alloc_host_port(self, node: str) -> int:
        """Random host port avoiding ports already claimed by host-network
        pods that could share a node (the reference draws blind from
        [30001,65535) and can collide, pod.go:470-486 — here allocation
        consults live state + in-flight reservations under a lock)."""
        with self._port_lock:
            now = time.time()
            self._port_inflight = {
                k: t for k, t in self._port_inflight.items()
                if now - t < self._INFLIGHT_TTL
            }
            in_use = set()
            for p in self.store.list("Pod", None):
                if not getattr(p.spec, "host_network", False):
                    continue
                if not self._port_conflicts(node, p.spec.node_name or ""):
                    continue
                for c in p.spec.containers:
                    for port in c.ports:
                        if port.host_port:
                            in_use.add(port.host_port)
            for (n, hp), _t in self._port_inflight.items():
                if self._port_conflicts(node, n):
                    in_use.add(hp)
            lo, hi = constants.HOST_PORT_RANGE
            chosen = None
            for _ in range(128):
                hp = self._rng.randrange(lo, hi)
                if hp not in in_use:
                    chosen = hp
                    break
            if chosen is None:
                for hp in range(lo, hi):  # dense node: deterministic sweep
                    if hp not in in_use:
                        chosen = hp
                        break
            if chosen is None:
                raise RuntimeError(f"no free host ports on node {node!r}")
            self._port_inflight[(node, chosen)] = now
            return chosen

    def _default_port(self, spec: ReplicaSpec) -> int:
        main = spec.template.spec.main_container()
        for p in main.ports:
            if p.name == constants.DEFAULT_PORT_NAME:
                return p.port
        return constants.DEFAULT_PORT

    def _new_pod(
        self,
        job: JobObject,
        ctx: ReconcileContext,
        rtype: ReplicaType,
        spec: ReplicaSpec,
        index: int,
    ) -> Pod:
        """Build one replica pod (reference: createNewPod, pod.go:326-387)."""
        template = spec.template.deep_copy()
        pod = Pod(spec=template.spec)
        pod.metadata.name = replica_name(job, rtype, index)
        pod.metadata.namespace = job.metadata.namespace
        pod.metadata.labels = {**template.labels, **self._replica_labels(job, rtype, index)}
        pod.metadata.annotations = dict(template.annotations)
        pod.metadata.owner_refs.append(self._owner_ref(job))

        # host-network wiring (reference: hostnetwork.go:29-100)
        if (
            self.features.enabled(HOST_NETWORK)
            and job.metadata.annotations.get(constants.ANNOTATION_NETWORK_MODE)
            == constants.NETWORK_MODE_HOST
        ):
            pod.spec.host_network = True
            node = ctx.placements.get(f"{rtype.value}-{index}", "").partition("@")[0]
            hp = self._alloc_host_port(node)
            ctx.host_ports[f"{rtype.value}-{index}"] = hp
            main = pod.spec.main_container()
            if not main.ports:
                main.ports.append(Port(constants.DEFAULT_PORT_NAME, constants.DEFAULT_PORT))
            main.ports[0].host_port = hp

        # code sync (reference: job.go:108-112)
        git_cfg = parse_git_sync(job.metadata.annotations)
        if git_cfg is not None:
            inject_code_sync(template, git_cfg)

        # model output (reference: job.go:312-339) via the storage union
        if job.spec.model_version is not None:
            from kubedl_tpu.lineage.storage import get_storage_provider

            main = pod.spec.main_container()
            root = job.spec.model_version.storage_root or constants.DEFAULT_MODEL_PATH
            provider = get_storage_provider(job.spec.model_version.storage_provider)
            # providers may RESOLVE the root (the http provider maps a
            # remote blob URL to a local staging dir the pod can write)
            root = provider.provision(root)
            main.set_env(constants.ENV_MODEL_PATH, root)
            provider.add_model_volume(pod, root)

        # persistent compile cache: restarted/resized/resumed replicas must
        # deserialize compiled XLA programs, not re-pay first-step compile
        # (round-2 startup regression). User-set env wins.
        if self.compile_cache_dir:
            main = pod.spec.main_container()
            if main.get_env(constants.ENV_COMPILE_CACHE_DIR) is None:
                main.set_env(
                    constants.ENV_COMPILE_CACHE_DIR, self.compile_cache_dir
                )

        # progress beacon (kubedl_tpu/watchdog/): per-pod file the worker's
        # beacon thread stamps and the kubelet heartbeat publishes onto the
        # Node object. User-set env wins (same contract as the cache dir).
        if self.beacon_dir:
            from kubedl_tpu.watchdog.beacon import beacon_path

            main = pod.spec.main_container()
            if main.get_env(constants.ENV_BEACON_FILE) is None:
                main.set_env(
                    constants.ENV_BEACON_FILE,
                    beacon_path(
                        self.beacon_dir, job.metadata.namespace,
                        pod.metadata.name,
                    ),
                )

        # gang binding: placement computed at admission
        placement = ctx.placements.get(f"{rtype.value}-{index}", "")
        if placement:
            node, _, slice_name = placement.partition("@")
            pod.spec.node_name = node
            pod.spec.slice_assignment = slice_name

        # the process-boundary payload: framework bootstrap env
        self.controller.set_mesh_spec(job, pod, rtype, index, ctx)
        return pod

    def _bound_node(self, job: JobObject, gang, global_index: int) -> str:
        """Returns "node@slice" (or "" when the gang is unconstrained)."""
        if self.gang is None:
            return ""
        probe = Pod()
        self.gang.bind_pod_to_gang(job, gang, probe, global_index)
        if not probe.spec.node_name:
            return ""
        return f"{probe.spec.node_name}@{probe.spec.slice_assignment}"

    def _global_index_base(self, job: JobObject, rtype: ReplicaType) -> int:
        """Slice-pinned replica types occupy contiguous global index ranges
        in reconcile order, so gang binding is stable. Topology-less groups
        don't consume slice hosts and are excluded."""
        base = 0
        for rt in self._ordered_types(job):
            if rt == rtype:
                return base
            spec = job.spec.replica_specs[rt]
            if spec.topology is not None:
                base += spec.replicas
        return base

    def _should_restart_pod(self, pod: Pod, policy: RestartPolicy) -> bool:
        if policy == RestartPolicy.NEVER:
            return False
        if policy == RestartPolicy.EXIT_CODE:
            if pod.is_evicted():
                return True
            code = pod.status.exit_code()
            return code is not None and is_retryable_exit_code(code)
        if policy == RestartPolicy.ON_FAILURE_SLICE:
            return False  # handled at gang granularity above
        return True  # Always / OnFailure

    def _check_limits(self, job: JobObject, now: float) -> Optional[Tuple[str, str]]:
        rp = job.spec.run_policy
        if rp.backoff_limit is not None and job.status.restart_count > rp.backoff_limit:
            return (
                "BackoffLimitExceeded",
                f"restarts {job.status.restart_count} > backoffLimit {rp.backoff_limit}",
            )
        if (
            rp.active_deadline_seconds is not None
            and job.status.start_time is not None
            and now - job.status.start_time > rp.active_deadline_seconds
        ):
            return (
                "DeadlineExceeded",
                f"job ran past activeDeadlineSeconds={rp.active_deadline_seconds}",
            )
        return None

    # -------------------------------------------------------- finalization

    def _finalize(self, job: JobObject, ctx: ReconcileContext) -> Optional[float]:
        """Terminal-state handling (reference: job.go:168-222)."""
        policy = job.spec.run_policy.clean_pod_policy
        self._delete_pods(job, ctx.pods, policy)
        for svc in list(ctx.services):
            self.store.try_delete("Service", svc.metadata.name, svc.metadata.namespace)
        if self.gang is not None:
            self.gang.delete_gang(job)
        if job.status.is_succeeded() and job.spec.model_version is not None:
            self._create_model_version(job, ctx)
        tb_requeue = self.tensorboard.reconcile(job)
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time is not None:
            remaining = job.status.completion_time + ttl - time.time()
            if remaining <= 0:
                self.metrics.deleted.inc(kind=self.controller.KIND)
                self.tensorboard.delete(job)
                self.store.try_delete(
                    self.controller.KIND, job.metadata.name, job.metadata.namespace
                )
                return None
            if tb_requeue is not None:
                return min(remaining, tb_requeue)
            return remaining
        return tb_requeue

    def _delete_pods(
        self, job: JobObject, pods: List[Pod], policy: CleanPodPolicy
    ) -> None:
        if policy == CleanPodPolicy.NONE:
            return
        for pod in pods:
            if policy == CleanPodPolicy.RUNNING:
                if pod.is_terminal():
                    continue
                # ctx.pods is a reconcile-start snapshot: a pod that
                # reached a terminal phase since then (its final update
                # racing the job's success transition) must be spared,
                # or its exit state is lost to the reap
                cur = self.store.try_get(
                    "Pod", pod.metadata.name, pod.metadata.namespace
                )
                if cur is None or cur.is_terminal():
                    continue
            self._delete_pod(pod)

    def _delete_pod(self, pod: Pod) -> None:
        # fenced actuation (KTL011): the kubelet SIGKILLs the process on
        # the DELETED event — a stale owner must not reap a pod a live
        # owner may have just adopted
        ref = pod.metadata.controller_ref()
        root = ref.name if ref is not None else pod.metadata.name
        assert_fenced_actuation(
            self.store, pod.metadata.namespace, root, action="pod delete",
        )
        self.store.try_delete("Pod", pod.metadata.name, pod.metadata.namespace)

    def _model_version_name(self, job: JobObject) -> str:
        return f"mv-{job.metadata.name}-{job.metadata.uid[-5:]}"

    def _create_model_version(self, job: JobObject, ctx: ReconcileContext) -> None:
        """Publish the job's output as a ModelVersion (reference:
        createModelVersion, job.go:341-382)."""
        from kubedl_tpu.lineage.types import ModelVersion

        mv_name = self._model_version_name(job)
        if (
            self.store.try_get("ModelVersion", mv_name, job.metadata.namespace)
            is not None
        ):
            if job.status.model_version != mv_name:
                job.status.model_version = mv_name
                self._update_status(job)
            return
        spec_ref = job.spec.model_version
        assert spec_ref is not None
        model_name = spec_ref.model_name or job.metadata.name
        storage_root = spec_ref.storage_root or constants.DEFAULT_MODEL_PATH
        # lineage is recorded AT registration: the parent is whatever the
        # Model pointed at when this version was published, and the
        # fingerprint pins the artifact bytes the training run produced
        # (best-effort — a remote root fingerprints at build time instead)
        parent = ""
        model = self.store.try_get("Model", model_name, job.metadata.namespace)
        if model is not None:
            parent = getattr(model, "latest_version", "") or ""
        fingerprint = ""
        try:
            from kubedl_tpu.training.checkpoint import checkpoint_fingerprint

            fingerprint = checkpoint_fingerprint(storage_root)
        except OSError:
            pass
        mv = ModelVersion(
            model_name=model_name,
            image_repo=spec_ref.image_repo,
            storage_root=storage_root,
            storage_provider=spec_ref.storage_provider,
            created_by=f"{self.controller.KIND}/{job.metadata.name}",
            node_name=self.controller.get_node_for_model_output(ctx.pods) or "",
            parent_version=parent,
            checkpoint_fingerprint=fingerprint,
        )
        mv.metadata.name = mv_name
        mv.metadata.namespace = job.metadata.namespace
        try:
            self.store.create(mv)
        except AlreadyExists:
            pass
        job.status.model_version = mv_name
        self._update_status(job)

    # -------------------------------------------------------------- status

    def _on_transition(
        self, job: JobObject, cond: JobConditionType, pods: List[Pod]
    ) -> None:
        if cond == JobConditionType.RUNNING:
            if job.status.start_time is None:
                job.status.start_time = time.time()
            self.recorder.event(job, "Normal", "JobRunning", "all replicas running")
        elif cond == JobConditionType.SUCCEEDED:
            job.status.completion_time = time.time()
            # the MV name is deterministic: stamp it in the SAME status
            # write as the success condition, so no client snapshot can
            # observe Succeeded with an empty model_version (the MV object
            # itself is created in _finalize moments later)
            if job.spec.model_version is not None and not job.status.model_version:
                job.status.model_version = self._model_version_name(job)
            self.metrics.successful.inc(kind=self.controller.KIND)
            self.recorder.event(job, "Normal", "JobSucceeded", "job succeeded")
        elif cond == JobConditionType.FAILED:
            job.status.completion_time = time.time()
            self.metrics.failed.inc(kind=self.controller.KIND)
            self.recorder.event(job, "Warning", "JobFailed", "job failed")

    def _observe_launch_delays(self, job: JobObject, pods: List[Pod]) -> None:
        """first/all-pods launch delay (reference: job_metrics.go:139-194),
        recorded exactly once per job via status annotations."""
        created = job.metadata.creation_timestamp
        ann = job.metadata.annotations
        running = [p for p in pods if p.status.start_time is not None]
        if running and "kubedl-tpu.io/first-pod-launched" not in ann:
            first = min(p.status.start_time for p in running)  # type: ignore[type-var]
            self.metrics.first_pod_launch_delay.observe(
                max(first - created, 0.0), kind=self.controller.KIND
            )
            ann["kubedl-tpu.io/first-pod-launched"] = "true"
            self._trace_job_milestone(job, "job.pod_launch", end_ts=first)
        total = sum(rs.replicas for rs in job.spec.replica_specs.values())
        if (
            len(running) >= total
            and total > 0
            and "kubedl-tpu.io/all-pods-launched" not in ann
        ):
            last = max(p.status.start_time for p in running)  # type: ignore[type-var]
            self.metrics.all_pods_launch_delay.observe(
                max(last - created, 0.0), kind=self.controller.KIND
            )
            ann["kubedl-tpu.io/all-pods-launched"] = "true"

    def _update_status(self, job: JobObject) -> None:
        """Optimistic status write; on conflict re-read and overwrite status
        (the reference requeues, job.go:298-306 — we retry inline)."""

        def mutate(obj: JobObject) -> None:  # type: ignore[type-arg]
            obj.status = job.status
            obj.metadata.annotations.update(job.metadata.annotations)

        try:
            updated = self.store.update_with_retry(
                self.controller.KIND, job.metadata.name, job.metadata.namespace, mutate
            )
            job.metadata.resource_version = updated.metadata.resource_version
        except NotFound:
            pass

"""Create/delete expectation cache suppressing redundant reconciles.

Reference: pkg/job_controller/expectations.go:28-47 + the borrowed
k8s.io/kubernetes controller expectations pattern. A reconcile that issues N
creates records `ExpectCreations(key, N)`; watch events observing those
creations decrement it; reconciles are no-ops for a key until its
expectations are satisfied (or expire), preventing double-creates when a
reconcile re-enters before the cache catches up.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable


EXPECTATION_TIMEOUT = 5 * 60.0


@dataclass
class _Exp:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.time)

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self) -> bool:
        return time.time() - self.timestamp > EXPECTATION_TIMEOUT


def expectation_key(job_key: str, rtype: str, resource: str) -> str:
    """`jobKey/replicatype/{pods,services}` (reference: GenExpectation*Key)."""
    return f"{job_key}/{rtype.lower()}/{resource}"


class ControllerExpectations:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._exps: dict[str, _Exp] = {}

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            self._exps[key] = _Exp(adds=count)

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            self._exps[key] = _Exp(dels=count)

    def creation_observed(self, key: str) -> None:
        self._lower(key, adds=1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, dels=1)

    def _lower(self, key: str, adds: int = 0, dels: int = 0) -> None:
        with self._lock:
            exp = self._exps.get(key)
            if exp is not None:
                exp.adds -= adds
                exp.dels -= dels

    def satisfied(self, key: str) -> bool:
        with self._lock:
            exp = self._exps.get(key)
            if exp is None:
                return True
            if exp.fulfilled() or exp.expired():
                return True
            return False

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._exps.pop(key, None)

    def clear(self) -> None:
        """Drop every expectation — crash recovery: expectations recorded
        by a dead incarnation must never suppress the new incarnation's
        reconciles (its creates/deletes were either durably observed via
        the rehydrated store or never happened)."""
        with self._lock:
            self._exps.clear()

    def collect_expired(self, job_key: str) -> list[str]:
        """Pop and return the job's timed-out expectation keys. A reconcile
        that proceeds past these lost watch events (or inherited them from
        a dead incarnation) — callers log + count instead of letting the
        expiry pass silently."""
        prefix = job_key + "/"
        with self._lock:
            expired = [
                k
                for k, exp in self._exps.items()
                if k.startswith(prefix) and exp.expired() and not exp.fulfilled()
            ]
            for k in expired:
                del self._exps[k]
        return expired

    def delete_job_expectations(self, job_key: str) -> None:
        """Drop every '<job_key>/<rtype>/<resource>' entry for a job."""
        prefix = job_key + "/"
        with self._lock:
            for k in [k for k in self._exps if k.startswith(prefix)]:
                del self._exps[k]

    def all_satisfied(self, job_key: str) -> bool:
        """All of one job's expectations fulfilled ('/'-bounded so job
        'train' is not blocked by job 'train2')."""
        prefix = job_key + "/"
        with self._lock:
            return all(
                exp.fulfilled() or exp.expired()
                for k, exp in self._exps.items()
                if k.startswith(prefix)
            )


class ShardedExpectations:
    """Per-reconcile-domain expectations: one ControllerExpectations per
    shard, routed by the job key prefix of every expectation key (both
    plain job keys ``ns/name`` and full ``ns/name/rtype/{pods,services}``
    keys start with the routing prefix). Shard failover then clears ONE
    domain's cache (:meth:`clear_shard`) instead of the whole world —
    expectations recorded by a dead shard owner never suppress the new
    owner's reconciles, while every other domain keeps its state."""

    def __init__(self, route: "ShardRouter", shards: int) -> None:
        self._route = route
        self._shards = [ControllerExpectations() for _ in range(shards)]

    def _for(self, key: str) -> ControllerExpectations:
        parts = key.split("/")
        namespace = parts[0]
        name = parts[1] if len(parts) > 1 else ""
        return self._shards[self._route(namespace, name)]

    def shard(self, i: int) -> ControllerExpectations:
        return self._shards[i]

    def clear_shard(self, i: int) -> None:
        """The failover-scoped analogue of :meth:`clear`."""
        self._shards[i].clear()

    # -- the ControllerExpectations surface, routed -----------------------

    def expect_creations(self, key: str, count: int) -> None:
        self._for(key).expect_creations(key, count)

    def expect_deletions(self, key: str, count: int) -> None:
        self._for(key).expect_deletions(key, count)

    def creation_observed(self, key: str) -> None:
        self._for(key).creation_observed(key)

    def deletion_observed(self, key: str) -> None:
        self._for(key).deletion_observed(key)

    def satisfied(self, key: str) -> bool:
        return self._for(key).satisfied(key)

    def delete_expectations(self, key: str) -> None:
        self._for(key).delete_expectations(key)

    def clear(self) -> None:
        for exp in self._shards:
            exp.clear()

    def collect_expired(self, job_key: str) -> list[str]:
        return self._for(job_key).collect_expired(job_key)

    def delete_job_expectations(self, job_key: str) -> None:
        self._for(job_key).delete_job_expectations(job_key)

    def all_satisfied(self, job_key: str) -> bool:
        return self._for(job_key).all_satisfied(job_key)


#: signature of the key router ShardedExpectations is built over —
#: ``ShardedObjectStore.shard_for_key`` fits directly
ShardRouter = Callable[[str, str], int]

"""Changed-vars-only environ writes (the PR 6 env-race guard).

glibc ``setenv``/``putenv`` may realloc the process environ block, racing
native ``getenv`` from XLA's persistent worker threads — one process hosts
every gang attempt, so a replacement pod re-enters an entrypoint with an
identical env and the steady-state restart path must not touch environ at
all. :func:`apply_env` writes each var only when its value actually
changes; every ThreadRuntime entrypoint goes through it (static analysis
rule KTL003 flags any other post-init ``os.environ`` mutation).
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def apply_env(env: Optional[Dict[str, str]]) -> int:
    """Fold ``env`` into ``os.environ``, writing only changed string
    values. Returns the number of vars actually written."""
    if not env:
        return 0
    written = 0
    for k, v in env.items():
        if isinstance(v, str) and os.environ.get(k) != v:
            os.environ[k] = v
            written += 1
    return written

"""shard_map version compat.

jax >= 0.6 promotes ``shard_map`` to the top-level namespace and renames
the replication-check kwarg ``check_rep`` -> ``check_vma``. Older jax
(0.4.x, still what some images bake in) only has
``jax.experimental.shard_map.shard_map`` with the old kwarg. Call sites
in this repo are written against the new API; this module papers over
the difference so they run on both.
"""

from __future__ import annotations

try:
    from jax import shard_map  # jax >= 0.6

    LEGACY = False
except ImportError:  # pre-promotion location + kwarg name
    from jax.experimental.shard_map import shard_map as _shard_map

    LEGACY = True

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


__all__ = ["LEGACY", "shard_map"]

"""ElasticPolicy: compute each elastic job's desired gang size.

One controller instance watches the whole fleet (Nodes for notice/capacity
churn, PodGroups for slice frees, every elastic job kind for spec/status
changes) and, per elastic job, decides a desired ``num_slices`` within the
kind-declared ``[min_slices, max_slices]``:

- **shrink** (urgent, bypasses cooldown): the job's gang holds draining
  slices — vacate them before the reclaim lands, down to at most
  ``min_slices``. At the floor the job keeps running on the draining
  slice; if the reclaim arrives, the ordinary eviction/gang-restart path
  recovers it.
- **grow** (voluntary, flap-damped): free healthy slices exist, the job is
  RUNNING below ``max_slices``, and at least ``cooldown`` seconds passed
  since its last resize — the same cooldown-stamp idiom as the serving
  autoscaler (serving/controller.py AUTOSCALE_COOLDOWN). Shrinks stamp the
  cooldown too, so a drain-shrink is not immediately undone by a grow into
  the very capacity that is about to vanish.

The policy only WRITES the desired size onto the job spec (through the
kind's ``set_num_slices`` hook); the engine executes the actual resize
protocol (in-place ``resize_gang`` + ``Resizing`` condition + checkpoint
restart) on its next reconcile of that job.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

from kubedl_tpu.api.interface import JobObject, WorkloadController
from kubedl_tpu.api.types import JobConditionType
from kubedl_tpu.core.manager import ControllerManager, EventRecorder
from kubedl_tpu.core.store import Conflict, NotFound, ObjectStore
from kubedl_tpu.gang.interface import GangScheduler
from kubedl_tpu.gang.slice_scheduler import SliceInventory, owner_key

log = logging.getLogger("kubedl_tpu.elastic")

#: phases in which the policy leaves a job alone entirely
_HANDS_OFF = (
    JobConditionType.SUCCEEDED,
    JobConditionType.FAILED,
    JobConditionType.SUSPENDED,
    JobConditionType.QUARANTINED,
)


class ElasticPolicy:
    """Fleet-wide desired-gang-size controller with grow hysteresis."""

    NAME = "elastic-policy"
    #: single synthetic workqueue key: every trigger rescans the (small)
    #: elastic-job population, so concurrent per-job keys can't race
    KEY = ("kubedl-system", "elastic-policy")

    def __init__(
        self,
        store: ObjectStore,
        inventory: SliceInventory,
        gang: GangScheduler,
        controllers: Dict[str, WorkloadController],
        recorder: Optional[EventRecorder] = None,
        cooldown: float = 30.0,
        clock=time.time,
    ) -> None:
        self.store = store
        self.inventory = inventory
        self.gang = gang
        self.controllers = controllers
        self.recorder = recorder or EventRecorder(store)
        self.cooldown = cooldown
        self.clock = clock
        #: (ns, name) -> our clock at the job's last policy-driven resize
        self._last_resize: Dict[Tuple[str, str], float] = {}

    def setup(self, manager: ControllerManager) -> None:
        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Node", "PodGroup"] + sorted(self.controllers),
            mapper=lambda e, obj, old: [self.KEY],
        )

    # ------------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        requeue: Optional[float] = None
        for kind in sorted(self.controllers):
            controller = self.controllers[kind]
            for job in self.store.list(kind, namespace=None):
                assert isinstance(job, JobObject)
                r = self._reconcile_job(kind, controller, job)
                if r is not None:
                    requeue = r if requeue is None else min(requeue, r)
        return requeue

    def _reconcile_job(
        self, kind: str, controller: WorkloadController, job: JobObject
    ) -> Optional[float]:
        rng = controller.elastic_range(job)
        if rng is None:
            return None
        mn, mx = rng
        phase = job.status.phase
        if phase is None or phase in _HANDS_OFF:
            return None
        try:
            demand = self.gang.slice_demand(job)
        except ValueError:
            return None  # malformed spec: validation's problem, not ours
        if not demand or not demand[0]:
            return None  # no slice-pinned replicas: nothing to scale
        slice_type = demand[0]
        current = controller.get_num_slices(job)
        owner = owner_key(job.metadata.namespace, job.metadata.name)
        key = (job.metadata.namespace, job.metadata.name)
        now = self.clock()
        draining_held = self.inventory.draining_slices(owner)
        desired, reason = current, ""
        if draining_held:
            desired = max(current - len(draining_held), mn)
            reason = (
                f"vacating {len(draining_held)} draining slice(s): "
                + ", ".join(draining_held)
            )
        elif phase == JobConditionType.RUNNING and current < mx:
            cd = controller.elastic_cooldown(job)
            cooldown = self.cooldown if cd is None else cd
            since = now - self._last_resize.get(key, 0.0)
            if since < cooldown:
                # capacity may be free but the job resized recently:
                # re-check once the cooldown window closes
                return max(cooldown - since, 0.05)
            free = len(self.inventory.free_slices(slice_type))
            if free > 0:
                desired = min(current + free, mx)
                reason = f"{free} free {slice_type} slice(s)"
        if desired == current:
            return None
        self._last_resize[key] = now

        def mutate(obj: JobObject) -> None:  # type: ignore[type-arg]
            controller.set_num_slices(obj, desired)

        try:
            self.store.update_with_retry(
                kind, job.metadata.name, job.metadata.namespace, mutate
            )
        except (NotFound, Conflict):
            return 0.5
        log.info(
            "%s %s/%s: %d -> %d slices (%s)",
            kind, job.metadata.namespace, job.metadata.name,
            current, desired, reason,
        )
        self.recorder.event(
            job, "Normal", "ElasticResize",
            f"desired slices {current} -> {desired}: {reason}",
        )
        return None

"""Resize-protocol helpers: batch-semantics preservation + goodput.

The resize protocol itself is distributed across the layers that own each
step (docs/elasticity.md): the engine checkpoints implicitly (replicas are
killed retryably, losing at most one save interval), ``resize_gang``
re-shapes the reservation, restarted replicas restore through the
cross-sharding checkpoint assembler (`training/checkpoint.py`
``_ShardStore.region``) onto the new mesh. What lives here is the math
that must agree between the operator and every worker:

**Batch semantics.** The trainer's ``global_batch`` is the per-optimizer-
step batch regardless of world size, and ``grad_accum`` only splits it
into sequential microbatches (scan-accumulated, mean-of-means — see
``training/trainer.py``). So the LOSS TRAJECTORY is already world-size
invariant; what a shrink changes is per-device memory pressure: half the
processes means each device holds twice the per-step tokens. A job tuned
at its base world would OOM after shrinking. :func:`grad_accum_for_world`
rescales accumulation inversely with world size so the per-device
*microbatch* stays at its tuned size while the effective global batch —
and the loss trajectory — is preserved exactly.

**Goodput.** :func:`goodput` is the step-time-weighted fraction of wall
clock spent training during a window — the bench artifact's
``goodput_under_preemption`` headline (time lost to checkpoints, restarts
and re-admission is exactly ``1 - goodput``).
"""

from __future__ import annotations

from dataclasses import dataclass


def grad_accum_for_world(
    base_grad_accum: int, base_world: int, world: int, global_batch: int
) -> int:
    """Gradient-accumulation factor for ``world`` processes such that the
    per-device microbatch matches the one tuned at ``base_world`` with
    ``base_grad_accum``, while the effective global batch is unchanged.

    Target is ``base_grad_accum * base_world / world`` (shrink => more
    accumulation, grow => less), rounded to the nearest feasible value:
    ``grad_accum`` must divide ``global_batch``, so we walk down from the
    target to the largest divisor (never below 1, never above
    ``global_batch``).
    """
    base_grad_accum = max(int(base_grad_accum), 1)
    base_world = max(int(base_world), 1)
    world = max(int(world), 1)
    global_batch = max(int(global_batch), 1)
    target = max((base_grad_accum * base_world) // world, 1)
    target = min(target, global_batch)
    while target > 1 and global_batch % target != 0:
        target -= 1
    return target


def data_parallel_world(mesh) -> int:
    """Number of gradient replicas a mesh implies: the product of the
    batch-sharding axes (replica x data x fsdp — fsdp shards parameters but
    each rank still consumes its own batch shard).

    This is the world size that batch semantics actually care about. When
    the auto-parallelism planner owns the mesh (docs/planning.md), a resize
    can move chips between data and model axes — e.g. 8 chips data=8 ->
    16 chips data=8,tensor=2 — so rescaling grad accumulation by raw
    process count would be wrong; entry.py uses this instead whenever the
    operator stamped a base DP degree.
    """
    n = 1
    for axis in ("replica", "data", "fsdp"):
        n *= max(int(mesh.axes.get(axis, 1)), 1)
    return n


def goodput(step_seconds: float, wall_seconds: float) -> float:
    """Fraction of ``wall_seconds`` spent in training steps, in [0, 1]."""
    if wall_seconds <= 0:
        return 0.0
    return max(0.0, min(step_seconds / wall_seconds, 1.0))


@dataclass
class GoodputBreakdown:
    """Attributable goodput: WHERE the non-productive seconds went.

    :func:`goodput` alone is a blind spot — a bench (or an operator
    staring at a regression) can see goodput dropped but not whether the
    loss was checkpoint stalls, restart serialization, or scheduler
    re-admission queueing. This accumulator splits ``1 - goodput`` into
    those buckets so the preemption-storm bench's restart-vs-PS delta is
    attributable line by line (BENCH_r15_ps.json, ``bench.py --ps``);
    the watchdog's ``stats()`` and the console's ``/api/v1/data/goodput``
    expose the same shape per job.
    """

    productive_seconds: float = 0.0
    #: time spent writing checkpoints (the save stall, not async overlap)
    checkpoint_seconds: float = 0.0
    #: process death -> replacement running (gang teardown + cold start)
    restart_seconds: float = 0.0
    #: replacement running -> training again (queue/reserve/warm-join)
    readmission_seconds: float = 0.0

    @property
    def lost_seconds(self) -> float:
        return (
            self.checkpoint_seconds
            + self.restart_seconds
            + self.readmission_seconds
        )

    @property
    def wall_seconds(self) -> float:
        return self.productive_seconds + self.lost_seconds

    def goodput(self) -> float:
        return goodput(self.productive_seconds, self.wall_seconds)

    def add(self, other: "GoodputBreakdown") -> "GoodputBreakdown":
        self.productive_seconds += other.productive_seconds
        self.checkpoint_seconds += other.checkpoint_seconds
        self.restart_seconds += other.restart_seconds
        self.readmission_seconds += other.readmission_seconds
        return self

    def to_dict(self) -> dict:
        return {
            "productive_seconds": round(self.productive_seconds, 6),
            "checkpoint_seconds": round(self.checkpoint_seconds, 6),
            "restart_seconds": round(self.restart_seconds, 6),
            "readmission_seconds": round(self.readmission_seconds, 6),
            "lost_seconds": round(self.lost_seconds, 6),
            "wall_seconds": round(self.wall_seconds, 6),
            "goodput": round(self.goodput(), 6),
        }

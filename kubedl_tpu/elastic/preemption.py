"""PreemptionController: turn per-host notices into per-slice drains.

Preemptible/maintenance-scheduled TPU capacity announces reclaim ahead of
time on the HOST (the cloud metadata server's preemption notice). The
kubelet publishes that through its heartbeat (`core/nodes.py`:
``NodeHeartbeater.announce_preemption`` / the ``elastic.preempt`` chaos
site), stamping ``Node.preempt_at``/``preempt_reason``. This controller
watches Nodes and translates: any noticed host marks its WHOLE slice
draining in the inventory — an ICI domain dies whole, so one reclaimed
host takes the slice with it. Draining slices are skipped by
``SliceInventory.try_reserve`` and shrink elastic jobs off themselves via
the ElasticPolicy. A withdrawn notice (all hosts clear) returns the slice
to service.

The notice is advance warning, not death: the node keeps heartbeating. If
the reclaim actually lands, the ordinary NodeLifecycleController eviction
path takes over (retryable whole-gang restart) — drains just make that
the rare case instead of the common one.
"""

from __future__ import annotations

import logging
from typing import Optional

from kubedl_tpu.core.manager import ControllerManager, EventRecorder
from kubedl_tpu.core.objects import Node
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.gang.slice_scheduler import SliceInventory
from kubedl_tpu.observability.metrics import DEFAULT_JOB_METRICS, JobMetrics

log = logging.getLogger("kubedl_tpu.elastic")


class PreemptionController:
    """Watch Node preemption notices; mark/clear slice drains."""

    NAME = "preemption"

    def __init__(
        self,
        store: ObjectStore,
        inventory: SliceInventory,
        recorder: Optional[EventRecorder] = None,
        metrics: Optional[JobMetrics] = None,
    ) -> None:
        self.store = store
        self.inventory = inventory
        self.recorder = recorder or EventRecorder(store)
        self.metrics = metrics or DEFAULT_JOB_METRICS

    def setup(self, manager: ControllerManager) -> None:
        manager.register(
            self.NAME,
            self.reconcile,
            watch_kinds=["Node"],
            mapper=lambda e, obj, old: [
                (obj.metadata.namespace, obj.metadata.name)
            ],
        )

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        node = self.store.try_get("Node", name, namespace)
        if not isinstance(node, Node):
            return None
        slice_name = self.inventory.slice_of_host(name)
        if slice_name is None:
            return None  # host outside the slice fleet (CPU pool)
        if node.preempt_at > 0:
            reason = node.preempt_reason or f"preemption notice on {name}"
            if self.inventory.mark_draining(slice_name, reason):
                self.metrics.preemption_notices.inc()
                log.warning("slice %s draining: %s", slice_name, reason)
                self.recorder.event(
                    node, "Warning", "PreemptionNotice",
                    f"slice {slice_name} draining: {reason}",
                )
        elif not self._any_notice(slice_name):
            # every host's notice withdrawn: capacity back in service
            if self.inventory.clear_draining(slice_name):
                log.info("slice %s back in service", slice_name)
                self.recorder.event(
                    node, "Normal", "PreemptionCleared",
                    f"slice {slice_name} back in service",
                )
        return None

    def _any_notice(self, slice_name: str) -> bool:
        """True while ANY host of the slice still carries a notice — a
        multi-host slice must not clear on the first host's withdrawal."""
        from kubedl_tpu.core.nodes import NODE_NAMESPACE

        for host in self.inventory.slice_hosts(slice_name):
            n = self.store.try_get("Node", host, NODE_NAMESPACE)
            if isinstance(n, Node) and n.preempt_at > 0:
                return True
        return False

"""Elastic slice scaling: preemption-aware grow/shrink of training gangs.

Gang size becomes a runtime variable within a job's declared
``[min_slices, max_slices]``:

- :mod:`kubedl_tpu.elastic.preemption` — nodes publish preemption/
  maintenance notices through the heartbeat path; the PreemptionController
  marks victim slices draining in the inventory.
- :mod:`kubedl_tpu.elastic.policy` — the ElasticPolicy controller watches
  free capacity + draining notices and writes the desired gang size onto
  elastic jobs (cooldown/hysteresis on voluntary grows).
- :mod:`kubedl_tpu.elastic.resize` — resize-protocol helpers: gradient-
  accumulation rescaling so the effective global batch (and thus the loss
  trajectory) is preserved across world sizes, and goodput accounting.

The engine executes the resize itself (`engine/job_controller.py`): on a
slice-demand change it tries :meth:`SliceGangScheduler.resize_gang`
(partial release/grow in place), stamps a ``Resizing`` condition, restarts
replicas at the new world size, and the training entry resumes from the
latest checkpoint via the cross-sharding assembler. See docs/elasticity.md.
"""

from kubedl_tpu.elastic.policy import ElasticPolicy
from kubedl_tpu.elastic.preemption import PreemptionController
from kubedl_tpu.elastic.resize import goodput, grad_accum_for_world

__all__ = [
    "ElasticPolicy",
    "PreemptionController",
    "goodput",
    "grad_accum_for_world",
]

# Build/test entry points (reference: Makefile:17-19 `make manager/test/...`).
# Everything runs CPU-only by default; `make bench` uses real hardware.

PY ?= python

.PHONY: test test-fast test-witness bench-smoke bench dryrun install lint all \
	render-deploy validate-deploy docker-build kind-e2e drive-router

all: test

# unit + integration suite on a virtual 8-device CPU mesh
test:
	KUBEDL_CI=true $(PY) -m pytest tests/ -x -q

test-fast:
	KUBEDL_CI=true $(PY) -m pytest tests/ -x -q -m "not slow"

# CPU smoke of the end-to-end bench (operator -> gang -> pod -> train)
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py

# real-hardware bench (one JSON line on stdout)
bench:
	$(PY) bench.py

# multi-chip sharding dry run on 8 virtual CPU devices
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

# parameterized deploy surface: manifests from deploy/values.yaml +
# CRD-equivalent JSON Schemas for every kind (reference: helm + config/crd)
render-deploy:
	$(PY) deploy/render.py

# kubeconform-class structural validation of every rendered manifest, the
# single-file bundle, the Dockerfile, and docker-compose (reference CI
# proves this on a kind cluster; see deploy/validate.py for what this
# checks without one). Green in the test suite via tests/test_deploy.py.
validate-deploy: render-deploy
	$(PY) deploy/validate.py

# CI-fashion image build (requires docker; validate-deploy lints the
# Dockerfile without it)
docker-build:
	docker build -t kubedl-tpu:latest .

# kind-cluster e2e, where a cluster toolchain exists (reference:
# scripts/deploy_kubedl.sh + run_tf_test_job.sh); exit 2 from the script
# means "toolchain absent" and keeps the lane green
kind-e2e:
	bash scripts/kind-e2e.sh || { rc=$$?; [ $$rc -eq 2 ] && echo "kind-e2e skipped (no cluster toolchain)" || exit $$rc; }

# serving-router fault drill: 3 real engine subprocesses, seeded SIGKILL
# under load, eject -> readmit, drain semantics (scripts/verify-drives/)
drive-router:
	JAX_PLATFORMS=cpu $(PY) scripts/verify-drives/drive_router.py

install:
	$(PY) -m pip install -e .

# bytecode-compile + the project-specific static analyzer (rule catalog:
# docs/static-analysis.md; findings beyond analysis/baseline.json fail)
lint:
	$(PY) -m compileall -q kubedl_tpu bench.py __graft_entry__.py
	JAX_PLATFORMS=cpu $(PY) -m kubedl_tpu.analysis

# tier-1 suite under the runtime lock-order witness (fails on ABBA cycles)
test-witness:
	KUBEDL_CI=true KUBEDL_LOCKWITNESS=1 $(PY) -m pytest tests/ -x -q -m "not slow"

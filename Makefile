# Build/test entry points (reference: Makefile:17-19 `make manager/test/...`).
# Everything runs CPU-only by default; `make bench` uses real hardware.

PY ?= python

.PHONY: test test-fast bench-smoke bench dryrun install lint all render-deploy

all: test

# unit + integration suite on a virtual 8-device CPU mesh
test:
	KUBEDL_CI=true $(PY) -m pytest tests/ -x -q

test-fast:
	KUBEDL_CI=true $(PY) -m pytest tests/ -x -q -m "not slow"

# CPU smoke of the end-to-end bench (operator -> gang -> pod -> train)
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py

# real-hardware bench (one JSON line on stdout)
bench:
	$(PY) bench.py

# multi-chip sharding dry run on 8 virtual CPU devices
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

# parameterized deploy surface: manifests from deploy/values.yaml +
# CRD-equivalent JSON Schemas for every kind (reference: helm + config/crd)
render-deploy:
	$(PY) deploy/render.py

install:
	$(PY) -m pip install -e .

lint:
	$(PY) -m compileall -q kubedl_tpu bench.py __graft_entry__.py

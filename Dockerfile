# Operator image (reference: Dockerfile — single static binary; here a
# slim Python image carrying the operator + console + compute path).
#
#   docker build -t kubedl-tpu:latest .
#   docker run kubedl-tpu:latest --workloads '*' --console-port 9090
#
# On TPU hosts, base this on a TPU-enabled JAX image instead and the same
# entrypoint serves both the control plane and in-pod workers.

FROM python:3.12-slim

WORKDIR /app

COPY pyproject.toml README.md bench.py __graft_entry__.py ./
COPY kubedl_tpu ./kubedl_tpu

# CPU JAX by default; TPU deployments override with jax[tpu]
RUN pip install --no-cache-dir -e .

# example workloads: the control-plane bench runs the real convnet/DDP
# trainers from here (bench.py degrades to env-asserts when absent).
# After the pip layer: editing a workload script must not bust the
# dependency-install cache
COPY examples ./examples

# console + metrics
EXPOSE 9090

ENTRYPOINT ["kubedl-tpu-operator"]
CMD ["--workloads", "*", "--console-port", "9090", "--console-host", "0.0.0.0"]

# Operator image (reference: Dockerfile — single static binary; here a
# slim Python image carrying the operator + console + compute path).
#
#   docker build -t kubedl-tpu:latest .
#   docker run kubedl-tpu:latest --workloads '*' --console-port 9090
#
# On TPU hosts, base this on a TPU-enabled JAX image instead and the same
# entrypoint serves both the control plane and in-pod workers.

FROM python:3.12-slim

WORKDIR /app

COPY pyproject.toml README.md bench.py ./
COPY kubedl_tpu ./kubedl_tpu

# CPU JAX by default; TPU deployments override with jax[tpu]
RUN pip install --no-cache-dir -e .

# not needed by pip install: kept after the dependency layer so editing
# a workload/driver script never busts the install cache
COPY __graft_entry__.py ./
COPY examples ./examples

# console + metrics
EXPOSE 9090

ENTRYPOINT ["kubedl-tpu-operator"]
CMD ["--workloads", "*", "--console-port", "9090", "--console-host", "0.0.0.0"]

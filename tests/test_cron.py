"""Cron tests: expression parsing (table-driven, reference analogue
cron_utils tests) + controller semantics with a fake clock."""

import time
from datetime import datetime

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ReplicaSpec, ReplicaType
from kubedl_tpu.core.objects import Container
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.cron.controller import CronController
from kubedl_tpu.cron.cronexpr import CronParseError, CronSchedule, missed_run_times
from kubedl_tpu.cron.types import ConcurrencyPolicy, Cron, CronHistoryEntry
from kubedl_tpu.workloads.tpujob import TPUJob


def ts(*args) -> float:
    return datetime(*args).timestamp()


class TestCronExpr:
    @pytest.mark.parametrize("expr,frm,want", [
        ("* * * * *", (2026, 1, 1, 10, 30), (2026, 1, 1, 10, 31)),
        ("0 * * * *", (2026, 1, 1, 10, 30), (2026, 1, 1, 11, 0)),
        ("*/15 * * * *", (2026, 1, 1, 10, 16), (2026, 1, 1, 10, 30)),
        ("30 4 * * *", (2026, 1, 1, 10, 0), (2026, 1, 2, 4, 30)),
        ("0 0 1 * *", (2026, 1, 15, 0, 0), (2026, 2, 1, 0, 0)),
        ("0 0 * * 0", (2026, 1, 1, 0, 0), (2026, 1, 4, 0, 0)),  # Thu->Sun
        ("0 9-17 * * *", (2026, 1, 1, 18, 0), (2026, 1, 2, 9, 0)),
        ("0 0 29 2 *", (2026, 1, 1, 0, 0), (2028, 2, 29, 0, 0)),  # leap
        ("@daily", (2026, 1, 1, 5, 0), (2026, 1, 2, 0, 0)),
        ("0 12 * jan mon", (2026, 1, 3, 0, 0), (2026, 1, 5, 12, 0)),
    ])
    def test_next_after(self, expr, frm, want):
        got = CronSchedule.parse(expr).next_after(ts(*frm))
        assert datetime.fromtimestamp(got) == datetime(*want)

    def test_vixie_dom_dow_or_rule(self):
        # both restricted: fires on the 13th OR on Friday
        s = CronSchedule.parse("0 0 13 * 5")
        got = datetime.fromtimestamp(s.next_after(ts(2026, 1, 10, 0, 0)))
        # Jan 10 2026 is a Saturday -> next Friday is Jan 16, but the 13th
        # (Tuesday) comes first under the OR rule
        assert got == datetime(2026, 1, 13, 0, 0)

    @pytest.mark.parametrize("bad", [
        "* * * *", "61 * * * *", "* 25 * * *", "* * 0 * *", "x * * * *",
        "*/0 * * * *", "5-1 * * * *",
    ])
    def test_parse_errors(self, bad):
        with pytest.raises(CronParseError):
            CronSchedule.parse(bad)

    def test_missed_runs(self):
        s = CronSchedule.parse("*/10 * * * *")
        missed = missed_run_times(s, ts(2026, 1, 1, 10, 0), ts(2026, 1, 1, 10, 35))
        assert [datetime.fromtimestamp(t).minute for t in missed] == [10, 20, 30]


def make_template(name="tpl"):
    job = TPUJob()
    spec = ReplicaSpec(replicas=1)
    spec.template.spec.containers.append(Container(command=["true"]))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    return job


class FakeClock:
    def __init__(self, t: float) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


class TestCronController:
    def setup_cron(self, schedule="*/5 * * * *", policy=ConcurrencyPolicy.ALLOW,
                   start=(2026, 1, 1, 10, 0)):
        store = ObjectStore()
        clock = FakeClock(ts(*start))
        ctrl = CronController(store, ["TPUJob"], clock=clock)
        cron = Cron(schedule=schedule, template=make_template(),
                    concurrency_policy=policy)
        cron.metadata.name = "nightly"
        cron.metadata.creation_timestamp = clock.t
        store.create(cron)
        return store, ctrl, clock

    def test_fires_on_schedule(self):
        store, ctrl, clock = self.setup_cron()
        ctrl.reconcile("default", "nightly")
        assert store.list("TPUJob") == []  # not due yet
        clock.t = ts(2026, 1, 1, 10, 5)
        ctrl.reconcile("default", "nightly")
        jobs = store.list("TPUJob")
        assert len(jobs) == 1
        job = jobs[0]
        assert job.metadata.labels[constants.LABEL_CRON_NAME] == "nightly"
        assert job.metadata.name.startswith("nightly-")
        cron = store.get("Cron", "nightly")
        assert cron.active == [job.metadata.name]
        assert cron.last_schedule_time == ts(2026, 1, 1, 10, 5)

    def test_requeue_is_time_to_next_fire(self):
        store, ctrl, clock = self.setup_cron()
        requeue = ctrl.reconcile("default", "nightly")
        assert requeue == pytest.approx(300, abs=1)

    def test_forbid_skips_while_active(self):
        store, ctrl, clock = self.setup_cron(policy=ConcurrencyPolicy.FORBID)
        clock.t = ts(2026, 1, 1, 10, 5)
        ctrl.reconcile("default", "nightly")
        assert len(store.list("TPUJob")) == 1
        clock.t = ts(2026, 1, 1, 10, 10)
        ctrl.reconcile("default", "nightly")
        assert len(store.list("TPUJob")) == 1  # skipped
        cron = store.get("Cron", "nightly")
        assert cron.last_schedule_time == ts(2026, 1, 1, 10, 10)

    def test_replace_deletes_active(self):
        store, ctrl, clock = self.setup_cron(policy=ConcurrencyPolicy.REPLACE)
        clock.t = ts(2026, 1, 1, 10, 5)
        ctrl.reconcile("default", "nightly")
        first = store.list("TPUJob")[0].metadata.name
        clock.t = ts(2026, 1, 1, 10, 10)
        ctrl.reconcile("default", "nightly")
        names = [j.metadata.name for j in store.list("TPUJob")]
        assert first not in names and len(names) == 1

    def test_allow_runs_concurrently(self):
        store, ctrl, clock = self.setup_cron()
        clock.t = ts(2026, 1, 1, 10, 5)
        ctrl.reconcile("default", "nightly")
        clock.t = ts(2026, 1, 1, 10, 10)
        ctrl.reconcile("default", "nightly")
        assert len(store.list("TPUJob")) == 2

    def test_suspend(self):
        store, ctrl, clock = self.setup_cron()
        cron = store.get("Cron", "nightly")
        cron.suspend = True
        store.update(cron)
        clock.t = ts(2026, 1, 1, 10, 5)
        ctrl.reconcile("default", "nightly")
        assert store.list("TPUJob") == []

    def test_starting_deadline_skips_stale_run(self):
        store, ctrl, clock = self.setup_cron()
        cron = store.get("Cron", "nightly")
        cron.starting_deadline_seconds = 60.0
        store.update(cron)
        clock.t = ts(2026, 1, 1, 11, 7)  # last fire 11:05 is 120s stale
        ctrl.reconcile("default", "nightly")
        assert store.list("TPUJob") == []
        cron = store.get("Cron", "nightly")
        assert cron.last_schedule_time == ts(2026, 1, 1, 11, 5)

    def test_history_ring_and_finished_trim(self):
        store, ctrl, clock = self.setup_cron()
        cron = store.get("Cron", "nightly")
        cron.history_limit = 2
        store.update(cron)
        for minute in (5, 10, 15):
            clock.t = ts(2026, 1, 1, 10, minute)
            ctrl.reconcile("default", "nightly")
        cron = store.get("Cron", "nightly")
        assert len(cron.history) == 2  # ring trimmed
        assert len(store.list("TPUJob")) == 2  # overflow object deleted
        # finish one job -> drops from active, history shows phase
        job_name = cron.active[0]
        def finish(obj):
            obj.status.set_condition(JobConditionType.SUCCEEDED, "done", "")
            obj.status.completion_time = clock.t
        store.update_with_retry("TPUJob", job_name, "default", finish)
        ctrl.reconcile("default", "nightly")
        cron = store.get("Cron", "nightly")
        assert job_name not in cron.active
        entry = next(e for e in cron.history if e.object_name == job_name)
        assert entry.status == "Succeeded"
        assert entry.finished == clock.t

    def test_too_many_missed_runs_warns_and_fires_latest(self):
        store, ctrl, clock = self.setup_cron(schedule="* * * * *")
        clock.t = ts(2026, 1, 1, 14, 0)  # 240 missed minutes
        ctrl.reconcile("default", "nightly")
        assert len(store.list("TPUJob")) == 1  # only the latest fires
        events = [e for e in store.list("Event")
                  if e.reason == "TooManyMissedRuns"]
        assert events


def test_cron_template_passes_admission():
    """r2 review: cron-materialized jobs must go through the same
    admission as direct submits — an invalid template surfaces as a
    Warning event instead of churning the store every tick."""
    from kubedl_tpu.operator import ValidationError
    from kubedl_tpu.workloads.tpujob import TPUJob, TPUJobController

    store = ObjectStore()
    clock = FakeClock(ts(2026, 1, 1, 10, 0))
    controller = TPUJobController(local_addresses=True)

    def submitter(job):
        errs = controller.validate(job)
        if errs:
            raise ValidationError(job.kind, errs)
        controller.apply_defaults(job)
        return store.create(job)

    ctrl = CronController(store, ["TPUJob"], clock=clock, submitter=submitter)
    bad = TPUJob()
    bad.metadata.name = "tpl"  # no replica specs: invalid
    cron = Cron(schedule="*/5 * * * *", template=bad)
    cron.metadata.name = "bad-cron"
    cron.metadata.creation_timestamp = clock.t
    store.create(cron)
    clock.t = ts(2026, 1, 1, 10, 5)
    ctrl.reconcile("default", "bad-cron")
    evs = [e for e in store.list("Event")
           if e.reason == "CronTemplateRejected"]
    assert evs, "expected a CronTemplateRejected event"
    assert not store.list("TPUJob")  # invalid job never reached the store


def test_cron_through_operator_uses_admission(tmp_path):
    """The operator wires Operator.submit as the cron submitter."""
    from kubedl_tpu.operator import Operator, OperatorOptions
    from kubedl_tpu.runtime.executor import FakeRuntime

    opts = OperatorOptions(local_addresses=True,
                           artifact_registry_root=str(tmp_path / "r"))
    op = Operator(opts, runtime=FakeRuntime())
    assert op.cron.submitter == op.submit

"""Serving tests (reference analogue: controllers/serving suite): predictor
gating on artifact build, canary weight normalization, framework setters,
and a real end-to-end generate through the JAX server."""

import json
import time

import pytest

from kubedl_tpu.core.manager import ControllerManager
from kubedl_tpu.core.objects import PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.lineage.types import Model, ModelVersion, ModelVersionPhase
from kubedl_tpu.serving.controller import (
    LABEL_PREDICTOR,
    HTTP_PORT,
    InferenceController,
)
from kubedl_tpu.serving.types import (
    Framework,
    Inference,
    Predictor,
    TrafficPolicy,
)

from tests.helpers import PodDriver, env_of


def make_mv(store, name="mv1", model="m1", phase=ModelVersionPhase.SUCCEEDED,
            storage_root="/tmp/x"):
    mv = ModelVersion(model_name=model, storage_root=storage_root,
                      image=f"models/{model}:{name}", phase=phase)
    mv.metadata.name = name
    store.create(mv)
    return mv


def make_inference(store, predictors, framework=Framework.JAX, name="inf1"):
    inf = Inference(framework=framework, predictors=predictors)
    inf.metadata.name = name
    store.create(inf)
    return inf


def setup():
    store = ObjectStore()
    ctrl = InferenceController(store, local_addresses=True)
    return store, ctrl


class TestPredictorSync:
    def test_gated_on_artifact_build(self):
        store, ctrl = setup()
        make_mv(store, phase=ModelVersionPhase.IMAGE_BUILDING)
        make_inference(store, [Predictor(name="main", model_version="mv1")])
        ctrl.reconcile("default", "inf1")
        assert store.list("Pod") == []  # gated (reference :149-204)
        inf = store.get("Inference", "inf1")
        assert "waiting for artifact" in inf.predictor_statuses["main"].message
        # build completes -> pods appear
        def done(mv):
            mv.phase = ModelVersionPhase.SUCCEEDED
        store.update_with_retry("ModelVersion", "mv1", "default", done)
        ctrl.reconcile("default", "inf1")
        pods = store.list("Pod")
        assert [p.metadata.name for p in pods] == ["inf1-main-0"]

    def test_entry_service_and_scale(self):
        store, ctrl = setup()
        make_mv(store)
        make_inference(store, [Predictor(name="main", model_version="mv1",
                                         replicas=3)])
        ctrl.reconcile("default", "inf1")
        assert store.try_get("Service", "inf1", "default") is not None
        assert len(store.list("Pod")) == 3
        # scale down
        inf = store.get("Inference", "inf1")
        inf.predictors[0].replicas = 1
        store.update(inf)
        ctrl.reconcile("default", "inf1")
        assert len(store.list("Pod")) == 1

    def test_latest_version_tracking(self):
        store, ctrl = setup()
        mv = make_mv(store, name="mv2", model="m1")
        model = Model(latest_version="mv2")
        model.metadata.name = "m1"
        store.create(model)
        make_inference(store, [Predictor(name="main", model_name="m1")])
        ctrl.reconcile("default", "inf1")
        inf = store.get("Inference", "inf1")
        assert inf.predictor_statuses["main"].image == mv.image

    def test_jax_setter_env(self):
        store, ctrl = setup()
        make_mv(store, storage_root="/ckpts/m1")
        make_inference(store, [Predictor(name="main", model_version="mv1")])
        ctrl.reconcile("default", "inf1")
        pod = store.get("Pod", "inf1-main-0")
        env = env_of(pod)
        assert env["KUBEDL_MODEL_PATH"] == "/ckpts/m1"
        cfg = json.loads(env["KUBEDL_SERVE_CONFIG"])
        assert cfg["port"] == HTTP_PORT
        assert pod.spec.main_container().entrypoint == (
            "kubedl_tpu.serving.server:serve_main"
        )

    def test_tfserving_setter_env(self):
        store, ctrl = setup()
        make_mv(store)
        make_inference(store, [Predictor(name="main", model_version="mv1")],
                       framework=Framework.TF_SERVING)
        ctrl.reconcile("default", "inf1")
        env = env_of(store.get("Pod", "inf1-main-0"))
        assert env["MODEL_NAME"] == "m1"
        assert env["MODEL_BASE_PATH"] == "/models/m1"

    def test_removed_predictor_gc(self):
        store, ctrl = setup()
        make_mv(store)
        make_inference(store, [
            Predictor(name="a", model_version="mv1"),
            Predictor(name="b", model_version="mv1"),
        ])
        ctrl.reconcile("default", "inf1")
        assert len(store.list("Pod")) == 2
        inf = store.get("Inference", "inf1")
        inf.predictors = [p for p in inf.predictors if p.name == "a"]
        store.update(inf)
        ctrl.reconcile("default", "inf1")
        names = [p.metadata.name for p in store.list("Pod")]
        assert names == ["inf1-a-0"]


class TestTraffic:
    def test_canary_weights_normalized_over_ready(self):
        store, ctrl = setup()
        driver = PodDriver(store)
        make_mv(store)
        make_inference(store, [
            Predictor(name="stable", model_version="mv1", traffic_weight=90),
            Predictor(name="canary", model_version="mv1", traffic_weight=10),
        ])
        ctrl.reconcile("default", "inf1")
        # nothing ready yet -> no routes
        tp = store.get("TrafficPolicy", "inf1")
        assert tp.routes == []
        # only stable ready -> 100% stable (never route to dead canary)
        driver.run("inf1-stable-0")
        ctrl.reconcile("default", "inf1")
        tp = store.get("TrafficPolicy", "inf1")
        assert {r.predictor: r.weight for r in tp.routes} == {"stable": 100}
        # both ready -> 90/10
        driver.run("inf1-canary-0")
        ctrl.reconcile("default", "inf1")
        tp = store.get("TrafficPolicy", "inf1")
        weights = {r.predictor: r.weight for r in tp.routes}
        assert weights == {"stable": 90, "canary": 10}
        assert sum(weights.values()) == 100


class TestEndToEndServe:
    def test_generate_through_operator(self, tmp_path):
        """Train-less serve: publish a ModelVersion, create an Inference,
        wait for the predictor pod to run the real JAX server, hit HTTP."""
        import urllib.request

        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import ThreadRuntime

        opts = OperatorOptions(
            local_addresses=True,
            artifact_registry_root=str(tmp_path / "reg"),
        )
        model_dir = tmp_path / "model"
        model_dir.mkdir()
        with Operator(opts, runtime=ThreadRuntime()) as op:
            mv = ModelVersion(model_name="m1", storage_root=str(model_dir),
                              phase=ModelVersionPhase.PENDING)
            mv.metadata.name = "mv1"
            op.store.create(mv)
            pred = Predictor(name="main", model_version="mv1")
            port = 18080
            pred.template.spec.main_container().set_env(
                "KUBEDL_SERVE_CONFIG", json.dumps({"port": port, "preset": "tiny"})
            )
            inf = Inference(framework=Framework.JAX, predictors=[pred])
            inf.metadata.name = "inf1"
            op.store.create(inf)

            # wait for the server pod to come up and answer
            deadline = time.time() + 60
            result = None
            while time.time() < deadline:
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/v1/generate",
                        data=json.dumps(
                            {"prompt_ids": [1, 2, 3], "max_tokens": 4}
                        ).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=5) as resp:
                        result = json.loads(resp.read())
                    break
                except Exception:
                    time.sleep(0.5)
            assert result is not None, "server never answered"
            assert len(result["token_ids"]) == 4
            assert result["prompt_len"] == 3
            tp = op.store.get("TrafficPolicy", "inf1")
            # serving pod is Running -> traffic routed to it
            assert any(r.predictor == "main" for r in tp.routes)


class TestContinuousBatching:
    def _reference_generate(self, engine, prompt, n):
        """Oracle: the original single-sequence decode_step loop."""
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = engine.cfg
        decode = jax.jit(lambda p, c, t: llama.decode_step(p, c, t, cfg))
        cache = llama.init_cache(cfg, 1, engine.max_seq)
        logits = None
        for tok in prompt:
            logits, cache = decode(engine.params, cache,
                                   jnp.full((1, 1), int(tok), jnp.int32))
        out = []
        for _ in range(n):
            nxt = int(logits[0].argmax())
            out.append(nxt)
            logits, cache = decode(engine.params, cache,
                                   jnp.full((1, 1), nxt, jnp.int32))
        return out

    def test_batched_matches_single_sequence_oracle(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            prompt = [5, 9, 13]
            got = eng.generate(prompt, max_tokens=6)
            want = self._reference_generate(eng, prompt, 6)
            assert got["token_ids"] == want
            assert got["prompt_len"] == 3
        finally:
            eng.close()

    def test_concurrent_requests_interleave_and_match(self):
        import threading

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=4, max_seq=64)
        try:
            prompts = [[1, 2], [7], [11, 3, 5], [2, 2, 2, 2]]
            want = [self._reference_generate(eng, p, 5) for p in prompts]
            results = [None] * len(prompts)

            def worker(i):
                results[i] = eng.generate(prompts[i], max_tokens=5)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for i, r in enumerate(results):
                assert r is not None and r["token_ids"] == want[i], (i, r)
        finally:
            eng.close()

    def test_more_requests_than_slots_all_complete(self):
        import threading

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            results = [None] * 5

            def worker(i):
                results[i] = eng.generate([i + 1], max_tokens=3)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(5)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(r is not None and len(r["token_ids"]) == 3
                       for r in results), results
        finally:
            eng.close()

    def test_temperature_sampling_varies(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64)
        try:
            outs = {tuple(eng.generate([3], max_tokens=8,
                                       temperature=2.0)["token_ids"])
                    for _ in range(5)}
            assert len(outs) > 1  # hot sampling is actually stochastic
        finally:
            eng.close()


def test_stats_endpoint_counts_requests():
    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
    try:
        eng.generate([1, 2], max_tokens=3)
        eng.generate([3], max_tokens=2)
        st = eng.stats()
        assert st["requests"] == 2
        assert st["tokens_out"] == 5
        assert st["tokens_in"] == 3
        assert st["qps"] > 0 and st["max_batch"] == 2
    finally:
        eng.close()


class TestAutoscaler:
    """Closed-loop QPS autoscaling (the reference only stubs autoScale in
    its API; here the controller drives replicas from live load)."""

    def _setup(self, qps_by_pod, clock):
        from kubedl_tpu.core.objects import PodPhase
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
        from kubedl_tpu.serving.controller import InferenceController
        from kubedl_tpu.serving.types import AutoScaleSpec, Inference, Predictor

        store = ObjectStore()
        mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED,
                          image="m:v1")
        mv.metadata.name = "m-v1"
        store.create(mv)

        def probe(pod):
            return qps_by_pod.get(pod.metadata.name, 0.0)

        ctrl = InferenceController(store, local_addresses=True,
                                   qps_probe=probe, clock=clock)
        inf = Inference()
        inf.metadata.name = "svc"
        inf.predictors.append(Predictor(
            name="main", model_version="m-v1", replicas=1,
            autoscale=AutoScaleSpec(min_replicas=1, max_replicas=4,
                                    target_qps=10.0),
        ))
        store.create(inf)

        def run_pods():
            for p in store.list("Pod"):
                if p.status.phase != PodPhase.RUNNING:
                    def mut(o):
                        o.status.phase = PodPhase.RUNNING
                    store.update_with_retry("Pod", p.metadata.name,
                                            "default", mut)
        return store, ctrl, run_pods

    def test_scales_up_on_load_and_down_after_cooldown(self):
        t = {"now": 1000.0}
        qps = {}
        store, ctrl, run_pods = self._setup(qps, clock=lambda: t["now"])
        ctrl.reconcile("default", "svc")
        run_pods()
        ctrl.reconcile("default", "svc")
        pods = [p.metadata.name for p in store.list("Pod")]
        assert pods == ["svc-main-0"]
        # load arrives: 35 qps against target 10 -> 4 replicas (max-capped)
        qps["svc-main-0"] = 35.0
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 4
        assert any(e.reason == "Autoscaled" for e in store.list("Event"))
        run_pods()
        # load drops immediately: cooldown holds the fleet steady...
        qps["svc-main-0"] = 1.0
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 4
        # ...until the cooldown window passes
        t["now"] += 60.0
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 1

    def test_no_probe_means_clamp_only(self):
        store, ctrl, run_pods = self._setup({}, clock=lambda: 0.0)
        ctrl.qps_probe = None
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 1  # min_replicas clamp, no scaling


def test_windowed_qps_not_lifetime_average():
    """r2 review: the autoscale signal must track LIVE load — a long-idle
    engine then hit by a burst must report the burst, not ~0."""
    import time as _time

    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
    try:
        # simulate a long-idle engine (backdate start + use a small window)
        eng._stats["started_at"] = _time.time() - 3600
        eng.qps_window_s = 5.0
        for _ in range(4):
            eng.generate([1], max_tokens=1)
        st = eng.stats()
        assert st["qps"] >= 0.5, st  # burst visible in the window
        assert st["lifetime_qps"] < 0.01, st  # the old signal would miss it
    finally:
        eng.close()


def test_probe_failure_never_scales_down(tmp_path):
    """r2 review: missing metrics must not justify deleting capacity."""
    import math

    from kubedl_tpu.core.objects import PodPhase
    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
    from kubedl_tpu.serving.controller import InferenceController
    from kubedl_tpu.serving.types import AutoScaleSpec, Inference, Predictor

    store = ObjectStore()
    mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED)
    mv.metadata.name = "m-v1"
    store.create(mv)
    qps = {"value": 40.0, "fail": False}

    def probe(pod):
        if qps["fail"]:
            raise TimeoutError("probe timeout")
        return qps["value"]

    t = {"now": 0.0}
    ctrl = InferenceController(store, local_addresses=True, qps_probe=probe,
                               clock=lambda: t["now"])
    inf = Inference()
    inf.metadata.name = "svc2"
    inf.predictors.append(Predictor(
        name="main", model_version="m-v1", replicas=1,
        autoscale=AutoScaleSpec(min_replicas=1, max_replicas=4,
                                target_qps=10.0)))
    store.create(inf)
    ctrl.reconcile("default", "svc2")
    for p in store.list("Pod"):
        def mut(o):
            o.status.phase = PodPhase.RUNNING
        store.update_with_retry("Pod", p.metadata.name, "default", mut)
    ctrl.reconcile("default", "svc2")  # scales to 4 on load
    for p in store.list("Pod"):
        def mut(o):
            o.status.phase = PodPhase.RUNNING
        store.update_with_retry("Pod", p.metadata.name, "default", mut)
    assert len(store.list("Pod")) == 4
    # probes start failing under overload: fleet must HOLD, not shrink
    qps["fail"] = True
    t["now"] += 120.0
    ctrl.reconcile("default", "svc2")
    assert len(store.list("Pod")) == 4


class TestPrefill:
    """Batched prefill (round-3 #2): whole prompts in ONE forward, then
    per-row dynamic-slice cache updates in decode."""

    def test_prefill_matches_stepwise_decode(self):
        """Prefilling a prompt must leave the cache/logits exactly where
        feeding it token-by-token through decode_step_batched would."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubedl_tpu.models import llama

        cfg = llama.TINY
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        prompt = [5, 9, 13, 2, 7]
        B, T = 2, 32

        # stepwise oracle: feed each prompt token through the decode step
        cache_a = llama.init_batched_cache(cfg, B, T)
        logits_a = None
        for tok in prompt:
            toks = jnp.zeros((B, 1), jnp.int32).at[0, 0].set(tok)
            logits_a, cache_a = llama.decode_step_batched(
                params, cache_a, toks, cfg
            )

        # prefill: one forward, row 1 inactive (length 0)
        cache_b = llama.init_batched_cache(cfg, B, T)
        toks = jnp.zeros((B, 8), jnp.int32).at[0, : len(prompt)].set(
            jnp.asarray(prompt)
        )
        lens = jnp.asarray([len(prompt), 0], jnp.int32)
        logits_b, cache_b = llama.prefill_batched(
            params, cache_b, toks, lens, cfg
        )

        assert int(cache_b["pos"][0]) == len(prompt)
        assert int(cache_b["pos"][1]) == 0  # inactive row untouched
        np.testing.assert_allclose(
            np.asarray(logits_a[0]), np.asarray(logits_b[0]),
            rtol=2e-4, atol=2e-4,
        )
        # row 0's cached K/V over the prompt span must agree
        np.testing.assert_allclose(
            np.asarray(cache_a["k"][:, 0, : len(prompt)]),
            np.asarray(cache_b["k"][:, 0, : len(prompt)]),
            rtol=2e-4, atol=2e-4,
        )
        # inactive row's cache really untouched (still zeros)
        assert float(jnp.abs(cache_b["k"][:, 1]).sum()) == 0.0

    def test_prefill_bucket_sizes(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64)
        try:
            assert eng._prefill_bucket(1) == 16
            assert eng._prefill_bucket(16) == 16
            assert eng._prefill_bucket(17) == 32
            assert eng._prefill_bucket(63) == 64
            assert eng._prefill_bucket(1000) == 64  # clamped to max_seq
        finally:
            eng.close()

    def test_long_prompt_single_tick(self):
        """A prompt near max_seq completes with 1 token without issue
        (prefill + a single decode step, not 60+ sequential steps)."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            prompt = list(range(1, 50))
            got = eng.generate(prompt, max_tokens=2)
            assert len(got["token_ids"]) == 2
            assert got["prompt_len"] == 49
        finally:
            eng.close()


def test_generate_timeout_frees_slot():
    """ADVICE r2 #5: an abandoned (timed-out) request must release its
    queue entry / batch row instead of occupying it until natural
    completion."""
    from kubedl_tpu.serving.server import LlamaEngine, _Slot

    eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64)
    try:
        # freeze the scheduler so the request can never complete
        with eng._cv:
            eng._stop = True
            eng._cv.notify_all()
        eng._thread.join(timeout=10)
        out = eng.generate([1, 2], max_tokens=4, timeout_s=0.2)
        assert out["error"] == "timed out"
        assert list(eng._waiting) == []  # queue entry released
        # row-occupying case: simulate a slot stuck mid-decode
        stuck = _Slot([1], 4, 0.0)
        eng._slots[0] = stuck
        out2 = eng.generate([3], max_tokens=1, timeout_s=0.2)
        assert out2["error"] == "timed out"
        assert list(eng._waiting) == []
    finally:
        eng._thread.join(timeout=1)


class TestInt8Quantization:
    """Weight-only int8 for serving (decode is HBM-bound; measured on
    v5e-1 Gemma-2B: b1 119 -> 199 tok/s, b8 793 -> 1218 tok/s)."""

    def test_quantize_roundtrip_accuracy(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubedl_tpu.models import llama

        cfg = llama.TINY
        p = llama.llama_init(jax.random.PRNGKey(0), cfg)
        qp = llama.quantize_params(p, cfg)
        # per-column symmetric: dequantized weights within 1/127 of scale
        w = np.asarray(p["layers"]["wq"], np.float32)
        dq = np.asarray(llama.deq(qp["layers"]["wq"]), np.float32)
        colmax = np.abs(w).max(axis=-2, keepdims=True)
        # int8 step + bf16 scale rounding (~2^-8 relative)
        bound = colmax / 127.0 + np.abs(w) * 2.0 ** -7 + 1e-6
        assert np.all(np.abs(w - dq) <= bound)
        # norms untouched
        assert qp["layers"]["attn_norm"] is p["layers"]["attn_norm"]

    def test_forward_decode_prefill_close_to_fp(self):
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.TINY
        p = llama.llama_init(jax.random.PRNGKey(0), cfg)
        qp = llama.quantize_params(p, cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                  cfg.vocab_size)
        lf = llama.llama_forward(p, toks, cfg)
        lq = llama.llama_forward(qp, toks, cfg)
        rel = float(jnp.abs(lf - lq).max() / (jnp.abs(lf).max() + 1e-9))
        assert rel < 0.1, rel
        # decode + prefill paths run with quantized params
        cache = llama.init_batched_cache(cfg, 2, 32)
        logits, cache = llama.decode_step_batched(qp, cache, toks[:, :1], cfg)
        assert logits.shape == (2, cfg.vocab_size)
        pre, _ = llama.prefill_batched(
            qp, llama.init_batched_cache(cfg, 2, 32), toks,
            jnp.array([16, 16]), cfg,
        )
        assert pre.shape == (2, cfg.vocab_size)
        # the single-sequence decode_step path accepts quantized params too
        sc = llama.init_cache(cfg, 2, 32)
        ls, _ = llama.decode_step(qp, sc, toks[:, :1], cfg)
        assert ls.shape == (2, cfg.vocab_size)

    def test_engine_serves_quantized(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          quantize="int8")
        try:
            got = eng.generate([5, 9, 13], max_tokens=6)
            assert len(got["token_ids"]) == 6
            assert got["prompt_len"] == 3
        finally:
            eng.close()
        import pytest as _pytest

        with _pytest.raises(ValueError, match="quantize"):
            LlamaEngine(preset="tiny", quantize="fp4")

    def test_tied_embeddings_quantized(self):
        """Gemma ties lm_head to the embedding: the quantized head path
        (deq(embed).T) must work too."""
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.TINY_GEMMA
        p = llama.llama_init(jax.random.PRNGKey(0), cfg)
        qp = llama.quantize_params(p, cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                  cfg.vocab_size)
        lf = llama.llama_forward(p, toks, cfg)
        lq = llama.llama_forward(qp, toks, cfg)
        rel = float(jnp.abs(lf - lq).max() / (jnp.abs(lf).max() + 1e-9))
        assert rel < 0.15, rel


def test_predictor_quantize_rides_serve_config():
    """quantize is a first-class Predictor field: the JAX setter plumbs it
    into KUBEDL_SERVE_CONFIG so a canary can A/B int8 vs full precision."""
    import json as _json

    store, ctrl = setup()
    make_mv(store)
    make_inference(store, [
        Predictor(name="fp", model_version="mv1"),
        Predictor(name="q8", model_version="mv1", quantize="int8"),
    ])
    ctrl.reconcile("default", "inf1")
    from tests.helpers import env_of as _env_of

    cfg_fp = _json.loads(_env_of(store.get("Pod", "inf1-fp-0"))["KUBEDL_SERVE_CONFIG"])
    cfg_q8 = _json.loads(_env_of(store.get("Pod", "inf1-q8-0"))["KUBEDL_SERVE_CONFIG"])
    assert cfg_fp["quantize"] == ""
    assert cfg_q8["quantize"] == "int8"


class TestShardedServing:
    """Mesh-sharded serving (BASELINE target 5: Gemma-2B on a v5e-4):
    weights megatron-shard over a tensor axis; greedy outputs must equal
    the single-device engine exactly."""

    def test_tensor_sharded_matches_unsharded(self):
        # exact equality holds on the fp32 TINY model; on bf16 hardware,
        # row-parallel psum reduction order can flip near-tie argmaxes
        from kubedl_tpu.serving.server import LlamaEngine

        eng1 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            want = eng1.generate([5, 9, 13], max_tokens=6)["token_ids"]
        finally:
            eng1.close()
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          mesh_axes={"tensor": 4})
        try:
            got = eng.generate([5, 9, 13], max_tokens=6)
            assert got["token_ids"] == want
            # weights really are sharded over 4 devices
            wq = eng.params["layers"]["wq"]
            assert len(wq.sharding.device_set) == 4
        finally:
            eng.close()

    def test_sharded_plus_int8(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          mesh_axes={"tensor": 2}, quantize="int8")
        try:
            got = eng.generate([3, 7], max_tokens=5)
            assert len(got["token_ids"]) == 5
            q8 = eng.params["layers"]["wq"]["q8"]
            assert len(q8.sharding.device_set) == 2
        finally:
            eng.close()

    def test_mesh_rides_serve_config(self):
        """`mesh` in KUBEDL_SERVE_CONFIG reaches the engine (predictor
        templates set it for multi-chip serving hosts)."""
        from kubedl_tpu.serving.server import engine_kwargs

        kw = engine_kwargs(
            {"preset": "tiny", "mesh": {"tensor": 2}, "quantize": "int8",
             "max_batch": 3, "max_queue_depth": 8, "max_queue_age_s": 5.0},
            "/ckpts/m",
        )
        assert kw == {"preset": "tiny", "ckpt_dir": "/ckpts/m",
                      "max_batch": 3, "quantize": "int8",
                      "mesh_axes": {"tensor": 2},
                      "max_queue_depth": 8, "max_queue_age_s": 5.0,
                      "prefix_cache_mb": 64.0,
                      "kv_layout": "paged", "kv_block_size": 16,
                      "kv_blocks": 0, "spec_k": 0, "spec_draft": "ngram",
                      "kv_attention": "gather", "spec_candidates": 1,
                      "spec_draft_layers": 0, "spec_tree": False,
                      "prefill_chunk_tokens": 0,
                      "advertise_prefix_len": 8, "role": "colocated",
                      "model_version": "base"}
        defaults = engine_kwargs({}, "")
        assert defaults["mesh_axes"] is None
        # load-shedding budget defaults ride the config too
        assert defaults["max_queue_depth"] == 64
        assert defaults["max_queue_age_s"] == 30.0
        # prefix cache rides the config (0 disables it)
        assert defaults["prefix_cache_mb"] == 64.0
        assert engine_kwargs({"prefix_cache_mb": 0}, "")["prefix_cache_mb"] == 0.0


class TestSegmentPolicy:
    """Pure host-side tests of the segment-size bucket policy (no device
    work): the `up - need <= up // 4` round-up rule and the while-waiting
    cap that bounds admission latency."""

    def test_round_up_only_on_small_overshoot(self):
        from kubedl_tpu.serving.server import LlamaEngine

        seg = LlamaEngine.segment_size
        assert seg(32, 32) == 32  # exact
        assert seg(31, 32) == 32  # overshoot 1 <= 8: run 32, discard 1
        assert seg(24, 32) == 32  # overshoot 8 == 32 // 4: still up
        assert seg(23, 32) == 4   # overshoot 9 > 8: step down
        assert seg(7, 32) == 4    # up to 32 would waste 25 decodes
        assert seg(4, 32) == 4
        assert seg(3, 32) == 4    # up=4, overshoot 1 <= 1
        assert seg(2, 32) == 1    # up=4, overshoot 2 > 1: down to 1
        assert seg(1, 32) == 1

    def test_waiting_cap_clamps_need(self):
        from kubedl_tpu.serving.server import LlamaEngine

        seg = LlamaEngine.segment_size
        # cap=4 (requests waiting): long budgets still decode in 4s so
        # admission latency stays <= 4 tokens
        assert seg(100, 4) == 4
        assert seg(100, 32) == 32
        assert seg(3, 4) == 4
        assert seg(2, 4) == 1
        assert seg(1, 4) == 1

    def test_degenerate_inputs(self):
        from kubedl_tpu.serving.server import LlamaEngine

        seg = LlamaEngine.segment_size
        assert seg(0, 32) == 1    # need clamps to >= 1
        assert seg(100, 1) == 1   # cap dominates
        assert seg(5, 3) == 4     # need clamps to cap=3, then rounds to 4


class TestChainAcrossPrefill:
    """The device token chain across interleaved prefills: merged on
    device when row sets allow (no host round trip), rebuilt from host
    tokens when the generation goes stale."""

    def _freeze(self, eng):
        """Stop the background scheduler so the test drives ticks."""
        with eng._cv:
            eng._stop = True
            eng._cv.notify_all()
        eng._thread.join(timeout=10)
        eng._stop = False

    def _drive(self, eng, slots, max_ticks=200):
        n = 0
        while not all(s.done.is_set() for s in slots):
            eng._loop_once()
            n += 1
            assert n < max_ticks, "pipeline did not converge"

    def test_interleaved_prefill_merges_chain_on_device(self):
        """A prefill landing mid-generation must NOT force the running
        row's token feed through the host: the sampled first token is
        merged into the device chain and both outputs stay exact."""
        from kubedl_tpu.serving.server import LlamaEngine, _Slot

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        oracle = TestContinuousBatching()
        try:
            self._freeze(eng)
            a = _Slot([5, 9, 13], 12, 0.0)
            with eng._cv:
                eng._waiting.append(a)
            eng._loop_once()  # prefill A + segment 1 in flight
            b = _Slot([7], 6, 0.0)  # arrives mid-generation
            with eng._cv:
                eng._waiting.append(b)
            self._drive(eng, [a, b])
            assert a.result["token_ids"] == oracle._reference_generate(
                eng, [5, 9, 13], 12
            )
            assert b.result["token_ids"] == oracle._reference_generate(
                eng, [7], 6
            )
            assert eng.pipeline_stats()["chain_rebuilds"] == 0
        finally:
            eng.close()

    def test_stale_chain_rebuilt_from_host_tokens(self):
        """A `_prefill_gen` bump invalidates the chain: the next tick must
        flush the in-flight segment (its values feed `next_input`), rebuild
        the token feed host-side, and still produce exact output."""
        from kubedl_tpu.serving.server import LlamaEngine, _Slot

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64)
        oracle = TestContinuousBatching()
        try:
            self._freeze(eng)
            a = _Slot([5, 9, 13], 10, 0.0)
            with eng._cv:
                eng._waiting.append(a)
            eng._loop_once()  # prefill + segment 1 in flight, chain live
            assert eng._chain is not None
            eng._prefill_gen += 1  # stale: what recovery paths produce
            self._drive(eng, [a])
            assert a.result["token_ids"] == oracle._reference_generate(
                eng, [5, 9, 13], 10
            )
            pipe = eng.pipeline_stats()
            assert pipe["chain_rebuilds"] >= 1
            assert eng.metrics.chain_rebuilds.value() >= 1.0
        finally:
            eng.close()


def test_scheduler_recovers_after_segment_failure():
    """Injected segment failure: the in-flight request fails, the donated
    cache + deferred segment are dropped safely, pipeline counters reset
    (the r5 stats-drift fix), and the NEXT request serves exactly."""
    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
    oracle = TestContinuousBatching()
    try:
        orig = eng._segment_fn
        state = {"armed": True}

        def boom(k, greedy):
            fn = orig(k, greedy)

            def wrapped(*a, **kw):
                if state["armed"]:
                    state["armed"] = False
                    raise RuntimeError("injected segment failure")
                return fn(*a, **kw)

            return wrapped

        eng._segment_fn = boom
        r1 = eng.generate([5, 9], max_tokens=6, timeout_s=60)
        assert "injected segment failure" in r1.get("error", ""), r1
        r2 = eng.generate([5, 9, 13], max_tokens=6, timeout_s=60)
        assert r2["token_ids"] == oracle._reference_generate(
            eng, [5, 9, 13], 6
        )
        pipe = eng.pipeline_stats()
        assert pipe["errors"] == 1
        assert pipe["inflight"] == 0
        # post-recovery accounting describes the recovered engine only
        assert pipe["ticks"] >= 1
        assert eng.metrics.scheduler_errors.value() == 1.0
    finally:
        eng.close()


def test_pipeline_stats_and_metrics_endpoint():
    """Pipeline accounting is visible end to end: stats() carries the
    per-tick timings, and /metrics exports the Prometheus family."""
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from kubedl_tpu.serving.server import LlamaEngine, make_handler

    eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
    try:
        eng.generate([1, 2, 3], max_tokens=8)
        eng.generate([4], max_tokens=8)
        st = eng.stats()
        pipe = st["pipeline"]
        assert pipe["ticks"] >= 1 and pipe["segments"] >= 1
        for k in ("dispatch_ms_avg", "harvest_ms_avg", "host_ms_avg",
                  "overlap_ratio", "dispatch_ms_p50", "tick_ms_p50"):
            assert k in pipe, (k, pipe)
        assert st["queued"] == 0

        srv = ThreadingHTTPServer(
            ("127.0.0.1", 0), make_handler(eng, "tiny")
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as r:
                text = r.read().decode()
                ctype = r.headers["Content-Type"]
        finally:
            srv.shutdown()
            srv.server_close()
        assert ctype.startswith("text/plain")
        assert "kubedl_tpu_serving_segments" in text
        assert "kubedl_tpu_serving_dispatch_ms_bucket" in text
        assert "kubedl_tpu_serving_overlap_ratio" in text
    finally:
        eng.close()


def test_queued_backlog_blocks_scale_down():
    """Dict-shaped probes (the full /v1/stats payload) feed the
    autoscaler; a backlog of queued requests vetoes scale-down even when
    completion-rate QPS looks idle."""
    from kubedl_tpu.core.objects import PodPhase
    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
    from kubedl_tpu.serving.controller import InferenceController
    from kubedl_tpu.serving.types import AutoScaleSpec, Inference, Predictor

    store = ObjectStore()
    mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED)
    mv.metadata.name = "m-v1"
    store.create(mv)
    load = {"qps": 35.0, "queued": 0}

    def probe(pod):
        return dict(load)

    t = {"now": 0.0}
    ctrl = InferenceController(store, local_addresses=True, qps_probe=probe,
                               clock=lambda: t["now"])
    inf = Inference()
    inf.metadata.name = "svc3"
    inf.predictors.append(Predictor(
        name="main", model_version="m-v1", replicas=1,
        autoscale=AutoScaleSpec(min_replicas=1, max_replicas=4,
                                target_qps=10.0)))
    store.create(inf)

    def run_pods():
        for p in store.list("Pod"):
            if p.status.phase != PodPhase.RUNNING:
                def mut(o):
                    o.status.phase = PodPhase.RUNNING
                store.update_with_retry("Pod", p.metadata.name, "default",
                                        mut)

    ctrl.reconcile("default", "svc3")
    run_pods()
    ctrl.reconcile("default", "svc3")  # dict probe drives scale-up
    assert len(store.list("Pod")) == 4
    run_pods()
    # QPS collapses because replicas saturate — but requests are QUEUED:
    # the backlog must veto the scale-down, cooldown or not
    load.update(qps=1.0, queued=6)
    t["now"] += 120.0
    ctrl.reconcile("default", "svc3")
    assert len(store.list("Pod")) == 4
    # backlog drains -> scale-down proceeds
    load.update(queued=0)
    t["now"] += 120.0
    ctrl.reconcile("default", "svc3")
    assert len(store.list("Pod")) == 1


class TestSchedulerMicrobench:
    """Tier-1 guard on host-side scheduler overhead: with the device
    stubbed out, per-tick time IS host overhead — regressions fail here
    instead of waiting for a full bench run."""

    def test_host_tick_overhead_within_budget(self):
        from scripts.scheduler_microbench import (
            TICK_BUDGET_MS,
            run_microbench,
        )

        out = run_microbench(requests=8, max_tokens=16, max_batch=4)
        assert out["tokens"] == 8 * 16
        assert out["tick_ms_p50"] <= TICK_BUDGET_MS, out
        assert out["within_budget"], out

    def test_prefix_match_graft_within_budget(self):
        """The prefix-cache admission path (observe + longest-prefix
        match + graft dispatch) is pure host work — it must fit the same
        per-tick envelope or reuse pays its savings back as overhead."""
        from scripts.scheduler_microbench import (
            PREFIX_BUDGET_MS,
            run_prefix_microbench,
        )

        out = run_prefix_microbench(requests=8, max_tokens=8, max_batch=4)
        assert out["hits"] == 8, out  # every request rode the cache
        assert out["tokens_saved"] >= 8 * out["prefix_len"]
        assert out["tick_ms_p50"] <= PREFIX_BUDGET_MS, out
        assert out["match_graft_ms"] <= PREFIX_BUDGET_MS, out
        assert out["within_budget"], out

    def test_paged_block_table_within_budget(self):
        """The paged layout's extra host work — mirror re-upload per
        dispatch plus allocator alloc/free on admission/finalize — must
        fit the same per-tick envelope, and the pool must drain back to
        empty (no block leaks) once every request completes."""
        from scripts.scheduler_microbench import (
            PAGED_BUDGET_MS,
            run_paged_microbench,
        )

        out = run_paged_microbench(requests=8, max_tokens=16, max_batch=4)
        assert out["tokens"] == 8 * 16
        assert out["blocks_leaked"] == 0, out
        assert out["tick_ms_p50"] <= PAGED_BUDGET_MS, out
        assert out["mirror_upload_ms"] <= PAGED_BUDGET_MS, out
        assert out["within_budget"], out

    def test_chunked_admission_within_budget(self):
        """The FIFO chunk scheduler (continuous batching) is pure host
        arithmetic on top of the paged tick — it must fit the same
        per-tick envelope, dispatch exactly ceil(len/budget) chunks per
        request, and leak no blocks."""
        from scripts.scheduler_microbench import (
            CHUNKED_BUDGET_MS,
            run_chunked_admission_microbench,
        )

        out = run_chunked_admission_microbench(
            requests=8, prompt_len=48, max_tokens=8, max_batch=4
        )
        assert out["tokens"] == 8 * 8
        assert out["chunks"] == 8 * 3  # 48 tokens / 16-token budget
        assert out["blocks_leaked"] == 0, out
        assert out["tick_ms_p50"] <= CHUNKED_BUDGET_MS, out
        assert out["within_budget"], out

    def test_tracing_disarmed_within_budget(self):
        """Every hot path calls TRACER unconditionally; with tracing
        disarmed the call must stay a near-free attribute test — an
        allocation or lock sneaking onto that path would tax every
        scheduler tick and router dispatch fleet-wide."""
        from scripts.scheduler_microbench import (
            TRACING_DISARMED_US,
            run_tracing_microbench,
        )

        out = run_tracing_microbench(calls=50_000)
        assert out["span_us"] <= TRACING_DISARMED_US, out
        assert out["begin_finish_us"] <= TRACING_DISARMED_US, out
        assert out["record_us"] <= TRACING_DISARMED_US, out
        assert out["within_budget"], out


class TestPrefixReuse:
    """Device-resident prefix KV cache (docs/serving.md "Prefix cache"):
    suffix-only prefill must be EXACTLY equivalent to full prefill for
    greedy decoding — causal attention's KV at position p depends only
    on tokens <= p, so a grafted cached prefix changes nothing."""

    def _freeze(self, eng):
        with eng._cv:
            eng._stop = True
            eng._cv.notify_all()
        eng._thread.join(timeout=10)
        eng._stop = False

    def test_suffix_prefill_matches_full_prefill(self):
        """Model-level equivalence: extract a row's prefix KV, graft it
        into a fresh cache, prefill only the suffix — same last-token
        logits, same cache contents over the valid span, same pos."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubedl_tpu.models import llama

        cfg = llama.TINY
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        B, T = 2, 64
        prompt = list(range(1, 21))  # 20 tokens: prefix 12 + suffix 8
        toks = np.zeros((B, 32), np.int32)
        toks[0, :20] = prompt
        lens = jnp.asarray(np.array([20, 0], np.int32))
        cache = llama.init_batched_cache(cfg, B, T)
        full_logits, full_cache = llama.prefill_batched(
            params, cache, jnp.asarray(toks), lens, cfg
        )
        # entry payload: first 16 positions of row 0 (12 valid + pad)
        k, v = llama.extract_prefix_from_row(full_cache, 0, 16)
        cache2 = llama.init_batched_cache(cfg, B, T)
        cache2 = llama.copy_prefix_into_row(cache2, k, v, 0, 12)
        assert int(cache2["pos"][0]) == 12
        suf = np.zeros((B, 16), np.int32)
        suf[0, :8] = prompt[12:]
        suf_logits, suf_cache = llama.prefill_batched_from(
            params, cache2, jnp.asarray(suf),
            jnp.asarray(np.array([8, 0], np.int32)),
            jnp.asarray(np.array([12, 0], np.int32)), cfg,
        )
        np.testing.assert_allclose(
            np.asarray(suf_logits[0]), np.asarray(full_logits[0]),
            rtol=2e-4, atol=2e-4,
        )
        assert int(suf_cache["pos"][0]) == 20
        np.testing.assert_allclose(
            np.asarray(suf_cache["k"][:, 0, :20]),
            np.asarray(full_cache["k"][:, 0, :20]),
            rtol=2e-4, atol=2e-4,
        )

    def test_greedy_equivalence_cache_on_vs_off(self):
        """Acceptance bar: with a shared >=8-token prefix, cache-on
        greedy token ids are bit-identical to cache-off, and the cache
        actually engaged (hits + tokens saved)."""
        from kubedl_tpu.serving.server import LlamaEngine

        shared = list(range(3, 15))  # 12-token shared system prompt
        prompts = [shared + [100 + j, 200 + j] for j in range(5)]
        ref = LlamaEngine(preset="tiny", max_seq=128, max_batch=4,
                          prefix_cache_mb=0)
        try:
            want = [ref.generate(p, max_tokens=6)["token_ids"]
                    for p in prompts]
        finally:
            ref.close()
        eng = LlamaEngine(preset="tiny", max_seq=128, max_batch=4,
                          prefix_cache_mb=8, prefix_min_len=8)
        try:
            got = [eng.generate(p, max_tokens=6) for p in prompts]
            assert [r["token_ids"] for r in got] == want
            st = eng.stats()["prefix_cache"]
            assert st["hits"] >= 1 and st["tokens_saved"] > 0
            assert st["pinned"] == 0  # every pin released at harvest
            # later requests actually rode the graft
            assert any(r["cached_prefix_len"] > 0 for r in got)
        finally:
            eng.close()

    def test_tagged_request_caches_on_first_sight(self):
        """`cache_prefix=True` (the HTTP body tag) inserts the prompt's
        prefix without waiting for min_seen repeats."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_seq=128, max_batch=2,
                          prefix_cache_mb=8, prefix_min_len=8)
        try:
            p = list(range(5, 20))
            eng.generate(p, max_tokens=2, cache_prefix=True)
            st = eng.stats()["prefix_cache"]
            assert st["inserts"] == 1 and st["entries"] == 1
            r = eng.generate(p + [42], max_tokens=2)
            assert r["cached_prefix_len"] >= 8
        finally:
            eng.close()

    def test_timeout_vacation_releases_pin(self):
        """Regression (satellite): a request that times out while its
        row is mid-prefill must release the prefix-cache pin its graft
        took — a leaked refcount blocks eviction forever."""
        import threading

        import numpy as np

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_seq=64, max_batch=1,
                          prefix_cache_mb=8, prefix_min_len=4)
        try:
            self._freeze(eng)  # test drives admission; prefill never runs
            L, _, _, KV, hd = eng._cache["k"].shape
            k = np.zeros((L, 16, KV, hd), np.float32)
            prefix = [1, 2, 3, 4, 5, 6]
            assert eng._pcache.insert(prefix, k, k.copy(), len(prefix))
            entry = eng._pcache._entries[tuple(prefix)]
            t = threading.Thread(
                target=eng.generate,
                args=(prefix + [7, 8],),
                kwargs={"max_tokens": 4, "timeout_s": 0.3},
            )
            t.start()
            # wait for the request to queue, then admit it: the match
            # pins the entry and the graft lands in row 0
            deadline = time.time() + 5
            while time.time() < deadline:
                with eng._cv:
                    eng._admit_locked()
                    if eng._slots[0] is not None:
                        break
                time.sleep(0.01)
            assert eng._slots[0] is not None
            assert entry.refs == 1 and eng._slots[0].cached_len == len(prefix)
            t.join(timeout=10)  # generate() times out and vacates
            assert not t.is_alive()
            assert entry.refs == 0, "vacated slot leaked its prefix pin"
            assert eng._slots[0] is None and list(eng._waiting) == []
        finally:
            with eng._cv:
                eng._stop = True
                eng._cv.notify_all()

    def test_graft_overflow_falls_back_to_full_prefill(self):
        """A graft whose start + suffix bucket would spill past max_seq
        must be dropped (dynamic_update_slice CLAMPS: the suffix would
        land at the wrong positions) — the row full-prefills instead and
        the output stays exact."""
        from kubedl_tpu.serving.server import LlamaEngine

        # max_seq=32: a 20-token prefix + 16-token min bucket overflows
        ref = LlamaEngine(preset="tiny", max_seq=32, max_batch=1,
                          prefix_cache_mb=0)
        eng = LlamaEngine(preset="tiny", max_seq=32, max_batch=1,
                          prefix_cache_mb=8, prefix_min_len=4)
        try:
            shared = list(range(2, 22))  # 20 tokens
            a = shared + [101]
            b = shared + [102]
            want = [ref.generate(p, max_tokens=4)["token_ids"]
                    for p in (a, b)]
            got = [eng.generate(p, max_tokens=4) for p in (a, b)]
            assert [r["token_ids"] for r in got] == want
            # the graft was dropped, not misplaced
            assert all(r["cached_prefix_len"] == 0 for r in got)
            assert eng._pcache.stats()["pinned"] == 0
        finally:
            ref.close()
            eng.close()


class TestProbeFailureSurfacing:
    """Consecutive stats-probe failures must SURFACE (NotReady condition +
    event + metric), not silently drop the pod out of the QPS math."""

    def _setup(self, probe):
        from kubedl_tpu.core.objects import PodPhase
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
        from kubedl_tpu.observability.metrics import ServingMetrics
        from kubedl_tpu.serving.controller import InferenceController
        from kubedl_tpu.serving.types import AutoScaleSpec, Inference, Predictor

        store = ObjectStore()
        mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED,
                          image="m:v1")
        mv.metadata.name = "m-v1"
        store.create(mv)
        metrics = ServingMetrics()
        ctrl = InferenceController(store, local_addresses=True,
                                   qps_probe=probe, metrics=metrics)
        inf = Inference()
        inf.metadata.name = "svc"
        inf.predictors.append(Predictor(
            name="main", model_version="m-v1", replicas=1,
            autoscale=AutoScaleSpec(min_replicas=1, max_replicas=2,
                                    target_qps=10.0)))
        store.create(inf)
        ctrl.reconcile("default", "svc")
        for p in store.list("Pod"):
            def mut(o):
                o.status.phase = PodPhase.RUNNING
            store.update_with_retry("Pod", p.metadata.name, "default", mut)
        return store, ctrl, metrics

    def test_consecutive_failures_flip_not_ready_and_back(self):
        state = {"fail": True}

        def probe(pod):
            if state["fail"]:
                raise TimeoutError("stats probe timeout")
            return {"qps": 1.0, "queued": 0}

        store, ctrl, metrics = self._setup(probe)
        thresh = ctrl.PROBE_NOTREADY_THRESHOLD
        for i in range(thresh - 1):
            ctrl.reconcile("default", "svc")
            inf = store.get("Inference", "svc")
            assert inf.predictor_statuses["main"].not_ready == []
        ctrl.reconcile("default", "svc")  # threshold crossing
        inf = store.get("Inference", "svc")
        st = inf.predictor_statuses["main"]
        assert st.not_ready == ["svc-main-0"]
        assert "NotReady" in st.message
        events = [e for e in store.list("Event")
                  if e.reason == "ReplicaNotReady"]
        assert len(events) == 1  # fires once at the crossing, no spam
        assert metrics.probe_failures.value(pod="svc-main-0") == float(thresh)
        assert metrics.replicas_not_ready.value(inference="svc") == 1.0
        # a later reconcile past the threshold does NOT re-fire the event
        ctrl.reconcile("default", "svc")
        events = [e for e in store.list("Event")
                  if e.reason == "ReplicaNotReady"]
        assert len(events) == 1
        # probe recovers: condition clears
        state["fail"] = False
        ctrl.reconcile("default", "svc")
        inf = store.get("Inference", "svc")
        assert inf.predictor_statuses["main"].not_ready == []
        assert metrics.replicas_not_ready.value(inference="svc") == 0.0

    def test_deleted_pod_counter_pruned(self):
        def probe(pod):
            raise TimeoutError("down")

        store, ctrl, _ = self._setup(probe)
        for _ in range(3):
            ctrl.reconcile("default", "svc")
        assert ctrl._probe_failures.get("svc-main-0", 0) >= 3
        store.try_delete("Pod", "svc-main-0", "default")
        ctrl.reconcile("default", "svc")
        assert "svc-main-0" not in ctrl._probe_failures


class TestDrainBeforeDelete:
    """Scale-down/GC with a drain window: the controller tells the replica
    to drain (hook + annotation), waits for idle stats or the grace, and
    only then deletes — in-flight decodes are never severed."""

    def _setup(self, clock, stats, drained_pods, grace=30.0):
        from kubedl_tpu.api import constants
        from kubedl_tpu.core.objects import PodPhase
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
        from kubedl_tpu.serving.controller import InferenceController
        from kubedl_tpu.serving.types import Inference, Predictor

        store = ObjectStore()
        mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED,
                          image="m:v1")
        mv.metadata.name = "m-v1"
        store.create(mv)

        def probe(pod):
            return stats[pod.metadata.name]

        def hook(pod):
            drained_pods.append(pod.metadata.name)

        ctrl = InferenceController(store, local_addresses=True,
                                   qps_probe=probe, clock=clock,
                                   drain_grace_s=grace, drain_hook=hook)
        inf = Inference()
        inf.metadata.name = "svc"
        inf.predictors.append(Predictor(name="main", model_version="m-v1",
                                        replicas=2))
        store.create(inf)
        ctrl.reconcile("default", "svc")
        for p in store.list("Pod"):
            def mut(o):
                o.status.phase = PodPhase.RUNNING
            store.update_with_retry("Pod", p.metadata.name, "default", mut)
        return store, ctrl

    def test_waits_for_idle_then_deletes(self):
        from kubedl_tpu.api import constants

        t = {"now": 100.0}
        stats = {"svc-main-0": {"active_slots": 0, "queued": 0},
                 "svc-main-1": {"active_slots": 2, "queued": 1}}
        drained = []
        store, ctrl = self._setup(lambda: t["now"], stats, drained)

        def shrink(o):
            o.predictors[0].replicas = 1
        store.update_with_retry("Inference", "svc", "default", shrink)
        # first sight: drain signal + annotation, pod NOT deleted
        requeue = ctrl.reconcile("default", "svc")
        pods = {p.metadata.name for p in store.list("Pod")}
        assert pods == {"svc-main-0", "svc-main-1"}
        assert drained == ["svc-main-1"]
        pod = store.get("Pod", "svc-main-1")
        assert constants.ANNOTATION_DRAIN_STARTED in pod.metadata.annotations
        assert any(e.reason == "Draining" for e in store.list("Event"))
        assert requeue == 1.0  # fast requeue while a drain is pending
        # still busy inside the grace: the pod survives another pass
        t["now"] += 1.0
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 2
        assert drained == ["svc-main-1"]  # hook fires once, not per pass
        # replica reports idle -> deleted before the grace expires
        stats["svc-main-1"] = {"active_slots": 0, "queued": 0}
        ctrl.reconcile("default", "svc")
        pods = {p.metadata.name for p in store.list("Pod")}
        assert pods == {"svc-main-0"}

    def test_grace_expiry_deletes_busy_pod(self):
        t = {"now": 100.0}
        stats = {"svc-main-0": {"active_slots": 0, "queued": 0},
                 "svc-main-1": {"active_slots": 2, "queued": 5}}
        store, ctrl = self._setup(lambda: t["now"], stats, [], grace=30.0)

        def shrink(o):
            o.predictors[0].replicas = 1
        store.update_with_retry("Inference", "svc", "default", shrink)
        ctrl.reconcile("default", "svc")
        assert len(store.list("Pod")) == 2
        t["now"] += 31.0  # grace expired: availability wins, delete anyway
        ctrl.reconcile("default", "svc")
        assert {p.metadata.name for p in store.list("Pod")} == {"svc-main-0"}

    def test_zero_grace_preserves_delete_on_sight(self):
        t = {"now": 0.0}
        stats = {"svc-main-0": {"active_slots": 0, "queued": 0},
                 "svc-main-1": {"active_slots": 9, "queued": 9}}
        drained = []
        store, ctrl = self._setup(lambda: t["now"], stats, drained, grace=0.0)

        def shrink(o):
            o.predictors[0].replicas = 1
        store.update_with_retry("Inference", "svc", "default", shrink)
        ctrl.reconcile("default", "svc")
        assert {p.metadata.name for p in store.list("Pod")} == {"svc-main-0"}
        assert drained == []  # no drain dance when the window is off


class TestModelLifecycle:
    """Engine-side weight hot-swap (docs/serving.md "Model lifecycle"):
    a second parameter tree rides the same jitted functions, requests
    pick their version at admission, retired trees evict only after the
    last referencing row drains, and every failure mode of the
    ``serving.weight_swap`` chaos site leaves the old version serving —
    never a torn state."""

    PROMPT = [3, 1, 4, 1, 5, 9]

    def _save_scaled(self, eng, tmp_path, tag, scale):
        """A real checkpoint whose weights provably differ from init."""
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.training.checkpoint import save_checkpoint

        params = llama.llama_init(jax.random.PRNGKey(0), eng.cfg)
        params = jax.tree_util.tree_map(lambda x: x * scale, params)
        d = str(tmp_path / tag)
        save_checkpoint(d, {"params": params}, 1)
        return d

    def test_hot_swap_serves_both_versions_bit_identically(self, tmp_path):
        from kubedl_tpu.serving.server import LlamaEngine, UnknownModelVersion

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            base_out = eng.generate(list(self.PROMPT), max_tokens=8)
            d = self._save_scaled(eng, tmp_path, "v2", 1.5)
            eng.load_version("v2", d)
            eng.load_version("v2", d)  # idempotent
            assert eng.versions()["loaded"] == ["base", "v2"]
            v2_out = eng.generate(list(self.PROMPT), max_tokens=8,
                                  model_version="v2")
            assert v2_out["model_version"] == "v2"
            assert v2_out["token_ids"] != base_out["token_ids"]
            # the default version is UNTOUCHED by co-residency
            again = eng.generate(list(self.PROMPT), max_tokens=8)
            assert again["token_ids"] == base_out["token_ids"]
            assert again["model_version"] == "base"
            with pytest.raises(UnknownModelVersion):
                eng.generate([1], max_tokens=2, model_version="nope")
        finally:
            eng.close()

    def test_concurrent_two_version_traffic_each_bit_identical(self, tmp_path):
        import threading

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=4, max_seq=64)
        try:
            d = self._save_scaled(eng, tmp_path, "v2", 2.0)
            eng.load_version("v2", d)
            ref = {
                "base": eng.generate(list(self.PROMPT), max_tokens=8),
                "v2": eng.generate(list(self.PROMPT), max_tokens=8,
                                   model_version="v2"),
            }
            results = []

            def worker(ver):
                for _ in range(3):
                    r = eng.generate(list(self.PROMPT), max_tokens=8,
                                     model_version="" if ver == "base"
                                     else ver)
                    results.append((ver, r))

            threads = [threading.Thread(target=worker, args=(v,))
                       for v in ("base", "v2", "base", "v2")]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 12
            for ver, r in results:
                # versions NEVER cross-contaminate, even interleaved in
                # the same batch window
                assert r["token_ids"] == ref[ver]["token_ids"], ver
                assert r["model_version"] == ver
        finally:
            eng.close()

    def test_retire_evicts_after_drain_default_fenced(self, tmp_path):
        from kubedl_tpu.serving.server import LlamaEngine, UnknownModelVersion

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            d = self._save_scaled(eng, tmp_path, "v2", 1.5)
            eng.load_version("v2", d)
            with pytest.raises(ValueError):
                eng.retire_version("base")  # the default cannot retire
            assert eng.retire_version("v2") is True
            assert eng.retire_version("ghost") is False
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if eng.versions()["loaded"] == ["base"]:
                    break
                eng.generate([2], max_tokens=1)  # admission pass evicts
            assert eng.versions()["loaded"] == ["base"]
            assert eng.versions()["retiring"] == []
            # a retired version is gone for NEW requests
            with pytest.raises(UnknownModelVersion):
                eng.generate([1], max_tokens=2, model_version="v2")
        finally:
            eng.close()

    def test_failed_load_leaves_old_version_serving(self, tmp_path):
        """The weight_swap contract: corrupt artifact, truncated step, or
        an injected mid-swap crash — the load FAILS, the serving tree is
        untouched, outputs stay bit-identical."""
        import json as _json

        from kubedl_tpu.chaos import FaultInjected, FaultPlan, FaultSpec
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            before = eng.generate(list(self.PROMPT), max_tokens=8)
            # missing artifact: no checkpoint at all under the dir
            with pytest.raises(ValueError):
                eng.load_version("v2", str(tmp_path / "empty"))
            # truncated artifact: manifest present, shard file missing
            torn = tmp_path / "torn" / "step-00000001"
            torn.mkdir(parents=True)
            (torn / "meta.json").write_text(_json.dumps(
                {"step": 1, "nprocs": 1, "leaves": {}}))
            (tmp_path / "torn" / "latest").write_text("step-00000001")
            with pytest.raises(ValueError):
                eng.load_version("v2", str(tmp_path / "torn"))
            # mid-swap crash: the chaos site fires inside the build
            good = self._save_scaled(eng, tmp_path, "good", 1.5)
            with FaultPlan(7, sites={
                "serving.weight_swap": [FaultSpec.nth(1)],
            }) as plan:
                with pytest.raises(FaultInjected):
                    eng.load_version("v2", good)
            assert plan.faults("serving.weight_swap") == 1
            assert eng.versions()["loaded"] == ["base"]  # no torn state
            after = eng.generate(list(self.PROMPT), max_tokens=8)
            assert after["token_ids"] == before["token_ids"]
            # and the SAME dir loads fine once the fault clears
            eng.load_version("v2", good)
            assert "v2" in eng.versions()["loaded"]
        finally:
            eng.close()

    def test_corrupt_restore_at_engine_start(self, tmp_path):
        """Engine START under weight_swap chaos / torn checkpoints: an
        injected fault fails the constructor cleanly (supervisor
        restarts, old pod keeps serving); a torn latest step falls back
        to the previous good one instead of serving random weights."""
        import jax

        from kubedl_tpu.chaos import FaultInjected, FaultPlan, FaultSpec
        from kubedl_tpu.models import llama
        from kubedl_tpu.serving.server import LlamaEngine
        from kubedl_tpu.training.checkpoint import save_checkpoint

        with FaultPlan(11, sites={
            "serving.weight_swap": [FaultSpec.nth(1)],
        }):
            with pytest.raises(FaultInjected):
                LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        # torn newest step: restore falls back to the good step 1
        eng0 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        try:
            params = llama.llama_init(jax.random.PRNGKey(0), eng0.cfg)
            params = jax.tree_util.tree_map(lambda x: x * 3.0, params)
            d = str(tmp_path / "ck")
            save_checkpoint(d, {"params": params}, 1)
            want = None
            eng1 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                               ckpt_dir=d)
            try:
                want = eng1.generate(list(self.PROMPT), max_tokens=8)
            finally:
                eng1.close()
            import json as _json
            import pathlib

            torn = pathlib.Path(d) / "step-00000002"
            torn.mkdir()
            (torn / "meta.json").write_text(_json.dumps(
                {"step": 2, "nprocs": 1, "leaves": {}}))
            (pathlib.Path(d) / "latest").write_text("step-00000002")
            eng2 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                               ckpt_dir=d)
            try:
                got = eng2.generate(list(self.PROMPT), max_tokens=8)
                assert got["token_ids"] == want["token_ids"]
            finally:
                eng2.close()
        finally:
            eng0.close()

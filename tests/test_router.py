"""Serving-router tests (docs/serving.md "Router"): policy state machines
with fake clocks, the routed request path against stub HTTP replicas, and
the tier-1 bit-identity contract — greedy outputs through the router match
direct engine calls exactly (routing/hedging must not change results)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubedl_tpu.serving import router_policy as policy
from kubedl_tpu.serving.router import ServingRouter, router_kwargs


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# policy layer (no sockets)
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_ejects_after_consecutive_failures_only(self):
        clk = FakeClock()
        br = policy.CircuitBreaker(fail_threshold=3, cooldown_s=2.0, clock=clk)
        br.record_failure()
        br.record_failure()
        br.record_success()  # streak broken: consecutive, not windowed
        br.record_failure()
        br.record_failure()
        assert br.state == policy.CLOSED
        br.record_failure()
        assert br.state == policy.OPEN
        assert br.ejections == 1
        assert not br.allow()  # cooling down: no traffic

    def test_half_open_admits_one_trial_then_readmits(self):
        clk = FakeClock()
        br = policy.CircuitBreaker(fail_threshold=1, cooldown_s=2.0, clock=clk)
        br.record_failure()
        assert br.state == policy.OPEN
        clk.t += 2.0
        assert br.allow()       # the single half-open trial
        assert not br.allow()   # second caller must wait for the verdict
        br.record_success()
        assert br.state == policy.CLOSED
        assert br.readmissions == 1
        assert br.allow()

    def test_failed_trial_reopens_with_fresh_cooldown(self):
        clk = FakeClock()
        br = policy.CircuitBreaker(fail_threshold=1, cooldown_s=2.0, clock=clk)
        br.record_failure()
        clk.t += 2.0
        assert br.allow()
        br.record_failure()  # trial failed
        assert br.state == policy.OPEN
        assert not br.allow()  # cooldown restarted, not inherited
        clk.t += 2.0
        assert br.allow()


class TestRetryBudget:
    def test_retries_are_a_fraction_of_traffic(self):
        b = policy.RetryBudget(ratio=0.1, min_tokens=0.0)
        assert not b.try_spend()  # empty bucket: no retry
        for _ in range(10):
            b.on_request()
        assert b.try_spend()      # 10 requests x 0.1 = 1 retry earned
        assert not b.try_spend()
        assert b.spent == 1 and b.denied == 2

    def test_min_tokens_lets_a_cold_router_fail_over(self):
        b = policy.RetryBudget(ratio=0.2, min_tokens=2.0)
        assert b.try_spend() and b.try_spend()
        assert not b.try_spend()


class TestLatencyTracker:
    def test_conservative_default_until_samples(self):
        lt = policy.LatencyTracker(min_samples=5, default_ms=1000.0)
        lt.record(10.0)
        assert lt.quantile(0.95) is None
        assert lt.hedge_delay_ms(floor_ms=50.0) == 1000.0

    def test_p95_with_floor(self):
        lt = policy.LatencyTracker(min_samples=5, default_ms=1000.0)
        for ms in range(1, 101):
            lt.record(float(ms))
        assert lt.quantile(0.95) >= 95.0
        assert lt.hedge_delay_ms(floor_ms=200.0) == 200.0


class TestConsistentHashRing:
    def test_deterministic_across_processes_and_rebuilds(self):
        # sha1-based, NOT hash(): PYTHONHASHSEED must not move the ring
        r1, r2 = policy.ConsistentHashRing(), policy.ConsistentHashRing()
        r1.rebuild(["a", "b", "c"])
        r2.rebuild(["a", "b", "c"])
        for seed in range(20):
            p = r1.key_for_prefix([seed] * 8, 8)
            assert r1.preference(p) == r2.preference(p)

    def test_removing_one_replica_remaps_minority(self):
        big, small = policy.ConsistentHashRing(), policy.ConsistentHashRing()
        big.rebuild(["a", "b", "c", "d"])
        small.rebuild(["a", "b", "c"])
        moved = 0
        for seed in range(200):
            p = big.key_for_prefix([seed] * 8, 8)
            was = big.preference(p)[0]
            if was != "d" and small.preference(p)[0] != was:
                moved += 1
        assert moved == 0  # keys not owned by the removed replica stay put

    def test_short_prompt_has_no_affinity(self):
        ring = policy.ConsistentHashRing()
        ring.rebuild(["a", "b"])
        assert ring.key_for_prefix([1, 2, 3], 8) is None

    def test_pick_replicas_owner_first_then_least_loaded(self):
        ring = policy.ConsistentHashRing()
        ring.rebuild(["a", "b", "c"])
        prompt = [7] * 8
        owner = ring.preference(ring.key_for_prefix(prompt, 8))[0]
        cands = {"a": 5, "b": 5, "c": 5}
        order = policy.pick_replicas(cands, prompt, ring, 8)
        assert order[0] == owner
        # hedge/failover target is the least-loaded NON-owner
        others = [n for n in cands if n != owner]
        cands2 = dict(cands)
        cands2[others[0]] = 0
        assert policy.pick_replicas(cands2, prompt, ring, 8)[1] == others[0]
        # no affinity (short prompt): pure least-loaded, name tie-break
        assert policy.pick_replicas({"a": 2, "b": 1}, [1], ring, 8) == ["b", "a"]

    def test_ejected_owner_falls_to_remaining(self):
        ring = policy.ConsistentHashRing()
        ring.rebuild(["a", "b"])
        prompt = [3] * 8
        owner = ring.preference(ring.key_for_prefix(prompt, 8))[0]
        other = "b" if owner == "a" else "a"
        assert policy.pick_replicas({other: 0}, prompt, ring, 8) == [other]


# ---------------------------------------------------------------------------
# routed request path against stub replicas
# ---------------------------------------------------------------------------

class _StubHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _json(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        st = {"queued": 0, "shed_recent": 0,
              "draining": self.server.behavior.get("stats_draining", False)}
        self._json(200, st)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        req = json.loads(self.rfile.read(n) or b"{}")
        beh = self.server.behavior
        if self.path == "/v1/cancel":
            self.server.cancels.append(req.get("request_id"))
            self._json(200, {"cancelled": True})
            return
        self.server.calls.append(
            {"req": req, "deadline_ms": self.headers.get("X-Deadline-Ms")}
        )
        if beh.get("delay"):
            time.sleep(beh["delay"])
        if beh.get("shed"):
            self._json(503, {"error": "busy", "shed": True,
                             "reason": beh.get("reason", "overloaded")},
                       {"Retry-After": str(beh.get("retry_after", 1))})
            return
        if beh.get("deadline_504"):
            self._json(504, {"error": "timed out", "timed_out": True})
            return
        self._json(200, {"token_ids": [1, 2, 3], "served_by": self.server.name})


def _stub(name, **behavior):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
    srv.name = name
    srv.behavior = behavior
    srv.calls = []
    srv.cancels = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    srv._thread = t
    return srv


def _owner_of(names, prefix_len=8):
    """Which replica the affinity ring makes primary for [7]*prefix_len."""
    ring = policy.ConsistentHashRing()
    ring.rebuild(sorted(names))
    return ring.preference(ring.key_for_prefix([7] * prefix_len, prefix_len))[0]


@pytest.fixture
def fleet():
    servers = {}

    def make(name, **behavior):
        servers[name] = _stub(name, **behavior)
        return servers[name]

    yield make, servers
    for s in servers.values():
        s.shutdown()
        s.server_close()


class TestRouterPath:
    def test_routes_and_propagates_deadline(self, fleet):
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)],
                          hedge_enabled=False)
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [1, 2], "max_tokens": 4}, deadline_ms=5000)
        assert code == 200 and payload["served_by"] == "a"
        # the REMAINING budget rode X-Deadline-Ms to the engine
        sent = float(a.calls[0]["deadline_ms"])
        assert 0 < sent <= 5000

    def test_transport_failure_fails_over_once(self, fleet):
        make, servers = fleet
        b = make("b")
        dead = _stub("a")
        port = dead.server_port
        dead.shutdown()
        dead.server_close()  # connection refused
        r = ServingRouter([("a", "127.0.0.1", port),
                           ("b", "127.0.0.1", b.server_port)],
                          hedge_enabled=False, affinity_prefix_len=0)
        # force the dead replica primary: give b artificial load via stats
        with r._lock:
            r._replicas["b"].stats = {"queued": 50}
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 200 and payload["served_by"] == "b"
        assert r.metrics.retries.value() == 1.0
        assert r.retry_budget.spent == 1

    def test_eject_then_readmit_via_half_open_probe(self, fleet):
        make, servers = fleet
        a = make("a")
        port = a.server_port
        r = ServingRouter([("a", "127.0.0.1", port)],
                          eject_threshold=3, readmit_cooldown_s=0.05,
                          probe_timeout_s=0.3, hedge_enabled=False)
        a.shutdown()
        a.server_close()
        for _ in range(3):
            r.probe_once()
        rep = r._replicas["a"]
        assert rep.breaker.state == policy.OPEN
        assert r.metrics.ejections.value(replica="a") == 1.0
        assert r.handle_generate({"prompt_ids": [1]}, 1000)[0] == 503
        # replica restarts on the same port; past the cooldown the probe's
        # half-open trial readmits it — requests never do
        servers["a"] = _stub("a")
        servers["a"].server_port_override = None
        restarted = ThreadingHTTPServer(("127.0.0.1", port), _StubHandler)
        restarted.name, restarted.behavior = "a", {}
        restarted.calls, restarted.cancels = [], []
        threading.Thread(target=restarted.serve_forever, daemon=True).start()
        try:
            time.sleep(0.06)
            r.probe_once()
            assert rep.breaker.state == policy.CLOSED
            assert r.metrics.readmissions.value(replica="a") == 1.0
            assert r.handle_generate({"prompt_ids": [1]}, 1000)[0] == 200
        finally:
            restarted.shutdown()
            restarted.server_close()

    def test_retry_after_honored_no_retry_storm(self, fleet):
        make, servers = fleet
        a = make("a", shed=True, retry_after=5)
        b = make("b", shed=True, retry_after=5)
        r = ServingRouter([("a", "127.0.0.1", a.server_port),
                           ("b", "127.0.0.1", b.server_port)],
                          hedge_enabled=False)
        code, payload, headers = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 503 and payload["reason"] == "overloaded"
        assert headers["Retry-After"] == "5"
        # one primary + at most max_retries dispatches, never a storm
        assert len(a.calls) + len(b.calls) == 2
        # both replicas are inside their Retry-After window now: further
        # requests are refused at the router without touching the engines
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 503 and payload["reason"] == "no_replica"
        assert len(a.calls) + len(b.calls) == 2

    def test_exhausted_budget_stops_retries(self, fleet):
        make, servers = fleet
        a = make("a", shed=True)
        b = make("b")
        r = ServingRouter([("a", "127.0.0.1", a.server_port),
                           ("b", "127.0.0.1", b.server_port)],
                          hedge_enabled=False, retry_budget_ratio=0.0,
                          affinity_prefix_len=0)
        while r.retry_budget.try_spend():
            pass  # drain the min-token trickle
        with r._lock:
            r._replicas["b"].stats = {"queued": 50}  # a goes primary
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 503
        assert len(b.calls) == 0  # no budget -> no failover dispatch
        assert r.retry_budget.denied > 0

    def test_expired_deadline_never_dispatches(self, fleet):
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)],
                          hedge_enabled=False)
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 0)
        assert code == 504
        assert a.calls == []
        assert r.metrics.deadline_exceeded.value() == 1.0

    def test_engine_deadline_504_is_never_retried_elsewhere(self, fleet):
        # a request that ran out of budget ON a replica must not be handed
        # to a second replica — its deadline is just as expired there
        make, servers = fleet
        a = make("a", deadline_504=True)
        b = make("b")
        r = ServingRouter([("a", "127.0.0.1", a.server_port),
                           ("b", "127.0.0.1", b.server_port)],
                          hedge_enabled=False, affinity_prefix_len=0)
        with r._lock:
            r._replicas["b"].stats = {"queued": 50}  # a goes primary
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 504
        assert len(a.calls) == 1 and len(b.calls) == 0

    def test_hedge_first_answer_wins_loser_cancelled(self, fleet):
        make, servers = fleet
        slow = _owner_of(["a", "b"])
        fast = "b" if slow == "a" else "a"
        s = make(slow, delay=0.8)
        f = make(fast)
        r = ServingRouter([(slow, "127.0.0.1", s.server_port),
                           (fast, "127.0.0.1", f.server_port)],
                          hedge_enabled=True, hedge_floor_ms=50.0,
                          hedge_default_ms=80.0)
        t0 = time.monotonic()
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [7] * 8, "max_tokens": 4}, 8000)
        elapsed = time.monotonic() - t0
        assert code == 200 and payload["served_by"] == fast
        assert elapsed < 0.7  # won by the hedge, not the slow primary
        assert r.metrics.hedges.value() == 1.0
        assert r.metrics.hedge_wins.value() == 1.0
        # loser cancellation is async best-effort: give it a beat
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not s.cancels:
            time.sleep(0.02)
        assert len(s.cancels) == 1  # the primary's request_id was cancelled
        assert r.metrics.cancellations.value() == 1.0

    def test_draining_replica_fails_over_free(self, fleet):
        # drain 503s are deterministic "go elsewhere" signals, not failures:
        # no retry-budget spend, no breaker penalty, replica marked draining
        make, servers = fleet
        draining = _owner_of(["a", "b"])
        other = "b" if draining == "a" else "a"
        d = make(draining, shed=True, reason="draining")
        o = make(other)
        r = ServingRouter([(draining, "127.0.0.1", d.server_port),
                           (other, "127.0.0.1", o.server_port)],
                          hedge_enabled=False)
        code, payload, _ = r.handle_generate({"prompt_ids": [7] * 8}, 5000)
        assert code == 200 and payload["served_by"] == other
        assert r.retry_budget.spent == 0
        rep = r._replicas[draining]
        assert rep.draining and rep.breaker.state == policy.CLOSED
        # next request skips the draining replica outright
        r.handle_generate({"prompt_ids": [7] * 8}, 5000)
        assert len(d.calls) == 1

    def test_router_drain_rejects_with_reason(self, fleet):
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)])
        assert r.drain(wait=True, timeout_s=1.0)
        code, payload, headers = r.handle_generate({"prompt_ids": [1]}, 1000)
        assert code == 503 and payload["reason"] == "draining"
        assert "Retry-After" in headers
        assert a.calls == []

    def test_set_replicas_preserves_breaker_state(self, fleet):
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)],
                          eject_threshold=1, readmit_cooldown_s=60.0)
        r._record_failure(r._replicas["a"])
        assert r._replicas["a"].breaker.state == policy.OPEN
        # a fleet resync must not mass-readmit ejected replicas
        r.set_replicas([("a", "127.0.0.1", a.server_port),
                        ("b", "127.0.0.1", a.server_port)])
        assert r._replicas["a"].breaker.state == policy.OPEN
        assert r._replicas["b"].breaker.state == policy.CLOSED

    def test_router_kwargs_parses_config(self):
        kw = router_kwargs({
            "eject_threshold": "4", "hedge_floor_ms": "25",
            "version_weights": {"v1": "90", "v2": 10},
            "replicas": [{"name": "r0", "port": 9000, "weight": 50}],
        })
        assert kw["eject_threshold"] == 4
        assert kw["hedge_floor_ms"] == 25.0
        assert kw["version_weights"] == {"v1": 90, "v2": 10}
        assert kw["replicas"] == [
            {"name": "r0", "host": "127.0.0.1", "port": 9000,
             "weight": 50, "role": "", "model": ""}
        ]

    def test_version_weights_tag_and_split_deterministically(self, fleet):
        """The canary split: untagged requests get a model_version from
        the smooth-WRR over the configured weights (deterministic — same
        interleave every run), client-pinned versions pass through, and
        every version feeds its own SLO partition."""
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)],
                          hedge_enabled=False,
                          version_weights={"v1": 75, "v2": 25})
        for _ in range(8):
            code, _, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
            assert code == 200
        tags = [c["req"]["model_version"] for c in a.calls]
        assert tags.count("v1") == 6 and tags.count("v2") == 2
        # a client-pinned version is never rewritten by the split
        r.handle_generate(
            {"prompt_ids": [1], "model_version": "v9"}, 5000)
        assert a.calls[-1]["req"]["model_version"] == "v9"
        st = r.stats()["versions"]
        assert st["weights"] == {"v1": 75, "v2": 25}
        assert st["slo"]["v1"]["requests"] == 6
        assert st["slo"]["v2"]["requests"] == 2
        assert r.metrics.version_requests.value(
            version="v1", result="ok") == 6.0
        assert r.metrics.rollout_weight.value(version="v2") == 25.0

    def test_version_sticky_across_failover(self, fleet):
        """A request keeps its model_version across retry legs: a hedge
        or failover answering with a different version would be a silent
        model swap."""
        make, servers = fleet
        a = make("a", shed=True)
        b = make("b")
        r = ServingRouter([("a", "127.0.0.1", a.server_port),
                           ("b", "127.0.0.1", b.server_port)],
                          hedge_enabled=False, affinity_prefix_len=0,
                          version_weights={"v2": 100})
        with r._lock:
            r._replicas["b"].stats = {"queued": 50}  # a goes primary
        code, payload, _ = r.handle_generate({"prompt_ids": [1]}, 5000)
        assert code == 200 and payload["served_by"] == "b"
        assert a.calls[0]["req"]["model_version"] == "v2"
        assert b.calls[0]["req"]["model_version"] == "v2"

    def test_version_slo_partition_isolates_failures(self, fleet):
        """A failing version burns ITS tracker, not the other's — the
        partition the rollout controller gates on."""
        make, servers = fleet
        a = make("a")
        r = ServingRouter([("a", "127.0.0.1", a.server_port)],
                          hedge_enabled=False,
                          slo={"objective": 0.5},
                          version_weights={"v1": 100})
        r.handle_generate({"prompt_ids": [1]}, 5000)
        # v2 requests fail (client-pinned, upstream 404s them here via
        # a dead port after we kill the replica)
        r.set_version_weights({"v1": 50, "v2": 50})
        a.behavior["shed"] = True
        code, _, _ = r.handle_generate(
            {"prompt_ids": [1], "model_version": "v2"}, 5000)
        assert code == 503
        v1 = r.version_tracker("v1").snapshot()
        v2 = r.version_tracker("v2").snapshot()
        assert v1["requests"] == 1 and v1["bad"] == 0
        assert v2["requests"] == 1 and v2["bad"] == 1
        assert r.metrics.version_requests.value(
            version="v2", result="error") == 1.0


def test_sync_from_store_builds_fleet_from_control_plane():
    """The router's replica set comes from the same store the controller
    programs: RUNNING predictor pods, engine port from the pod's serve
    config, canary weight from the TrafficPolicy."""
    from kubedl_tpu.core.objects import PodPhase
    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
    from kubedl_tpu.serving.controller import HTTP_PORT, InferenceController
    from kubedl_tpu.serving.types import Inference, Predictor

    store = ObjectStore()
    mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED,
                      image="m:v1", storage_root="/tmp/x")
    mv.metadata.name = "m-v1"
    store.create(mv)
    inf = Inference(predictors=[
        Predictor(name="main", model_version="m-v1", replicas=2),
    ])
    inf.metadata.name = "svc"
    store.create(inf)
    ctrl = InferenceController(store, local_addresses=True)
    ctrl.reconcile("default", "svc")
    for p in store.list("Pod"):
        def mut(o):
            o.status.phase = PodPhase.RUNNING
        store.update_with_retry("Pod", p.metadata.name, "default", mut)
    ctrl.reconcile("default", "svc")  # TrafficPolicy over ready predictors

    r = ServingRouter(hedge_enabled=False)
    n = r.sync_from_store(store, "svc")
    assert n == 2
    st = r.stats()["replicas"]
    assert sorted(st) == ["svc-main-0", "svc-main-1"]
    for rep in st.values():
        assert rep["url"].endswith(f":{HTTP_PORT}")
        assert rep["weight"] == 100


def test_sync_from_store_weight_zero_stays_unroutable():
    """Regression: a predictor ABSENT from an armed TrafficPolicy's
    routes (weight 0 — the controller pulled it from rotation) must stay
    registered-but-unroutable. The old default resurrected it at weight
    100 on every router restart and breaker half-open readmission."""
    from kubedl_tpu.core.objects import PodPhase
    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
    from kubedl_tpu.serving.controller import InferenceController
    from kubedl_tpu.serving.types import Inference, Predictor, TrafficRoute

    store = ObjectStore()
    mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED,
                      image="m:v1", storage_root="/tmp/x")
    mv.metadata.name = "m-v1"
    store.create(mv)
    inf = Inference(predictors=[
        Predictor(name="main", model_version="m-v1", replicas=1),
        Predictor(name="canary", model_version="m-v1", replicas=1),
    ])
    inf.metadata.name = "svc"
    store.create(inf)
    ctrl = InferenceController(store, local_addresses=True)
    ctrl.reconcile("default", "svc")
    for p in store.list("Pod"):
        def mut(o):
            o.status.phase = PodPhase.RUNNING
        store.update_with_retry("Pod", p.metadata.name, "default", mut)
    ctrl.reconcile("default", "svc")

    # the operator takes the canary out of rotation: its route vanishes
    def drop_canary(tp):
        tp.routes = [TrafficRoute(predictor="main", weight=100,
                                  service="svc-main")]
    store.update_with_retry("TrafficPolicy", "svc", "default", drop_canary)

    r = ServingRouter(hedge_enabled=False)
    r.sync_from_store(store, "svc")
    st = r.stats()["replicas"]
    assert st["svc-main-0"]["weight"] == 100
    assert st["svc-canary-0"]["weight"] == 0
    # unroutable means unroutable: never selected for dispatch
    sel = r._select({"prompt_ids": [1]}, set())
    assert sel is not None and sel.name == "svc-main-0"
    # a breaker half-open readmission touches health, never weight
    rep = r._replicas["svc-canary-0"]
    for _ in range(3):
        rep.breaker.record_failure()
    rep.breaker.record_success()
    assert rep.weight == 0
    assert r._select({"prompt_ids": [1]}, {"svc-main-0"}) is None
    # a router restart re-syncs from the store: still weight 0
    r2 = ServingRouter(hedge_enabled=False)
    r2.sync_from_store(store, "svc")
    assert r2.stats()["replicas"]["svc-canary-0"]["weight"] == 0
    # routes=[] (nothing ready per the controller): EVERY pod unroutable
    def clear_routes(tp):
        tp.routes = []
    store.update_with_retry("TrafficPolicy", "svc", "default", clear_routes)
    r2.sync_from_store(store, "svc")
    assert all(v["weight"] == 0
               for v in r2.stats()["replicas"].values())
    code, payload, _ = r2.handle_generate({"prompt_ids": [1]}, 1000)
    assert code == 503 and payload["reason"] == "no_replica"


# ---------------------------------------------------------------------------
# real engines behind the router
# ---------------------------------------------------------------------------

class TestRouterEngineIntegration:
    def _serve(self, engine, name="tiny"):
        from kubedl_tpu.serving.server import make_handler

        srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(engine, name))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_greedy_bit_identical_through_router(self):
        """Tier-1 acceptance: routing/hedging must not change RESULTS —
        greedy outputs through the router are bit-identical to a direct
        engine call, whichever replica serves them."""
        from kubedl_tpu.serving.server import LlamaEngine

        e1 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        e2 = LlamaEngine(preset="tiny", max_batch=2, max_seq=64)
        s1 = s2 = None
        try:
            s1, s2 = self._serve(e1), self._serve(e2)
            r = ServingRouter([("r0", "127.0.0.1", s1.server_port),
                               ("r1", "127.0.0.1", s2.server_port)],
                              hedge_enabled=True, hedge_default_ms=5000.0)
            prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8], [11] * 12]
            for prompt in prompts:
                direct = e1.generate(list(prompt), max_tokens=8,
                                     temperature=0.0)
                code, payload, _ = r.handle_generate(
                    {"prompt_ids": list(prompt), "max_tokens": 8,
                     "temperature": 0.0}, 30_000)
                assert code == 200
                assert payload["token_ids"] == direct["token_ids"]
        finally:
            for s in (s1, s2):
                if s is not None:
                    s.shutdown()
                    s.server_close()
            e1.close()
            e2.close()

    def test_cancel_releases_queue_slot(self):
        """Hedge-loser cancellation frees the loser's engine queue slot:
        a cancelled queued request leaves _waiting immediately instead of
        occupying a batch slot when one frees up."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=128)
        try:
            done_a, res_b = threading.Event(), {}

            def run_a():
                eng.generate([1, 2, 3], max_tokens=100)
                done_a.set()

            ta = threading.Thread(target=run_a, daemon=True)
            ta.start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if eng.stats()["active_slots"] == 1:
                    break
                time.sleep(0.005)
            assert eng.stats()["active_slots"] == 1

            def run_b():
                res_b["r"] = eng.generate([5, 6], max_tokens=100,
                                          request_id="loser")

            tb = threading.Thread(target=run_b, daemon=True)
            tb.start()
            while time.monotonic() < deadline:
                if eng.stats()["queued"] == 1:
                    break
                time.sleep(0.005)
            assert eng.stats()["queued"] == 1
            assert eng.cancel("loser") is True
            tb.join(timeout=5)
            assert res_b["r"].get("cancelled") is True
            assert eng.stats()["queued"] == 0  # slot released NOW
            assert eng.cancel("loser") is False  # idempotent
            ta.join(timeout=30)
            assert done_a.is_set()  # the running request was untouched
        finally:
            eng.close()

    def test_engine_drain_rejects_new_finishes_inflight(self):
        from kubedl_tpu.serving.server import EngineOverloaded, LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=128)
        try:
            res = {}

            def run():
                res["r"] = eng.generate([1, 2], max_tokens=60)

            t = threading.Thread(target=run, daemon=True)
            t.start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if eng.stats()["active_slots"] == 1:
                    break
                time.sleep(0.005)
            eng.drain()
            with pytest.raises(EngineOverloaded) as ei:
                eng.generate([9], max_tokens=4)
            assert ei.value.reason == "draining"
            st = eng.stats()
            assert st["draining"] is True and st["drain_rejects"] == 1
            # in-flight work runs to completion despite the drain
            assert eng.wait_drained(timeout_s=30.0)
            t.join(timeout=5)
            assert len(res["r"]["token_ids"]) == 60
        finally:
            eng.close()

    def test_http_drain_and_deadline_endpoints(self):
        import urllib.error
        import urllib.request

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64)
        srv = None
        try:
            srv = self._serve(eng)
            base = f"http://127.0.0.1:{srv.server_port}"
            # an already-expired X-Deadline-Ms is a 504 before any decode
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({"prompt_ids": [1]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Deadline-Ms": "0"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 504
            # POST /admin/drain flips admission off with the drain reason
            req = urllib.request.Request(f"{base}/admin/drain", data=b"{}")
            assert json.loads(
                urllib.request.urlopen(req, timeout=5).read()
            )["draining"] is True
            req = urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({"prompt_ids": [1]}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["reason"] == "draining"
        finally:
            if srv is not None:
                srv.shutdown()
                srv.server_close()
            eng.close()
